"""Parameter-server fault-tolerance suite (native.pserver +
parallel.pserver_client + the pserver faults in testing.faults).

Every test proves a recovery path end-to-end against a deterministic
injected fault, in-process on localhost — the reference proved its Go
pserver the same way (reference: go/pserver/client/client_test.go runs
real pservers on localhost; trainer/tests kill them mid-run). The
acceptance chaos scenario: kill the primary of one shard MID-PASS,
fail over to its chain replica, finish the pass, and the final table
is bit-identical to an unfaulted run — with a lost-ACK retried push
applied exactly once, asserted by row values, not counters.
"""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.native.pserver import (
    PServerGroup,
    PServerShard,
    ShardState,
    start_shard_pair,
)
from paddle_tpu.parallel.pserver_client import (
    PServerClient,
    PServerEmbedding,
)
from paddle_tpu.testing import FaultPlan
from paddle_tpu.testing.faults import ManualClock

pytestmark = [pytest.mark.faults, pytest.mark.pserver]

DIM = 4


def _client(specs, trainer_id=0, **kw):
    kw.setdefault("backoff_base", 0.005)
    kw.setdefault("backoff_max", 0.1)
    kw.setdefault("timeout", 5.0)
    return PServerClient(specs, DIM, trainer_id=trainer_id, **kw)


def _table(vocab, seed=0):
    return (np.random.RandomState(seed)
            .rand(vocab, DIM).astype(np.float32))


def _restart_shard_on(port, vocab, **kw):
    """Bring a shard back on a just-killed shard's port: the dead
    listener's fd release can lag its kill() by a scheduler tick, so
    retry the bind briefly."""
    deadline = time.monotonic() + 5.0
    while True:
        try:
            return PServerShard(0, 0, vocab, DIM, port=port, **kw)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.02)


def _push_schedule(vocab, steps, seed=3):
    """Deterministic (ids, grads) per step — the mini training pass the
    chaos runs replay identically with and without faults."""
    r = np.random.RandomState(seed)
    return [(r.randint(0, vocab, 5).astype(np.int64),
             r.rand(5, DIM).astype(np.float32))
            for _ in range(steps)]


def _run_pass(client, table, schedule, lr=0.1):
    client.register()
    client.load_table(table)
    for ids, grads in schedule:
        client.push_row_grads(ids, grads, lr)
    client.finish_pass(timeout_s=10.0)
    return client.fetch_table()


def _reference_apply(table, schedule, lr=0.1):
    out = table.astype(np.float32).copy()
    for ids, grads in schedule:
        np.add.at(out, ids, (-lr * grads).astype(np.float32))
    return out


# ---- basics ------------------------------------------------------------

def test_roundtrip_push_and_padding_contract():
    """get_rows/push_row_grads against a 2-shard group: reads assemble
    across shards, out-of-range ids give ZERO rows (the sharded_lookup
    contract), pushes land only on owned rows."""
    vocab = 16
    with PServerGroup(vocab, DIM, n_shards=2, replicated=False) as g:
        with _client(g.specs) as c:
            c.register()
            table = _table(vocab)
            c.load_table(table)
            ids = np.asarray([0, 7, 8, 15, -1, vocab + 3], np.int64)
            rows = c.get_rows(ids)
            expect = np.zeros((6, DIM), np.float32)
            expect[:4] = table[[0, 7, 8, 15]]
            assert np.array_equal(rows, expect)

            sched = _push_schedule(vocab, 3)
            for ids, grads in sched:
                c.push_row_grads(ids, grads, 0.1)
            assert np.array_equal(c.fetch_table(),
                                  _reference_apply(table, sched))


def test_duplicate_epoch_not_reapplied():
    """The exactly-once primitive in isolation: replaying an epoch the
    shard has applied is a DUP-ACK no-op, by row values."""
    st = ShardState(0, 8, DIM)
    ids = np.asarray([1, 1, 5], np.int64)
    grads = np.ones((3, DIM), np.float32)
    assert st.apply_push(7, 1, ids, grads, lr=1.0)
    once = st.rows.copy()
    assert once[1, 0] == -2.0       # in-push duplicates accumulate
    assert not st.apply_push(7, 1, ids, grads, lr=1.0)   # replay
    assert np.array_equal(st.rows, once)
    assert st.apply_push(7, 2, ids, grads, lr=1.0)       # next epoch


def test_chain_replication_keeps_backup_identical():
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM)
    try:
        with _client([spec]) as c:
            c.register()
            c.load_table(_table(vocab))
            for ids, grads in _push_schedule(vocab, 4):
                c.push_row_grads(ids, grads, 0.2)
        assert backup.state.version == primary.state.version
        assert np.array_equal(backup.state.rows, primary.state.rows)
        assert backup.state.epochs == primary.state.epochs
    finally:
        primary.stop()
        backup.stop()


# ---- lost ACK: exactly-once by row values ------------------------------

def test_lost_ack_retried_push_applied_exactly_once():
    """The nth push is applied AND replicated, then the connection dies
    before the ACK. The client retries the SAME epoch on the same
    endpoint; the shard answers DUP. Exactly-once is asserted by final
    row equality with a single application — not by counters."""
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM)
    plan = FaultPlan(pserver_lost_ack_at=1)
    plan.wrap_pserver_shard(primary)
    try:
        with _client([spec]) as c:
            table = _table(vocab)
            sched = _push_schedule(vocab, 3)
            got = _run_pass(c, table, sched)
            assert plan.count("pslostack") == 1
            assert c.stats["duplicate_acks"] == 1
            assert np.array_equal(got, _reference_apply(table, sched))
        # the replica saw each update exactly once too
        assert np.array_equal(backup.state.rows, primary.state.rows)
    finally:
        primary.stop()
        backup.stop()


def test_restarted_trainer_resumes_epoch_sequence():
    """A trainer that crashes and comes back (fresh client, epochs at
    0) must have its NEW pushes applied: register() hands back the
    shard's applied-epoch watermark and the client numbers past it —
    without that, the first N pushes would be DUP-discarded against
    the dead incarnation's watermark."""
    vocab = 8
    with PServerGroup(vocab, DIM, n_shards=1, replicated=False) as g:
        table = _table(vocab)
        sched = _push_schedule(vocab, 3)
        with _client(g.specs, trainer_id=7) as c1:
            c1.register()
            c1.load_table(table)
            for ids, grads in sched:
                c1.push_row_grads(ids, grads, 0.1)
        after_first = _reference_apply(table, sched)

        with _client(g.specs, trainer_id=7) as c2:   # the restart
            c2.register()
            extra = _push_schedule(vocab, 2, seed=9)
            for ids, grads in extra:
                c2.push_row_grads(ids, grads, 0.1)
            assert c2.stats["duplicate_acks"] == 0   # nothing discarded
            assert np.array_equal(c2.fetch_table(),
                                  _reference_apply(after_first, extra))


def test_replica_outage_triggers_full_resync():
    """A backup that missed records while unreachable must NOT be
    trusted with later increments (it would apply over the gap and
    silently diverge): the first replication after the link degrades
    ships the FULL state, so a restarted backup is exact again."""
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM)
    try:
        with _client([spec]) as c:
            table = _table(vocab)
            sched = _push_schedule(vocab, 4)
            c.register()
            c.load_table(table)
            c.push_row_grads(*sched[0], 0.1)
            backup.kill()
            # applied + ACKed while unreplicated (degrade, not block)
            c.push_row_grads(*sched[1], 0.1)
            assert primary.stats()["replica_lost"]
            # backup returns on the SAME address
            backup2 = _restart_shard_on(backup.addr[1], vocab)
            try:
                c.push_row_grads(*sched[2], 0.1)    # triggers resync
                c.push_row_grads(*sched[3], 0.1)    # incremental again
                assert backup2.stats()["repl_resyncs"] == 1
                assert backup2.state.version == primary.state.version
                assert np.array_equal(backup2.state.rows,
                                      primary.state.rows)
                assert np.array_equal(
                    primary.state.rows,
                    _reference_apply(table, sched))
            finally:
                backup2.stop()
    finally:
        primary.stop()
        backup.stop()


def test_backup_fast_restart_gap_refused_then_resynced():
    """A backup that restarts FAST (reachable again within the repl
    link's in-call reconnect) must not accept an incremental record
    over the gap it just acquired — it refuses with NEED_RESYNC and
    the next replication ships the full state, making it exact."""
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM,
                                             repl_retry_s=0.0)
    backup2 = None
    try:
        with _client([spec]) as c:
            table = _table(vocab)
            sched = _push_schedule(vocab, 4)
            c.register()
            c.load_table(table)
            c.push_row_grads(*sched[0], 0.1)
            backup.kill()
            # fresh backup on the SAME address, version 0 — a gap
            backup2 = _restart_shard_on(backup.addr[1], vocab)
            c.push_row_grads(*sched[1], 0.1)    # incremental REFUSED
            assert backup2.state.version == 0   # nothing applied over it
            c.push_row_grads(*sched[2], 0.1)    # full-state resync
            c.push_row_grads(*sched[3], 0.1)    # incremental again
            assert backup2.stats()["repl_resyncs"] == 1
            assert backup2.state.version == primary.state.version
            assert np.array_equal(backup2.state.rows,
                                  primary.state.rows)
            assert np.array_equal(primary.state.rows,
                                  _reference_apply(table, sched))
    finally:
        if backup2 is not None:
            backup2.stop()
        primary.stop()
        backup.stop()


# ---- the acceptance chaos run ------------------------------------------

@pytest.mark.locks      # chaos lane re-run under LockOrderGuard
def test_chaos_primary_kill_midpass_failover_bit_identical(
        lock_order_guard):
    """Kill the primary of shard 0 on its 3rd push, MID-PASS, while a
    lost ACK hits shard 1 — the client fails over to shard 0's chain
    replica, re-registers, retries the in-flight epoch, finishes the
    pass, and the final table is BIT-identical to an unfaulted run of
    the same schedule."""
    vocab = 32
    n_shards = 2
    sched = _push_schedule(vocab, 8)
    table = _table(vocab)

    with PServerGroup(vocab, DIM, n_shards=n_shards) as ref_group:
        with _client(ref_group.specs) as c:
            unfaulted = _run_pass(c, table, sched)

    with PServerGroup(vocab, DIM, n_shards=n_shards) as group:
        kill_plan = FaultPlan(pserver_kill_push_at=2)
        ack_plan = FaultPlan(pserver_lost_ack_at=4)
        kill_plan.wrap_pserver_shard(group.primaries[0])
        ack_plan.wrap_pserver_shard(group.primaries[1])
        with _client(group.specs) as c:
            faulted = _run_pass(c, table, sched)
            assert kill_plan.count("pskill") == 1
            assert ack_plan.count("pslostack") == 1
            assert group.primaries[0].killed
            # failover re-registered on the replica; the lost-ACK retry
            # was answered DUP somewhere
            assert c.stats["reregistrations"] >= 1
            assert c.stats["duplicate_acks"] >= 1
        assert np.array_equal(faulted, unfaulted)
        # shard 0 survives on its replica: it holds every update
        # exactly once despite never seeing the killed primary again
        assert np.array_equal(
            group.backups[0].state.rows,
            unfaulted[group.specs[0].row_lo:group.specs[0].row_hi])


# ---- leases ------------------------------------------------------------

def test_lease_expiry_releases_in_flight_pass():
    """Trainer A registers, pushes, then dies silently. Trainer B
    finishes — the pass must NOT wedge on A: once A's lease expires,
    A is released from the barrier and B's pass completes. A's later
    push transparently re-registers (fresh lease, same epochs)."""
    vocab = 8
    clock = ManualClock()
    shard = PServerShard(0, 0, vocab, DIM, lease_ttl_s=5.0, clock=clock)
    from paddle_tpu.native.pserver import ShardSpec

    spec = ShardSpec(0, 0, vocab, [shard.addr])
    try:
        # leases renew with the TTL each trainer REGISTERED — A's short
        # lease dies with it while B (longer lease, the survivor)
        # keeps the pass
        a = _client([spec], trainer_id=1, lease_ttl_s=5.0)
        b = _client([spec], trainer_id=2, lease_ttl_s=50.0)
        a.register()
        b.register()
        a.push_row_grads(np.asarray([3], np.int64),
                         np.ones((1, DIM), np.float32), 0.1)
        assert b.finish_pass(wait=False) == 0       # A still holds it
        assert b.pass_state() == 0
        clock.advance(6.0)                          # A's lease expires
        assert b.pass_state() == 1                  # pass released
        assert shard.stats()["lease_expirations"] == 1
        # A is gone from the barrier but its epoch watermark survives:
        # a re-registered A cannot double-apply an old epoch
        before = shard.state.rows.copy()
        a._tokens[0] = None      # simulate A noticing via LEASE_EXPIRED
        a._epochs[0] -= 1        # replay the last epoch
        a.push_row_grads(np.asarray([3], np.int64),
                         np.ones((1, DIM), np.float32), 0.1)
        assert np.array_equal(shard.state.rows, before)
        assert a.stats["duplicate_acks"] == 1
        a.close()
        b.close()
    finally:
        shard.stop()


def test_finish_pass_barrier_survives_primary_death():
    """A finish vote lives on the server that took it. Trainer A votes
    on the primary and waits; the primary dies; trainer B's vote fails
    over to the replica. A's poll must detect its lease token changing
    (the heartbeat re-registers on the replica) and RE-VOTE there —
    the barrier completes on the replica instead of stranding A in
    TimeoutError against a dead server's pass counter."""
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM)
    # real wall-clock leases: 2.0s (renewed every ttl/3 by the barrier
    # poll) rides out scheduler stalls on a loaded 1-vCPU runner that
    # expired a 0.5s lease mid-barrier and released the vote early
    a = _client([spec], trainer_id=1, lease_ttl_s=2.0)
    b = _client([spec], trainer_id=2, lease_ttl_s=2.0)
    try:
        a.register()
        b.register()
        a.push_row_grads(np.asarray([1], np.int64),
                         np.ones((1, DIM), np.float32), 0.1)
        result = {}

        def wait_a():
            try:
                result["pass"] = a.finish_pass(poll_s=0.02,
                                               timeout_s=15.0)
            except Exception as e:          # surfaced on the main thread
                result["err"] = e

        t = threading.Thread(target=wait_a, daemon=True)
        t.start()
        time.sleep(0.3)                 # A's vote lands on the primary
        primary.kill()                  # ...and dies with it
        got_b = b.finish_pass(poll_s=0.02, timeout_s=15.0)
        t.join(timeout=20.0)
        assert not t.is_alive()
        assert "err" not in result, result.get("err")
        assert result["pass"] == got_b >= 1
    finally:
        a.close()
        b.close()
        primary.stop()
        backup.stop()


def test_push_without_lease_reregisters():
    """A push landing on a server that never granted this trainer a
    lease (the failover target) gets LEASE_EXPIRED and the client
    re-registers + retries the same epoch — no manual intervention."""
    vocab = 8
    with PServerGroup(vocab, DIM, n_shards=1, replicated=False) as g:
        with _client(g.specs) as c:
            # a token the server never granted — the state a client is
            # in right after failing over to a replica
            c._tokens[0] = 12345
            c.push_row_grads(np.asarray([1], np.int64),
                             np.ones((1, DIM), np.float32), 1.0)
            assert c.stats["reregistrations"] == 1
            assert g.primaries[0].state.rows[1, 0] == -1.0
            # no manual register() at all: the first push registers
            # lazily and applies exactly once
            assert g.primaries[0].stats()["live_trainers"] == 1


# ---- snapshots + restart catch-up --------------------------------------

def test_snapshot_restart_resumes_plus_replica_catchup(tmp_path):
    """Snapshot, keep pushing, kill the primary abruptly. The restarted
    shard loads its (stale) snapshot, then adopts the replica's newer
    state — resuming at the exact row values the pair had."""
    vocab = 8
    primary, backup, spec = start_shard_pair(
        0, 0, vocab, DIM, snapshot_dir=str(tmp_path), name="s0")
    try:
        with _client([spec]) as c:
            table = _table(vocab)
            sched = _push_schedule(vocab, 4)
            c.register()
            c.load_table(table)
            c.push_row_grads(*sched[0], 0.1)
            primary.snapshot()
            for ids, grads in sched[1:]:
                c.push_row_grads(ids, grads, 0.1)
        expected = _reference_apply(table, sched)
        primary.kill()          # abrupt: no final snapshot

        restarted = PServerShard(
            0, 0, vocab, DIM, name="s0-primary",
            snapshot_dir=str(tmp_path), sync_from=backup.addr,
            replica_addr=backup.addr)
        try:
            assert restarted.restored_from is not None
            assert restarted.synced_from_peer
            assert restarted.state.version == backup.state.version
            assert np.array_equal(restarted.state.rows, expected)
            # epochs came along: a replayed client epoch still dedupes
            assert restarted.state.epochs == backup.state.epochs
        finally:
            restarted.stop()
    finally:
        primary.stop()
        backup.stop()


def test_snapshot_write_oserror_keeps_serving(tmp_path):
    """The flaky-NFS shape: a snapshot-write OSError must not take the
    shard down — the gap stays visible in last_snapshot_error and the
    next snapshot clears it."""
    vocab = 8
    shard = PServerShard(0, 0, vocab, DIM, snapshot_dir=str(tmp_path),
                         name="flaky")
    plan = FaultPlan(pserver_snapshot_error_at=0)
    plan.wrap_pserver_shard(shard)
    from paddle_tpu.native.pserver import ShardSpec

    spec = ShardSpec(0, 0, vocab, [shard.addr])
    try:
        with _client([spec]) as c:
            c.register()
            c.load_table(_table(vocab))
            with pytest.raises(OSError):
                shard.snapshot()
            assert shard.last_snapshot_error is not None
            assert plan.count("pssnap") == 1
            # still serving
            assert c.get_rows(np.asarray([2], np.int64)).shape == (1, DIM)
            shard.snapshot()            # fault spent (once=True)
            assert shard.last_snapshot_error is None
            assert ShardState.load(shard.snapshot_path, DIM).version \
                == shard.state.version
    finally:
        shard.stop()


def test_slow_replica_stretches_chain_without_losing_it():
    """A stalled replica apply delays the ACK (chain replication waits
    for the tail) but neither reorders nor drops updates."""
    vocab = 8
    primary, backup, spec = start_shard_pair(0, 0, vocab, DIM)
    plan = FaultPlan(pserver_replica_delay_at=1,
                     pserver_replica_delay_s=0.05)
    plan.wrap_pserver_shard(backup)
    try:
        with _client([spec]) as c:
            table = _table(vocab)
            sched = _push_schedule(vocab, 3)
            got = _run_pass(c, table, sched)
        assert plan.count("psslowrepl") == 1
        assert np.array_equal(got, _reference_apply(table, sched))
        assert np.array_equal(backup.state.rows, primary.state.rows)
    finally:
        primary.stop()
        backup.stop()


# ---- the embedding adapter + the resilient trainer ---------------------

def test_pserver_embedding_matches_rowwise_reference():
    """PServerEmbedding's lookup/apply_row_grads agree with the local
    rowwise_sgd_update semantics (padding ids contribute zero and are
    never applied)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.sparse import rowwise_sgd_update

    vocab = 16
    with PServerGroup(vocab, DIM, n_shards=2, replicated=False) as g:
        with _client(g.specs) as c:
            c.register()
            emb = PServerEmbedding(c)
            handle = emb.init(jax.random.key(1))
            server_table = c.fetch_table()

            ids = jnp.asarray([0, 9, 15, -1], jnp.int32)
            vecs = emb.lookup(handle, ids)
            assert np.array_equal(np.asarray(vecs[3]), np.zeros(DIM))
            assert np.array_equal(np.asarray(vecs[:3]),
                                  server_table[[0, 9, 15]])

            grads = jnp.asarray(
                np.random.RandomState(5).rand(4, DIM), jnp.float32)
            emb.apply_row_grads(handle, ids, grads, 0.3)
            ref = rowwise_sgd_update(jnp.asarray(server_table),
                                     ids, grads, 0.3)
            np.testing.assert_allclose(c.fetch_table(), np.asarray(ref),
                                       rtol=1e-6)


def test_resilient_trainer_through_shard_kill(tmp_path):
    """The tentpole integration: a ResilientTrainer run whose data path
    looks rows up from the pserver tier and pushes row grads after
    every iteration — with the shard's PRIMARY killed mid-pass. The
    run must complete through the failover with final dense params AND
    final sparse table identical to an unfaulted twin run."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import nn, optim
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train import ResilientTrainer, Trainer
    from paddle_tpu.train import events as E

    vocab, steps = 8, 6
    id_sched = [np.random.RandomState(100 + i).randint(0, vocab, 4)
                .astype(np.int64) for i in range(steps)]

    def run(specs, ckpt_dir):
        with _client(specs) as c:
            c.register()
            emb = PServerEmbedding(c)
            handle = emb.init(jax.random.key(2))

            def factory():
                for i in range(steps):
                    vecs = np.asarray(emb.lookup(handle, id_sched[i]))
                    yield (vecs,
                           (id_sched[i] % 3).astype(np.int64))

            def on_event(ev):
                if isinstance(ev, E.EndIteration):
                    i = ev.batch_id
                    g = np.full((4, DIM), (i + 1) / 10.0, np.float32)
                    emb.apply_row_grads(handle, id_sched[i], g, 0.5)

            model = nn.Sequential([nn.Dense(3, name="out")])
            tr = Trainer(model,
                         lambda o, y: jnp.mean(
                             losses.softmax_cross_entropy(o, y)),
                         optim.sgd(0.1))
            state = tr.init_state(ShapeSpec((4, DIM)))
            rt = ResilientTrainer(tr, str(ckpt_dir))
            final = rt.run(state, factory, num_passes=1,
                           event_handler=on_event)
            c.finish_pass(timeout_s=10.0)
            return (jax.tree.map(np.asarray, final.params),
                    c.fetch_table())

    with PServerGroup(vocab, DIM, n_shards=1) as ref_group:
        ref_params, ref_table = run(ref_group.specs, tmp_path / "ref")

    with PServerGroup(vocab, DIM, n_shards=1) as group:
        plan = FaultPlan(pserver_kill_push_at=2)
        plan.wrap_pserver_shard(group.primaries[0])
        params, table = run(group.specs, tmp_path / "chaos")
        assert plan.count("pskill") == 1
        assert group.primaries[0].killed

    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_array_equal(a, b)
    assert np.array_equal(table, ref_table)


# ---- the MasterClient contract the epoch scheme leans on ---------------

class _FrameSink:
    """Accepts connections, reads ONE length-prefixed frame per
    connection, counts it, then closes WITHOUT replying — the
    lost-response shape that separates idempotent (retried) from
    non-idempotent (single-send) MasterClient ops."""

    def __init__(self):
        import socket as _socket

        self._sock = _socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self.frames = 0
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        import struct as _struct

        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                conn.settimeout(2.0)
                hdr = b""
                while len(hdr) < 4:
                    b = conn.recv(4 - len(hdr))
                    if not b:
                        raise ConnectionError
                    hdr += b
                (n,) = _struct.unpack("<I", hdr)
                got = 0
                while got < n:
                    b = conn.recv(n - got)
                    if not b:
                        raise ConnectionError
                    got += len(b)
                self.frames += 1
            except OSError:
                pass
            finally:
                conn.close()

    def close(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


@pytest.mark.parametrize("op,expected_frames", [
    ("add_task", 1),      # non-idempotent: ONE send, no silent replay
    ("next_pass", 1),     # non-idempotent: drain-check must not double
    ("counts", 4),        # idempotent read: retried (retries + 1)
])
def test_masterclient_send_policy(op, expected_frames):
    """add_task/next_pass get exactly ONE send attempt when the
    response is lost (a re-send could register a duplicate task / trip
    the drain check — the failure class the pserver push epochs exist
    to remove), while idempotent ops retry through the same outage."""
    from paddle_tpu.native.taskqueue import MasterClient

    sink = _FrameSink()
    try:
        client = MasterClient(port=sink.port, timeout=1.0, retries=3,
                              backoff_base=0.001, backoff_max=0.01,
                              seed=0)
        with pytest.raises(ConnectionError):
            if op == "add_task":
                client.add_task(b"payload")
            elif op == "next_pass":
                client.next_pass()
            else:
                client.counts()
        deadline = time.monotonic() + 2.0
        while sink.frames < expected_frames \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sink.frames == expected_frames
        client.close()
    finally:
        sink.close()
