"""Python-free native inference: export .ptni, drive from pure C.

The reference's capi contract (reference: capi/gradient_machine.h:36-112)
is a C API that serves a merged config+weights file with no interpreter
in the process, including multi-threaded serving over shared parameters
(:62 create_shared_param). These tests:

  1. export LeNet / an MLP / a residual CIFAR ResNet to .ptni,
  2. check the native engine's outputs against the jax forward (via
     ctypes for convenience),
  3. compile tests/capi_native_driver.c with NO Python includes or libs
     and run it: single forward vs golden + N concurrent threads on one
     shared model handle.
"""

import ctypes
import os
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import models, nn
from paddle_tpu.native import build
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.serve.native_export import export_native


def _export_and_load(tmp_path, model, spec, seed=0):
    rng = jax.random.key(seed)
    params, state = model.init(rng, spec)
    path = os.path.join(tmp_path, "model.ptni")
    export_native(model, params, state, spec, path)
    return params, state, path


def _native_forward(path, x):
    lib = ctypes.CDLL(build.ensure_infer_built())
    lib.ptn_load.restype = ctypes.c_void_p
    lib.ptn_load.argtypes = [ctypes.c_char_p]
    lib.ptn_forward.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.c_longlong,
                                ctypes.POINTER(ctypes.c_float)]
    lib.ptn_output_dim.restype = ctypes.c_longlong
    lib.ptn_output_dim.argtypes = [ctypes.c_void_p]
    lib.ptn_last_error.restype = ctypes.c_char_p
    m = lib.ptn_load(path.encode())
    assert m, lib.ptn_last_error().decode()
    x = np.ascontiguousarray(x, np.float32)
    out = np.zeros((x.shape[0], lib.ptn_output_dim(m)), np.float32)
    rc = lib.ptn_forward(
        m, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.shape[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    assert rc == 0, lib.ptn_last_error().decode()
    lib.ptn_free(ctypes.c_void_p(m))
    return out


def _jax_forward(model, params, state, x):
    out, _ = model.apply(params, state, jnp.asarray(x), training=False)
    return np.asarray(out)


@pytest.mark.parametrize("make_model,spec", [
    (lambda: models.lenet.lenet(10, with_bn=True),
     ShapeSpec((4, 28, 28, 1))),
    (lambda: models.lenet.mlp(10, hidden=(32, 16)),
     ShapeSpec((4, 28, 28, 1))),
    (lambda: models.resnet.resnet_cifar(8, num_classes=10),
     ShapeSpec((2, 16, 16, 3))),
])
def test_native_matches_jax(tmp_path, make_model, spec):
    model = make_model()
    params, state, path = _export_and_load(str(tmp_path), model, spec)
    x = np.random.RandomState(0).rand(*spec.shape).astype(np.float32)
    ours = _native_forward(path, x)
    ref = _jax_forward(model, params, state, x)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_native_dynamic_batch(tmp_path):
    """The artifact's batch dim is dynamic: export with one batch size,
    serve another."""
    model = models.lenet.lenet(10, with_bn=False)
    spec = ShapeSpec((4, 28, 28, 1))
    params, state, path = _export_and_load(str(tmp_path), model, spec)
    x = np.random.RandomState(1).rand(7, 28, 28, 1).astype(np.float32)
    ours = _native_forward(path, x)
    ref = _jax_forward(model, params, state, x)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_unsupported_layer_lists_supported_set(tmp_path):
    model = nn.Sequential([nn.Lambda(lambda x: x, name="odd")])
    params, state = model.init(jax.random.key(0), ShapeSpec((2, 4)))
    with pytest.raises(ValueError, match="supported"):
        export_native(model, params, state, ShapeSpec((2, 4)),
                      os.path.join(str(tmp_path), "x.ptni"))


def test_pure_c_driver_no_python(tmp_path):
    """Compile the C driver WITHOUT Python and run it: the single-thread
    forward must match the jax golden, then N threads share one model
    handle concurrently (the reference's clone-serving pattern)."""
    tmp = str(tmp_path)
    model = models.lenet.lenet(10, with_bn=True)
    spec = ShapeSpec((8, 28, 28, 1))
    params, state, path = _export_and_load(tmp, model, spec)

    x = np.random.RandomState(2).rand(8, 28, 28, 1).astype(np.float32)
    golden = _jax_forward(model, params, state, x)
    in_path = os.path.join(tmp, "input.f32")
    golden_path = os.path.join(tmp, "golden.f32")
    x.astype(np.float32).tofile(in_path)
    golden.astype(np.float32).tofile(golden_path)

    lib = build.ensure_infer_built()
    driver_src = os.path.join(os.path.dirname(__file__),
                              "capi_native_driver.c")
    exe = os.path.join(tmp, "driver")
    # the whole point: NO python-config anywhere on this line
    compile_cmd = ["gcc", "-O2", "-Wall", driver_src,
                   lib, "-lm", "-lpthread", "-o", exe]
    subprocess.run(compile_cmd, check=True, capture_output=True, text=True)

    env = dict(os.environ)
    env["LD_LIBRARY_PATH"] = os.path.dirname(lib)
    proc = subprocess.run(
        [exe, path, in_path, golden_path, "8", "8"],
        capture_output=True, text=True, env=env, timeout=300)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "single-thread forward matches golden" in proc.stdout
    assert "8 concurrent threads" in proc.stdout
