/* C driver for the inference ABI — the parity check for the reference's
 * capi consumer programs (reference: capi/examples, go/pserver/client/c/
 * test/test_cclient.c style: a real C main driving the library).
 *
 * Usage: capi_driver <libpaddle_tpu_capi.so> <repo_root> <artifact.tar>
 *        <n_floats_in> <n_floats_out_expected>
 * Feeds an all-0.5 buffer, checks output count and finiteness, prints
 * the first output value as "OUT0 <v>".
 */
#include <dlfcn.h>
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

typedef int (*pt_init_t)(const char*);
typedef void* (*pt_load_t)(const char*);
typedef const char* (*pt_signature_t)(void*);
typedef int (*pt_forward_t)(void*, const char**, const uint64_t*, int,
                            char***, uint64_t**, int*);
typedef void (*pt_free_outputs_t)(char**, uint64_t*, int);
typedef void (*pt_release_t)(void*);
typedef const char* (*pt_last_error_t)(void);

int main(int argc, char** argv) {
  if (argc != 6) {
    fprintf(stderr, "usage: %s lib.so repo_root artifact n_in n_out\n",
            argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 2;
  }
  pt_init_t pt_init = (pt_init_t)dlsym(lib, "pt_init");
  pt_load_t pt_load = (pt_load_t)dlsym(lib, "pt_load");
  pt_signature_t pt_signature = (pt_signature_t)dlsym(lib, "pt_signature");
  pt_forward_t pt_forward = (pt_forward_t)dlsym(lib, "pt_forward");
  pt_free_outputs_t pt_free_outputs =
      (pt_free_outputs_t)dlsym(lib, "pt_free_outputs");
  pt_release_t pt_release = (pt_release_t)dlsym(lib, "pt_release");
  pt_last_error_t pt_last_error =
      (pt_last_error_t)dlsym(lib, "pt_last_error");
  if (!pt_init || !pt_load || !pt_forward || !pt_free_outputs ||
      !pt_release || !pt_signature || !pt_last_error) {
    fprintf(stderr, "missing symbols\n");
    return 2;
  }

  if (pt_init(argv[2]) != 0) {
    fprintf(stderr, "pt_init: %s\n", pt_last_error());
    return 1;
  }
  void* model = pt_load(argv[3]);
  if (!model) {
    fprintf(stderr, "pt_load: %s\n", pt_last_error());
    return 1;
  }
  printf("SIGNATURE %s\n", pt_signature(model));

  long n_in = strtol(argv[4], NULL, 10);
  long n_out_expected = strtol(argv[5], NULL, 10);
  float* in = (float*)malloc(sizeof(float) * n_in);
  for (long i = 0; i < n_in; i++) in[i] = 0.5f;
  const char* in_bufs[1] = {(const char*)in};
  uint64_t in_lens[1] = {(uint64_t)(sizeof(float) * n_in)};

  char** out_bufs;
  uint64_t* out_lens;
  int n_out;
  if (pt_forward(model, in_bufs, in_lens, 1, &out_bufs, &out_lens, &n_out) !=
      0) {
    fprintf(stderr, "pt_forward: %s\n", pt_last_error());
    return 1;
  }
  uint64_t total = 0;
  for (int i = 0; i < n_out; i++) total += out_lens[i] / sizeof(float);
  if (total != (uint64_t)n_out_expected) {
    fprintf(stderr, "expected %ld output floats, got %llu\n", n_out_expected,
            (unsigned long long)total);
    return 1;
  }
  float* out0 = (float*)out_bufs[0];
  for (uint64_t i = 0; i < out_lens[0] / sizeof(float); i++) {
    if (!isfinite(out0[i])) {
      fprintf(stderr, "non-finite output\n");
      return 1;
    }
  }
  printf("OUT0 %f\n", out0[0]);

  /* second forward on the same handle (serving reuse) */
  if (pt_forward(model, in_bufs, in_lens, 1, &out_bufs, &out_lens, &n_out) !=
      0) {
    fprintf(stderr, "second pt_forward: %s\n", pt_last_error());
    return 1;
  }
  pt_free_outputs(out_bufs, out_lens, n_out);
  pt_release(model);
  free(in);
  printf("CAPI_OK\n");
  return 0;
}
