"""Ring / Ulysses sequence-parallel attention vs dense reference.

Validated on the 8-device CPU mesh (conftest), mirroring the reference's
in-process multi-node simulation strategy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.parallel import ring_attention as ra


def _qkv(np_rng, b=2, t=32, h=4, d=8, dtype=jnp.float32):
    q = jnp.asarray(np_rng.randn(b, t, h, d), dtype)
    k = jnp.asarray(np_rng.randn(b, t, h, d), dtype)
    v = jnp.asarray(np_rng.randn(b, t, h, d), dtype)
    return q, k, v


def _seq_mesh(n=4):
    return mesh_lib.build_mesh(
        mesh_lib.MeshConfig(data=1, model=1, seq=n),
        devices=jax.devices()[:n])


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_matches_dense(np_rng, kind, causal):
    q, k, v = _qkv(np_rng)
    mesh = _seq_mesh(4)
    fn = ra.make_sequence_parallel_attention(mesh, kind=kind, causal=causal)
    out = jax.jit(fn)(q, k, v)
    ref = ra.dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_dense(np_rng):
    q, k, v = _qkv(np_rng, b=1, t=16, h=2, d=4)
    mesh = _seq_mesh(4)
    fn = ra.make_sequence_parallel_attention(mesh, kind="ring", causal=True)

    def loss_sp(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ra.dense_attention(q, k, v, causal=True) ** 2)

    g_sp = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sp, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_batch_and_seq_axes(np_rng):
    """seq axis composes with data-parallel batch sharding."""
    q, k, v = _qkv(np_rng, b=4, t=16)
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshConfig(data=2, model=1, seq=4))
    fn = ra.make_sequence_parallel_attention(
        mesh, kind="ring", causal=True, batch_axis=mesh_lib.DATA_AXIS)
    out = jax.jit(fn)(q, k, v)
    ref = ra.dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_dense_attention_mask(np_rng):
    q, k, v = _qkv(np_rng, b=2, t=8, h=2, d=4)
    mask = jnp.asarray(np_rng.rand(2, 8, 8) > 0.3)
    mask = mask | jnp.eye(8, dtype=bool)[None]  # keep rows non-empty
    out = ra.dense_attention(q, k, v, mask=mask)
    # brute-force per-row check
    d = q.shape[-1]
    scores = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(k))
    scores /= np.sqrt(d)
    scores = np.where(np.asarray(mask)[:, None], scores, -1e30)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)
