"""Multi-host helpers, remat train step, pruning hooks, MultiTask."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn, optim, parallel
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.optim.hooks import magnitude_masks, with_pruning
from paddle_tpu.parallel import distributed as D
from paddle_tpu.train.state import TrainState
from paddle_tpu.train.trainer import make_train_step


def test_distributed_single_process_noops():
    D.initialize()  # must not raise without a coordinator
    assert D.process_count() == 1
    assert D.process_index() == 0
    assert D.is_primary()
    D.sync_hosts()  # no-op
    tree = {"a": np.ones(3)}
    assert D.broadcast_from_primary(tree) is tree
    assert D.replicated_agree(np.asarray([1, 2]))


def _fit_step(remat):
    model = nn.Sequential([nn.Dense(16, activation="relu"), nn.Dense(4)])
    params, mstate = model.init(jax.random.key(0), ShapeSpec((8, 8)))
    opt = optim.sgd(0.1)
    state = TrainState.create(params, mstate, opt)
    step = make_train_step(
        model, lambda lo, la: jnp.mean(losses.softmax_cross_entropy(lo, la)),
        opt, remat=remat, donate=False)
    x = jnp.asarray(np.random.RandomState(0).rand(8, 8), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 4, 8))
    rng = jax.random.key(1)
    s1, l1, _ = step(state, rng, (x,), (y,))
    return float(l1), s1


def test_remat_matches_plain():
    l_plain, s_plain = _fit_step(remat=False)
    l_remat, s_remat = _fit_step(remat=True)
    assert l_plain == l_remat
    for a, b in zip(jax.tree_util.tree_leaves(s_plain.params),
                    jax.tree_util.tree_leaves(s_remat.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_magnitude_masks_exact_k_with_ties():
    # zero-initialized tensor: every magnitude ties at 0; exactly k
    # entries must still survive
    params = {"b": jnp.zeros(8)}
    masks = magnitude_masks(params, 0.75)
    assert int(np.asarray(masks["b"]).sum()) == 2


def test_multitask_wrong_arity_raises():
    import pytest as _pytest

    model = nn.MultiTask({"a": nn.Dense(2), "b": nn.Dense(3)})
    params, mstate = model.init(jax.random.key(0), ShapeSpec((4, 5)),
                                ShapeSpec((4, 6)))
    with _pytest.raises(Exception, match="sub-networks"):
        model.apply(params, mstate, jnp.ones((4, 5)))


def test_magnitude_masks_and_pruning():
    params = {"fc": {"kernel": jnp.asarray(
        np.random.RandomState(0).randn(8, 8), jnp.float32),
        "bias": jnp.zeros(8)}}
    masks = magnitude_masks(params, 0.75,
                            match=lambda path: "kernel" in path)
    km = np.asarray(masks["fc"]["kernel"])
    assert km.sum() == 16  # kept 25% of 64
    assert np.asarray(masks["fc"]["bias"]).all()  # unmatched -> all ones

    opt = with_pruning(optim.sgd(0.1), masks)
    opt_state = opt.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    new_params, _ = opt.update(grads, opt_state, params, 0)
    nk = np.asarray(new_params["fc"]["kernel"])
    assert (nk[~km] == 0).all()          # pruned entries forced to zero
    assert (nk[km] != 0).any()


def test_multitask_joint_training():
    model = nn.MultiTask([
        ("cls", nn.Sequential([nn.Dense(8, activation="relu"),
                               nn.Dense(2)])),
        ("reg", nn.Dense(1)),
    ])
    params, mstate = model.init(jax.random.key(0), ShapeSpec((4, 6)),
                                ShapeSpec((4, 3)))
    assert set(params) == {"cls", "reg"}
    (cls_out, reg_out), _ = model.apply(
        params, mstate, jnp.ones((4, 6)), jnp.ones((4, 3)))
    assert cls_out.shape == (4, 2) and reg_out.shape == (4, 1)

    # joint loss trains both heads in one step
    opt = optim.adam(1e-2)
    state = TrainState.create(params, mstate, opt)

    def loss_fn(outputs, labels_cls, labels_reg):
        c, r = outputs
        return (jnp.mean(losses.softmax_cross_entropy(c, labels_cls))
                + jnp.mean((r[:, 0] - labels_reg) ** 2))

    step = make_train_step(model, loss_fn, opt, donate=False)
    rngs = np.random.RandomState(0)
    x1 = jnp.asarray(rngs.rand(4, 6), jnp.float32)
    x2 = jnp.asarray(rngs.rand(4, 3), jnp.float32)
    y1 = jnp.asarray(rngs.randint(0, 2, 4))
    y2 = jnp.asarray(rngs.rand(4), jnp.float32)
    state2, loss, _ = step(state, jax.random.key(1), (x1, x2), (y1, y2))
    assert np.isfinite(float(loss))
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(state2.params)))
    assert changed


def test_multitask_abstract_out_spec():
    model = nn.MultiTask({"a": nn.Dense(2), "b": nn.Dense(3)})
    _, _, outs = model._init(None, ShapeSpec((4, 6)), ShapeSpec((4, 3)),
                             _abstract=True)
    assert outs[0].shape == (4, 2) and outs[1].shape == (4, 3)
