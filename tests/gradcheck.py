"""Numeric-vs-analytic gradient checking utilities.

The rebuild's version of the reference's layer gradient harness
(reference: gserver/tests/LayerGradUtil.h:298 testLayerGrad — perturb along
a random direction, compare analytic directional derivative to central
difference) and fluid's numeric checker (reference:
python/paddle/v2/fluid/tests/op_test.py get_numeric_gradient, which works
in double precision). Requires jax_enable_x64 (set in conftest).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtypes import Policy, default_policy, set_default_policy


def directional_grad_check(f, x, *, eps: float = 1e-5, atol: float = 1e-5,
                           rtol: float = 1e-3, seed: int = 0, n_dirs: int = 3):
    """Check d/dt f(x + t*v) at t=0 against jax.grad along random directions.

    f: pytree -> scalar. x: pytree of float arrays. Runs f in float64 (both
    by casting inputs and by overriding the global dtype policy) so the
    central difference isn't drowned by float32 cancellation.
    """
    x64 = jax.tree.map(lambda l: jnp.asarray(l, jnp.float64), x)
    prev_policy = default_policy()
    set_default_policy(
        Policy(param_dtype=jnp.float64, compute_dtype=jnp.float64,
               accum_dtype=jnp.float64)
    )
    try:
        g = jax.grad(lambda p: jnp.asarray(f(p), jnp.float64))(x64)
        rng = np.random.RandomState(seed)
        leaves, treedef = jax.tree.flatten(x64)
        g_leaves = treedef.flatten_up_to(g)
        for d in range(n_dirs):
            vs = [rng.randn(*l.shape) for l in leaves]
            analytic = sum(
                float(jnp.sum(gl * v)) for gl, v in zip(g_leaves, vs)
            )
            xp = treedef.unflatten([l + eps * v for l, v in zip(leaves, vs)])
            xm = treedef.unflatten([l - eps * v for l, v in zip(leaves, vs)])
            numeric = (float(f(xp)) - float(f(xm))) / (2 * eps)
            np.testing.assert_allclose(
                analytic, numeric, rtol=rtol, atol=atol,
                err_msg=f"direction {d}: analytic {analytic} vs numeric {numeric}",
            )
    finally:
        set_default_policy(prev_policy)
