"""Mixture-of-experts tests: gating invariants, dense-dispatch vs naive
per-token routing, and expert-parallel (all-to-all) vs single-device
equivalence on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.parallel import moe

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


def _params(rng, e=4, d=8, f=16):
    return moe.init_moe_params(rng, e, d, f)


class TestGating:
    def test_dispatch_combine_invariants(self):
        t, e, cap = 16, 4, 16  # cap=T: nothing can ever drop
        logits = jax.random.normal(jax.random.key(0), (t, e))
        dispatch, combine, aux, dropped = moe.top_k_gating(logits, 2, cap)
        # each token occupies at most k slots, each slot at most once
        assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= 2 + 1e-6
        slot_use = jnp.sum(dispatch, axis=0)  # [E, C]
        assert float(jnp.max(slot_use)) <= 1 + 1e-6
        # kept tokens' combine weights sum to 1
        w = jnp.sum(combine, axis=(1, 2))
        kept = jnp.sum(dispatch, axis=(1, 2)) > 0
        np.testing.assert_allclose(np.asarray(w[kept]), 1.0, atol=1e-5)
        assert float(aux) > 0
        assert float(dropped) == 0.0

    def test_capacity_drops(self):
        t, e = 32, 2
        # all tokens prefer expert 0 -> capacity 4 must drop most
        logits = jnp.tile(jnp.asarray([[5.0, -5.0]]), (t, 1))
        dispatch, combine, aux, dropped = moe.top_k_gating(logits, 1, 4)
        assert float(jnp.sum(dispatch[:, 0])) == 4.0
        assert float(dropped) == pytest.approx((t - 4) / t)
        # aux loss far above the balanced value of 1.0
        assert float(aux) > 1.5

    def test_capacity_for(self):
        assert moe.capacity_for(256, 8, 1.25) == 40
        assert moe.capacity_for(256, 8, 1.25, k=2) == 80  # scales with k
        assert moe.capacity_for(10, 64, 1.0) == 4  # floor 1, rounded to 4


class TestMoEFFN:
    def test_matches_naive_per_token(self):
        t, d, e, f = 24, 8, 4, 16
        params = _params(jax.random.key(1), e, d, f)
        x = jax.random.normal(jax.random.key(2), (t, d))
        out = moe.moe_ffn(params, x, k=2, capacity_factor=8.0)  # no drops
        assert float(out.dropped) == 0.0

        # naive: route each token through its top-2 experts in python
        probs = np.asarray(jax.nn.softmax(
            x @ params["router"]["kernel"], axis=-1))
        y_ref = np.zeros((t, d), np.float32)
        for i in range(t):
            top = np.argsort(-probs[i])[:2]
            gsum = probs[i][top].sum()
            for ex in top:
                h = np.asarray(jax.nn.gelu(
                    x[i] @ params["w1"][ex] + params["b1"][ex]))
                y = h @ params["w2"][ex] + params["b2"][ex]
                y_ref[i] += (probs[i][ex] / gsum) * np.asarray(y)
        np.testing.assert_allclose(np.asarray(out.y), y_ref, atol=1e-4)

    def test_grads_flow_to_all_parts(self):
        t, d = 16, 8
        params = _params(jax.random.key(3))
        x = jax.random.normal(jax.random.key(4), (t, d))

        def loss(p):
            out = moe.moe_ffn(p, x, k=2, capacity_factor=4.0)
            return jnp.sum(out.y ** 2) + 0.01 * out.aux_loss

        grads = jax.grad(loss)(params)
        for name in ("w1", "w2"):
            assert float(jnp.max(jnp.abs(grads[name]))) > 0
        assert float(jnp.max(jnp.abs(grads["router"]["kernel"]))) > 0


class TestExpertParallel:
    def test_matches_single_device(self):
        devices = jax.devices()
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=1, model=8), devices=devices[:8])
        t, d, e, f = 32, 8, 8, 16
        params = _params(jax.random.key(5), e, d, f)
        x = jax.random.normal(jax.random.key(6), (t, d))

        single = moe.moe_ffn(params, x, k=2, capacity_factor=8.0)

        sharded = moe.shard_moe_params(params, mesh)
        ep = moe.make_expert_parallel_ffn(
            mesh, k=2, capacity_factor=8.0)
        out = jax.jit(ep)(sharded, x)
        np.testing.assert_allclose(np.asarray(out.y),
                                   np.asarray(single.y), atol=1e-4)
        np.testing.assert_allclose(float(out.aux_loss),
                                   float(single.aux_loss), rtol=1e-5)

    def test_data_sharded_tokens_match_single_device(self):
        """The all_to_all exchange path (data-sharded tokens) must agree
        numerically with the single-device reference: at a no-drop
        capacity every token meets its top-k experts with identical
        gates, so a regroup-ordering bug cannot hide."""
        devices = jax.devices()
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=4), devices=devices[:8])
        t, d, e, f = 32, 8, 8, 16
        params = _params(jax.random.key(7), e, d, f)
        sharded = moe.shard_moe_params(params, mesh)
        xh = np.random.RandomState(0).randn(t, d).astype(np.float32)
        x = jax.device_put(
            xh, jax.NamedSharding(mesh, jax.sharding.PartitionSpec(
                mesh_lib.DATA_AXIS)))
        ep = moe.make_expert_parallel_ffn(
            mesh, data_axis=mesh_lib.DATA_AXIS, k=2, capacity_factor=8.0)
        single = moe.moe_ffn(params, jnp.asarray(xh), k=2,
                             capacity_factor=8.0)
        out_fwd = jax.jit(ep)(sharded, x)
        np.testing.assert_allclose(np.asarray(out_fwd.y),
                                   np.asarray(single.y), atol=1e-4)
        ep = moe.make_expert_parallel_ffn(
            mesh, data_axis=mesh_lib.DATA_AXIS, k=2, capacity_factor=4.0)

        @jax.jit
        def step(p, x):
            def loss(p):
                out = ep(p, x)
                return jnp.mean(out.y ** 2) + 0.01 * out.aux_loss, out
            (l, out), grads = jax.value_and_grad(loss, has_aux=True)(p)
            return l, out, grads

        l, out, grads = step(sharded, x)
        assert np.isfinite(float(l))
        assert out.y.shape == (t, d)
        assert float(jnp.max(jnp.abs(grads["w1"]))) > 0


class TestMoETransformer:
    def _cfg(self):
        from paddle_tpu.models import transformer as T
        return T.TransformerConfig(vocab=64, dim=16, n_layers=2, n_heads=2,
                                   mlp_ratio=2, attn_impl="dense",
                                   moe_experts=4, moe_every=2,
                                   moe_capacity_factor=4.0)

    def test_moe_block_placement_and_loss(self):
        from paddle_tpu.models import transformer as T
        cfg = self._cfg()
        params = T.init_params(jax.random.key(0), cfg)
        assert "fc1" in params["blocks"][0] and "moe" not in params["blocks"][0]
        assert "moe" in params["blocks"][1] and "fc1" not in params["blocks"][1]
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 64, (4, 12)), jnp.int32)
        l_moe = T.loss(params, cfg, toks)
        assert np.isfinite(float(l_moe))
        # aux loss participates: weight 0 changes the value
        import dataclasses as dc
        l_no_aux = T.loss(params, dc.replace(cfg, moe_aux_weight=0.0), toks)
        assert float(l_moe) != float(l_no_aux)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_moe_transformer_trains_and_generates(self):
        from paddle_tpu import optim
        from paddle_tpu.models import transformer as T
        cfg = self._cfg()
        params = T.init_params(jax.random.key(1), cfg)
        opt = optim.adam(3e-3)
        opt_state = opt.init(params)
        r = np.random.RandomState(1)
        # learnable structure: next token = (tok + 1) % 32
        base = r.randint(0, 32, (8, 1))
        toks = jnp.asarray((base + np.arange(16)) % 32, jnp.int32)

        @jax.jit
        def step(p, o, toks, i):
            l, g = jax.value_and_grad(lambda p: T.loss(p, cfg, toks))(p)
            p, o = opt.update(g, o, p, i)
            return p, o, l

        first = last = None
        for i in range(60):
            params, opt_state, l = step(params, opt_state, toks,
                                        jnp.asarray(i))
            if first is None:
                first = float(l)
            last = float(l)
        assert last < first * 0.5, (first, last)
        # expert grads actually flowed
        out = T.generate(params, cfg, toks[:2, :4], steps=3)
        assert out.shape == (2, 7)

    def test_moe_tp_sharded_step(self):
        from paddle_tpu import optim
        from paddle_tpu.models import transformer as T
        from paddle_tpu.parallel import sharding as shard_lib
        cfg = self._cfg()
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=4), devices=jax.devices()[:8])
        params = T.init_params(jax.random.key(2), cfg)
        params = jax.device_put(
            params, shard_lib.make_param_shardings(params, mesh,
                                                   T.TP_MOE_RULES))
        opt = optim.adam(1e-3)
        opt_state = opt.init(params)
        toks = jax.device_put(
            np.random.RandomState(2).randint(0, 64, (8, 12)).astype(np.int32),
            shard_lib.batch_sharding(mesh))

        @jax.jit
        def step(p, o, toks):
            l, g = jax.value_and_grad(lambda p: T.loss(p, cfg, toks))(p)
            p, o = opt.update(g, o, p, jnp.zeros((), jnp.int32))
            return p, o, l

        params, opt_state, l = step(params, opt_state, toks)
        jax.block_until_ready(params)
        assert np.isfinite(float(l))
        # expert weights really are sharded over the model axis
        spec = params["blocks"][1]["moe"]["w1"].sharding.spec
        assert spec[0] == mesh_lib.MODEL_AXIS


class TestPaddingMask:
    def test_pads_claim_no_capacity(self):
        t, e, cap = 8, 2, 4
        # all tokens want expert 0; tokens 0..3 are padding
        logits = jnp.tile(jnp.asarray([[5.0, -5.0]]), (t, 1))
        mask = jnp.arange(t) >= 4
        dispatch, combine, aux, dropped = moe.top_k_gating(
            logits, 1, cap, token_mask=mask)
        # the 4 REAL tokens all fit: pads must not have eaten the slots
        assert float(jnp.sum(dispatch[4:, 0])) == 4.0
        assert float(jnp.sum(dispatch[:4])) == 0.0
        assert float(dropped) == 0.0
        # aux ignores pads: identical to the unpadded 4-token batch
        _, _, aux4, _ = moe.top_k_gating(logits[4:], 1, cap)
        np.testing.assert_allclose(float(aux), float(aux4), rtol=1e-6)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_transformer_loss_with_lengths(self):
        from paddle_tpu.models import transformer as T
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_capacity_factor=2.0)
        params = T.init_params(jax.random.key(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 10)), jnp.int32)
        lens = jnp.asarray([10, 7, 5, 3])
        l = T.loss(params, cfg, toks, lens)
        assert np.isfinite(float(l))
        g = jax.grad(lambda p: T.loss(p, cfg, toks, lens))(params)
        assert float(jnp.max(jnp.abs(g["blocks"][1]["moe"]["w1"]))) > 0


class TestMoELayerWrapper:
    def test_layer_protocol(self):
        from paddle_tpu import nn
        from paddle_tpu.nn.module import ShapeSpec
        layer = nn.MoE(4, 32, capacity_factor=4.0)
        params, state = layer.init(jax.random.key(0), ShapeSpec((2, 6, 8)))
        assert params["w1"].shape == (4, 8, 32)
        x = jax.random.normal(jax.random.key(1), (2, 6, 8))
        y, new_state = layer.apply(params, state, x, training=True)
        assert y.shape == x.shape
        assert np.isfinite(float(new_state["aux_loss"]))
        # shape inference without allocation
        assert layer.out_spec(ShapeSpec((2, 6, 8))).shape == (2, 6, 8)


class TestMoETrainerFlow:
    def test_trainer_with_aux_loss_weight(self):
        """The Layer-DSL user flow: Sequential with an MoE block under
        the Trainer, load-balance aux folded into the cost via
        aux_loss_weight."""
        from paddle_tpu import nn, optim
        from paddle_tpu.nn.module import ShapeSpec
        from paddle_tpu.ops import losses
        from paddle_tpu.train import events as E
        from paddle_tpu.train.trainer import Trainer

        model = nn.Sequential([
            nn.Dense(16, name="in", activation="relu"),
            nn.MoE(4, 32, capacity_factor=4.0, name="moe"),
            nn.Dense(4, name="out"),
        ])
        trainer = Trainer(
            model,
            loss_fn=lambda logits, y: jnp.mean(
                losses.softmax_cross_entropy(logits[:, 0], y)),
            optimizer=optim.adam(3e-3),
            aux_loss_weight=0.01,
        )
        state = trainer.init_state(ShapeSpec((16, 1, 8)))
        r = np.random.RandomState(0)
        xs = r.randn(4, 16, 1, 8).astype(np.float32)
        ys = r.randint(0, 4, (4, 16))

        def batches():
            for i in range(4):
                yield (jnp.asarray(xs[i]), jnp.asarray(ys[i]))

        costs = []

        def handler(ev):
            if isinstance(ev, E.EndIteration):
                costs.append(float(ev.cost))

        state = trainer.train(state, batches, num_passes=30,
                              event_handler=handler)
        assert costs[-1] < costs[0], (costs[0], costs[-1])
        # the state carries the per-call aux loss
        aux = state.model_state["moe"]["aux_loss"]
        assert np.isfinite(float(aux)) and float(aux) > 0


class TestDispatchImpls:
    def test_scatter_matches_einsum(self):
        """The linear-memory scatter/gather dispatch must be numerically
        identical to the dense einsum dispatch — including dropped
        assignments (tight capacity) and padding masks."""
        t, d, e, f = 40, 8, 4, 16
        params = _params(jax.random.key(11), e, d, f)
        x = jax.random.normal(jax.random.key(12), (t, d))
        mask = jnp.arange(t) < 36  # last 4 are padding
        for cf in (0.5, 4.0):  # with and without drops
            a = moe.moe_ffn(params, x, k=2, capacity_factor=cf,
                            token_mask=mask, dispatch_impl="einsum")
            b = moe.moe_ffn(params, x, k=2, capacity_factor=cf,
                            token_mask=mask, dispatch_impl="scatter")
            np.testing.assert_allclose(np.asarray(a.y), np.asarray(b.y),
                                       atol=1e-5)
            np.testing.assert_allclose(float(a.aux_loss),
                                       float(b.aux_loss), rtol=1e-6)
            np.testing.assert_allclose(float(a.dropped),
                                       float(b.dropped), rtol=1e-6)

    def test_grads_agree(self):
        t, d = 24, 8
        params = _params(jax.random.key(13))
        x = jax.random.normal(jax.random.key(14), (t, d))

        def loss(p, impl):
            out = moe.moe_ffn(p, x, k=2, capacity_factor=2.0,
                              dispatch_impl=impl)
            return jnp.sum(out.y ** 2) + 0.01 * out.aux_loss

        ga = jax.grad(lambda p: loss(p, "einsum"))(params)
        gb = jax.grad(lambda p: loss(p, "scatter"))(params)
        for ka in ("w1", "w2", "b1", "b2"):
            np.testing.assert_allclose(np.asarray(ga[ka]),
                                       np.asarray(gb[ka]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(ga["router"]["kernel"]),
            np.asarray(gb["router"]["kernel"]), atol=1e-5)


class TestRoutingProperties:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_invariants_random_shapes(self):
        """Property sweep: for random (T, E, cap, k, mask) the routing
        must never collide slots, never let pads claim capacity, and
        keep per-token gate mass in [0, 1]."""
        r = np.random.RandomState(0)
        for trial in range(12):
            t = int(r.randint(3, 40))
            e = int(r.choice([2, 3, 4, 8]))
            cap = int(r.randint(1, 10))
            k = int(r.randint(1, min(e, 3) + 1))
            logits = jnp.asarray(r.randn(t, e), jnp.float32)
            mask = jnp.asarray(r.rand(t) > 0.3) if trial % 2 else None
            rt = moe.top_k_routing(logits, k, cap, token_mask=mask)
            kept = np.asarray(rt.keep)
            ex = np.asarray(rt.expert)[kept]
            sl = np.asarray(rt.slot)[kept]
            # (expert, slot) pairs unique among kept assignments
            pairs = list(zip(ex.tolist(), sl.tolist()))
            assert len(pairs) == len(set(pairs)), (trial, pairs)
            assert (sl < cap).all()
            # pads never kept
            if mask is not None:
                assert not kept[:, ~np.asarray(mask)].any()
            # gate mass per token in [0, 1] (+eps)
            mass = np.asarray(jnp.sum(rt.gate, axis=0))
            assert (mass <= 1 + 1e-5).all() and (mass >= -1e-6).all()
            # dropped fraction consistent with keeps on valid tokens
            valid = np.ones(t, bool) if mask is None else np.asarray(mask)
            got_any = kept.any(axis=0)
            want = 1.0 - got_any[valid].mean() if valid.any() else 0.0
            np.testing.assert_allclose(float(rt.dropped), want, atol=1e-6)

    def test_layer_dsl_sharded_moe_step(self):
        """Layer-DSL EP: Sequential with nn.MoE under
        make_sharded_train_step with expert-dim param rules."""
        from jax.sharding import PartitionSpec as P

        from paddle_tpu import nn, optim, parallel
        from paddle_tpu.nn.module import ShapeSpec
        from paddle_tpu.ops import losses
        from paddle_tpu.train.state import TrainState

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=4),
            devices=jax.devices()[:8])
        model = nn.Sequential([
            nn.Dense(16, name="in", activation="relu"),
            nn.MoE(4, 32, capacity_factor=4.0, name="moe"),
            nn.Dense(4, name="out"),
        ])
        rules = [(r"moe/router/kernel$", P()),
                 (r"moe/(w1|b1|w2|b2)$", P(mesh_lib.MODEL_AXIS))]
        params, mstate = model.init(jax.random.key(0),
                                    ShapeSpec((16, 1, 8)))
        opt = optim.adam(1e-3)
        state = parallel.shard_train_state(
            TrainState.create(params, mstate, opt), mesh,
            param_rules=rules)
        step = parallel.make_sharded_train_step(
            model, lambda logits, y: jnp.mean(
                losses.softmax_cross_entropy(logits[:, 0], y)),
            opt, mesh, param_rules=rules)
        r = np.random.RandomState(0)
        x = jax.device_put(r.randn(16, 1, 8).astype(np.float32),
                           parallel.batch_sharding(mesh))
        y = jax.device_put(r.randint(0, 4, 16),
                           parallel.batch_sharding(mesh))
        new_state, loss, _ = step(state, jax.random.key(1), (x,), (y,))
        jax.block_until_ready(new_state.params)
        assert np.isfinite(float(loss))
        spec = new_state.params["moe"]["w1"].sharding.spec
        assert spec[0] == mesh_lib.MODEL_AXIS


class TestExpertChoice:
    def test_matches_naive(self):
        """Expert-choice: every expert's top-C tokens by router prob,
        combine weight = that prob; verify against a numpy loop."""
        t, d, e, f = 24, 8, 4, 16
        params = _params(jax.random.key(20), e, d, f)
        x = jax.random.normal(jax.random.key(21), (t, d))
        cf = 2.0
        out = moe.expert_choice_ffn(params, x, capacity_factor=cf)
        cap = moe.capacity_for(t, e, cf)

        probs = np.asarray(jax.nn.softmax(
            x @ params["router"]["kernel"], axis=-1))
        y_ref = np.zeros((t, d), np.float32)
        for ex in range(e):
            top = np.argsort(-probs[:, ex], kind="stable")[:cap]
            for i in top:
                h = np.asarray(jax.nn.gelu(
                    x[i] @ params["w1"][ex] + params["b1"][ex]))
                y_ref[i] += probs[i, ex] * np.asarray(
                    h @ params["w2"][ex] + params["b2"][ex])
        np.testing.assert_allclose(np.asarray(out.y), y_ref, atol=1e-4)
        assert float(out.aux_loss) == 0.0

    def test_perfect_balance_and_mask(self):
        t, e = 32, 4
        logits = jnp.asarray(np.random.RandomState(0).randn(t, e),
                             jnp.float32)
        mask = jnp.arange(t) < 24
        r = moe.expert_choice_routing(logits, 4, token_mask=mask)
        assert r.token_idx.shape == (e, 4)  # every slot filled
        # masked tokens can only appear with gate 0
        picked_pad = np.isin(np.asarray(r.token_idx),
                             np.arange(24, t))
        assert (np.asarray(r.gate)[picked_pad] == 0).all()

    def test_grads_flow(self):
        params = _params(jax.random.key(22))
        x = jax.random.normal(jax.random.key(23), (16, 8))

        def loss(p):
            return jnp.sum(moe.expert_choice_ffn(p, x).y ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.max(jnp.abs(g["w1"]))) > 0
        assert float(jnp.max(jnp.abs(g["router"]["kernel"]))) > 0


class TestExpertChoiceTransformer:
    def test_trains(self):
        from paddle_tpu import optim
        from paddle_tpu.models import transformer as T
        cfg = T.TransformerConfig(vocab=64, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_router="expert_choice",
                                  moe_capacity_factor=2.0)
        params = T.init_params(jax.random.key(0), cfg)
        opt = optim.adam(3e-3)
        opt_state = opt.init(params)
        base = np.random.RandomState(0).randint(0, 32, (8, 1))
        toks = jnp.asarray((base + np.arange(16)) % 32, jnp.int32)

        @jax.jit
        def step(p, o, toks, i):
            l, g = jax.value_and_grad(lambda p: T.loss(p, cfg, toks))(p)
            p, o = opt.update(g, o, p, i)
            return p, o, l

        first = last = None
        for i in range(50):
            params, opt_state, l = step(params, opt_state, toks,
                                        jnp.asarray(i))
            first = first if first is not None else float(l)
            last = float(l)
        assert last < first * 0.6, (first, last)


class TestExpertChoiceDecode:
    def test_generate_single_token_steps(self):
        """Decode runs MoE blocks with t=batch tokens per step — the
        capacity clamp must keep expert-choice viable there."""
        from paddle_tpu.models import transformer as T
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_router="expert_choice",
                                  moe_capacity_factor=2.0)
        params = T.init_params(jax.random.key(0), cfg)
        out = T.generate(params, cfg,
                         jnp.zeros((1, 3), jnp.int32), steps=4)
        assert out.shape == (1, 7)

    def test_bad_router_raises(self):
        import dataclasses as dc

        from paddle_tpu.models import transformer as T
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_router="expert-choice")
        params_cfg = dc.replace(cfg, moe_router="topk")
        params = T.init_params(jax.random.key(0), params_cfg)
        toks = jnp.zeros((2, 6), jnp.int32)
        with pytest.raises(ValueError, match="moe_router"):
            T.loss(params, cfg, toks)
