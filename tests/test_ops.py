"""Op-level tests: shapes, known values, gradient checks.

Mirrors the reference's per-op test style (reference:
python/paddle/v2/fluid/tests/op_test.py check_output/check_grad).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import activations as A
from paddle_tpu.ops import conv as C
from paddle_tpu.ops import losses as L
from paddle_tpu.ops import metrics as M
from paddle_tpu.ops import norm as N

from gradcheck import directional_grad_check


class TestActivations:
    @pytest.mark.parametrize(
        "name",
        ["sigmoid", "tanh", "relu", "brelu", "softrelu", "stanh", "abs",
         "square", "exponential", "softmax", "swish", "leaky_relu",
         "hard_sigmoid", "soft_shrink"],
    )
    def test_finite_and_shape(self, name, np_rng):
        x = jnp.asarray(np_rng.randn(4, 7), jnp.float32)
        y = A.get(name)(x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y)))

    def test_registry_unknown(self):
        with pytest.raises(ValueError):
            A.get("nope")

    def test_brelu_clips(self):
        x = jnp.asarray([-5.0, 3.0, 30.0])
        np.testing.assert_allclose(A.brelu(x), [0.0, 3.0, 24.0])

    def test_softmax_sums_to_one(self, np_rng):
        x = jnp.asarray(np_rng.randn(3, 9), jnp.float32)
        np.testing.assert_allclose(jnp.sum(A.softmax(x), -1), np.ones(3), rtol=1e-5)


class TestConv:
    def test_conv2d_shape_same(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 8, 8, 3), jnp.float32)
        k = jnp.asarray(np_rng.randn(3, 3, 3, 16) * 0.1, jnp.float32)
        y = C.conv2d(x, k, stride=2, padding="SAME")
        assert y.shape == (2, 4, 4, 16)

    def test_conv2d_identity_kernel(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        k = jnp.zeros((3, 3, 1, 1)).at[1, 1, 0, 0].set(1.0)
        y = C.conv2d(x, k, padding="SAME")
        np.testing.assert_allclose(y, x, rtol=1e-6)

    def test_depthwise(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 8, 8, 4), jnp.float32)
        k = jnp.asarray(np_rng.randn(3, 3, 1, 4) * 0.1, jnp.float32)
        y = C.depthwise_conv2d(x, k)
        assert y.shape == (2, 8, 8, 4)

    def test_max_pool(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y = C.max_pool2d(x, 2)
        np.testing.assert_allclose(y[0, :, :, 0], [[5.0, 7.0], [13.0, 15.0]])

    def test_avg_pool(self):
        x = jnp.ones((1, 4, 4, 1))
        y = C.avg_pool2d(x, 2)
        np.testing.assert_allclose(y, np.ones((1, 2, 2, 1)))

    def test_conv_grad(self, np_rng):
        x = jnp.asarray(np_rng.randn(1, 5, 5, 2), jnp.float32)
        k = jnp.asarray(np_rng.randn(3, 3, 2, 3) * 0.3, jnp.float32)
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(C.conv2d(x, p["k"]))), {"k": k}
        )

    @pytest.mark.parametrize(
        "window,stride,padding",
        [(2, 2, "VALID"), (3, 2, "SAME"), (3, 1, "SAME"), ((2, 3), (2, 1), "VALID"),
         (3, 2, 1)],
    )
    def test_max_pool_tie_split_matches_native(self, window, stride, padding,
                                               np_rng):
        # away from ties the custom VJP must equal select-and-scatter's
        x = jnp.asarray(np_rng.randn(2, 9, 11, 3), jnp.float32)
        w = jnp.asarray(np_rng.randn(
            *C.max_pool2d(x, window, stride=stride, padding=padding).shape),
            jnp.float32)

        def f(x, tie_split):
            y = C.max_pool2d(x, window, stride=stride, padding=padding,
                             tie_split=tie_split)
            return jnp.sum(y * w)

        np.testing.assert_allclose(f(x, True), f(x, False), rtol=1e-6)
        g_ts = jax.grad(lambda x: f(x, True))(x)
        g_raw = jax.grad(lambda x: f(x, False))(x)
        np.testing.assert_allclose(g_ts, g_raw, rtol=1e-5, atol=1e-6)

    def test_max_pool_tie_split_shares_gradient(self):
        # a 4-way tie gets dy/4 each (XLA native would give one element
        # 1); explicit opt-in — the DEFAULT is the native formulation
        # until the on-chip A/B clears the custom VJP (probe_pool.py)
        x = jnp.ones((1, 2, 2, 1), jnp.float32)
        g = jax.grad(lambda x: jnp.sum(
            C.max_pool2d(x, 2, tie_split=True)))(x)
        np.testing.assert_allclose(g, np.full((1, 2, 2, 1), 0.25))
        # gradient mass is conserved either way
        assert float(jnp.sum(g)) == pytest.approx(1.0)

    def test_max_pool_nan_window_stays_finite_elsewhere(self):
        # a NaN window max means cnt==0 (NaN != NaN); the guard drops
        # that window's grad instead of spreading inf/NaN around it
        x = np.random.RandomState(0).randn(1, 8, 8, 1).astype(np.float32)
        x[0, 2, 2, 0] = np.nan
        g = jax.grad(lambda x: jnp.nansum(C.max_pool2d(x, 2)))(jnp.asarray(x))
        # positions outside the NaN window keep finite gradients
        mask = np.ones((1, 8, 8, 1), bool)
        mask[0, 2:4, 2:4, 0] = False
        assert bool(jnp.all(jnp.isfinite(g[mask])))

    def test_max_pool_jvp_via_tie_split_off(self, np_rng):
        # forward-mode needs the native path (custom_vjp rejects jvp)
        x = jnp.asarray(np_rng.randn(1, 4, 4, 2), jnp.float32)
        _, t = jax.jvp(
            lambda x: C.max_pool2d(x, 2, tie_split=False), (x,), (x,))
        assert t.shape == (1, 2, 2, 2)

    def test_out_hw_explicit_asymmetric_padding(self):
        assert C.out_hw(8, 8, 3, 2, ((1, 2), (0, 1))) == (5, 4)
        # and the s2d conv accepts the nested form end-to-end
        x = jnp.asarray(np.random.RandomState(0).randn(1, 8, 8, 3),
                        jnp.float32)
        k = jnp.asarray(np.random.RandomState(1).randn(4, 4, 3, 4) * 0.2,
                        jnp.float32)
        y0 = C.conv2d(x, k, stride=2, padding=((2, 2), (2, 2)))
        y1 = C.conv2d_space_to_depth(x, k, stride=2, padding=((2, 2), (2, 2)))
        np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-4)

    def test_space_to_depth_roundtrip(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 6, 8, 5), jnp.float32)
        np.testing.assert_array_equal(
            C.depth_to_space(C.space_to_depth(x, (3, 2)), (3, 2)), x)

    @pytest.mark.parametrize(
        "hw,kernel,stride,padding",
        [(16, 7, 2, "SAME"),     # the ResNet stem shape (pad 2/3 -> blocks)
         (16, 4, 2, "VALID"),
         (15, 5, 3, "VALID"),    # block 3, kernel padded 5->6
         (16, 4, 2, "SAME"),     # pad (1,1): odd low pad -> fallback path
         (18, 3, 3, "SAME")],
    )
    def test_conv_space_to_depth_equivalence(self, hw, kernel, stride,
                                             padding, np_rng):
        x = jnp.asarray(np_rng.randn(2, hw, hw, 3), jnp.float32)
        k = jnp.asarray(np_rng.randn(kernel, kernel, 3, 8) * 0.2, jnp.float32)
        y0 = C.conv2d(x, k, stride=stride, padding=padding)
        y1 = C.conv2d_space_to_depth(x, k, stride=stride, padding=padding)
        assert y0.shape == y1.shape
        np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-4)
        # gradients agree too (wrt input and kernel)
        g0 = jax.grad(lambda x, k: jnp.sum(jnp.square(
            C.conv2d(x, k, stride=stride, padding=padding))), (0, 1))(x, k)
        g1 = jax.grad(lambda x, k: jnp.sum(jnp.square(
            C.conv2d_space_to_depth(x, k, stride=stride, padding=padding))),
            (0, 1))(x, k)
        np.testing.assert_allclose(g0[0], g1[0], rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(g0[1], g1[1], rtol=1e-3, atol=1e-3)

    def test_im2col_shape(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 6, 6, 3), jnp.float32)
        p = C.im2col(x, 3, stride=1, padding="VALID")
        assert p.shape == (2, 4, 4, 27)

    def test_roi_pool_shape(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 8, 8, 3), jnp.float32)
        rois = jnp.asarray([[0, 0, 0, 4, 4], [1, 2, 2, 7, 7]], jnp.float32)
        y = C.roi_pool(x, rois, (2, 2))
        assert y.shape == (2, 2, 2, 3)


class TestNorm:
    def test_batch_norm_train_normalizes(self, np_rng):
        x = jnp.asarray(np_rng.randn(64, 5) * 3 + 2, jnp.float32)
        y, m, v = N.batch_norm(
            x, jnp.ones(5), jnp.zeros(5), jnp.zeros(5), jnp.ones(5),
            training=True,
        )
        np.testing.assert_allclose(np.mean(np.asarray(y), 0), np.zeros(5), atol=1e-4)
        np.testing.assert_allclose(np.std(np.asarray(y), 0), np.ones(5), atol=1e-2)

    def test_batch_norm_eval_uses_running(self, np_rng):
        x = jnp.asarray(np_rng.randn(8, 3), jnp.float32)
        y, m, v = N.batch_norm(
            x, jnp.ones(3), jnp.zeros(3), jnp.zeros(3), jnp.ones(3),
            training=False, epsilon=0.0,
        )
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5)

    def test_lrn_shape(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 4, 4, 8), jnp.float32)
        y = N.lrn(x)
        assert y.shape == x.shape

    def test_layer_norm(self, np_rng):
        x = jnp.asarray(np_rng.randn(4, 6) * 5, jnp.float32)
        y = N.layer_norm(x, jnp.ones(6), jnp.zeros(6))
        np.testing.assert_allclose(np.mean(np.asarray(y), -1), np.zeros(4), atol=1e-4)


class TestLosses:
    def test_softmax_ce_matches_manual(self, np_rng):
        logits = jnp.asarray(np_rng.randn(6, 4), jnp.float32)
        labels = jnp.asarray([0, 1, 2, 3, 0, 1])
        got = L.softmax_cross_entropy(logits, labels)
        logp = np.log(np.asarray(A.softmax(logits)))
        want = -logp[np.arange(6), np.asarray(labels)]
        np.testing.assert_allclose(got, want, rtol=1e-3)

    def test_sigmoid_ce_stable(self):
        logits = jnp.asarray([1000.0, -1000.0])
        labels = jnp.asarray([1.0, 0.0])
        got = L.sigmoid_cross_entropy(logits, labels)
        assert bool(jnp.all(jnp.isfinite(got)))
        np.testing.assert_allclose(got, [0.0, 0.0], atol=1e-5)

    def test_squared_error(self):
        pred = jnp.asarray([[1.0, 2.0]])
        tgt = jnp.asarray([[0.0, 0.0]])
        np.testing.assert_allclose(L.squared_error(pred, tgt), [2.5])

    def test_huber_regression_regions(self):
        pred = jnp.asarray([[0.5], [3.0]])
        tgt = jnp.zeros((2, 1))
        got = L.huber_regression(pred, tgt, delta=1.0)
        np.testing.assert_allclose(got, [0.125, 2.5])

    def test_rank_cost_symmetry(self):
        a, b = jnp.asarray([1.0]), jnp.asarray([0.0])
        # label 1 => prefers left higher => lower cost when left > right
        c_hi = float(L.rank_cost(a, b, jnp.asarray([1.0]))[0])
        c_lo = float(L.rank_cost(b, a, jnp.asarray([1.0]))[0])
        assert c_hi < c_lo

    def test_ce_grad(self, np_rng):
        logits = jnp.asarray(np_rng.randn(5, 7), jnp.float32)
        labels = jnp.asarray(np_rng.randint(0, 7, 5))
        directional_grad_check(
            lambda p: jnp.mean(L.softmax_cross_entropy(p["x"], labels)),
            {"x": logits},
        )

    def test_cos_sim(self):
        a = jnp.asarray([[1.0, 0.0]])
        np.testing.assert_allclose(L.cos_sim(a, a), [1.0], rtol=1e-5)

    def test_lambda_rank_runs(self, np_rng):
        scores = jnp.asarray(np_rng.randn(8), jnp.float32)
        rel = jnp.asarray(np_rng.randint(0, 3, 8), jnp.float32)
        val = L.lambda_rank_segment(scores, rel)
        assert np.isfinite(float(val))


class TestMetrics:
    def test_accuracy(self):
        logits = jnp.asarray([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])
        labels = jnp.asarray([0, 1, 1])
        np.testing.assert_allclose(M.accuracy(logits, labels), 2.0 / 3.0, rtol=1e-6)

    def test_top_k(self):
        logits = jnp.asarray([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]])
        labels = jnp.asarray([1, 0])
        np.testing.assert_allclose(M.top_k_accuracy(logits, labels, k=2), 0.5)


class TestRound3LossGaps:
    """modified_huber / squared_l2 family (reference:
    operators/modified_huber_loss_op.cc, squared_l2_distance_op.cc,
    l1_norm_op.cc, squared_l2_norm_op.cc)."""

    def test_modified_huber_regions(self):
        from paddle_tpu.ops import losses

        logits = jnp.asarray([2.0, 0.5, -0.5, -2.0])
        labels = jnp.asarray([1, 1, 1, 1])
        out = np.asarray(losses.modified_huber_loss(logits, labels))
        # z = [2, .5, -.5, -2]: quadratic branch for z>=-1, linear else
        np.testing.assert_allclose(out, [0.0, 0.25, 2.25, 8.0], rtol=1e-6)
        # label 0 mirrors
        out0 = np.asarray(losses.modified_huber_loss(-logits,
                                                     jnp.zeros(4, jnp.int32)))
        np.testing.assert_allclose(out0, out, rtol=1e-6)

    def test_modified_huber_grad(self, np_rng):
        from gradcheck import directional_grad_check
        from paddle_tpu.ops import losses

        x = jnp.asarray(np_rng.randn(6), jnp.float32)
        labels = jnp.asarray(np_rng.randint(0, 2, 6))
        directional_grad_check(
            lambda p: jnp.sum(losses.modified_huber_loss(p, labels)), x)

    def test_squared_l2_family(self, np_rng):
        from paddle_tpu.ops import losses

        x = jnp.asarray(np_rng.randn(3, 4), jnp.float32)
        y = jnp.asarray(np_rng.randn(3, 4), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(losses.squared_l2_distance(x, y)),
            ((np.asarray(x) - np.asarray(y)) ** 2).sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            float(losses.l1_norm(x)), np.abs(np.asarray(x)).sum(),
            rtol=1e-5)
        np.testing.assert_allclose(
            float(losses.squared_l2_norm(x)),
            (np.asarray(x) ** 2).sum(), rtol=1e-5)


class TestTokenSampling:
    """Distribution-shape invariants for the ops.sampling per-row
    sampler (the serving engine's sampler) and the speculative
    acceptance rule."""

    def _logits(self, np_rng, n=5, v=17):
        return jnp.asarray(np_rng.randn(n, v), jnp.float32)

    def test_top_k_masks_exactly_k(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng)
        n, v = lg.shape
        for k in (1, 3, v, v + 5):
            out = S.per_row_filter_logits(
                lg, jnp.ones((n,)), jnp.full((n,), k, jnp.int32),
                jnp.ones((n,)))
            kept = np.isfinite(np.asarray(out)).sum(axis=-1)
            # gaussian logits: ties measure-zero, so exactly min(k, V)
            np.testing.assert_array_equal(kept, min(k, v))

    def test_per_row_k_varies_by_row(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng, n=4)
        ks = jnp.asarray([1, 2, 5, 17], jnp.int32)
        out = S.per_row_filter_logits(
            lg, jnp.ones((4,)), ks, jnp.ones((4,)))
        np.testing.assert_array_equal(
            np.isfinite(np.asarray(out)).sum(axis=-1), np.asarray(ks))

    def test_temperature_zero_is_greedy(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng)
        n = lg.shape[0]
        keys = jax.random.split(jax.random.key(0), n)
        toks = S.per_row_sample(lg, jnp.zeros((n,)),
                                jnp.full((n,), 17, jnp.int32),
                                jnp.ones((n,)), keys)
        np.testing.assert_array_equal(
            np.asarray(toks), np.asarray(jnp.argmax(lg, axis=-1)))

    def test_temperature_to_zero_converges_to_greedy(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng)
        n = lg.shape[0]
        keys = jax.random.split(jax.random.key(1), n)
        greedy = np.asarray(jnp.argmax(lg, axis=-1))
        for temp in (1e-2, 1e-4):
            toks = S.per_row_sample(
                lg, jnp.full((n,), temp),
                jnp.full((n,), 17, jnp.int32), jnp.ones((n,)), keys)
            np.testing.assert_array_equal(np.asarray(toks), greedy)

    def test_nucleus_keeps_argmax_and_masks_tail(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng)
        n, v = lg.shape
        out = S.per_row_filter_logits(
            lg, jnp.ones((n,)), jnp.full((n,), v, jnp.int32),
            jnp.full((n,), 1e-6, jnp.float32))
        kept = np.isfinite(np.asarray(out))
        np.testing.assert_array_equal(kept.sum(axis=-1), 1)
        assert kept[np.arange(n), np.asarray(jnp.argmax(lg, -1))].all()

    def test_seeded_determinism_and_row_independence(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg = self._logits(np_rng)
        n = lg.shape[0]
        keys = jax.random.split(jax.random.key(7), n)
        args = (jnp.ones((n,)), jnp.full((n,), 17, jnp.int32),
                jnp.ones((n,)))
        a = S.per_row_sample(lg, *args, keys)
        b = S.per_row_sample(lg, *args, keys)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # a row's draw depends only on its own key: perturbing row 0's
        # logits and key must not move the other rows
        lg2 = lg.at[0].set(-lg[0])
        keys2 = keys.at[0].set(jax.random.key(99))
        c = S.per_row_sample(lg2, *args, keys2)
        np.testing.assert_array_equal(np.asarray(a)[1:],
                                      np.asarray(c)[1:])

    def test_matches_models_filter_when_uniform(self, np_rng):
        from paddle_tpu.ops import sampling as S
        from paddle_tpu.models import transformer as T

        lg = self._logits(np_rng)
        n = lg.shape[0]
        ref = T._filter_logits(T.at_least_f32(lg), 0.7, 3, 0.9)
        out = S.per_row_filter_logits(
            lg, jnp.full((n,), 0.7, jnp.float32),
            jnp.full((n,), 3, jnp.int32),
            jnp.full((n,), 0.9, jnp.float32))
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


class TestSpecVerifyRule:
    """ngram_spec_verify: the rejection-sampling acceptance rule for
    deterministic drafts."""

    def _setup(self, np_rng, s=3, k=4, v=13):
        lg = jnp.asarray(np_rng.randn(s, k + 1, v), jnp.float32)
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        window = jnp.concatenate(
            [jnp.full((s, 1), 5, jnp.int32), greedy[:, :k]], axis=1)
        return lg, greedy, window

    def test_greedy_accepts_agreeing_prefix(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg, greedy, window = self._setup(np_rng)
        s, k = 3, 4
        v = lg.shape[-1]
        # row 1 disagrees at j=2; row 2 budget-capped at 2
        window = window.at[1, 3].set(
            (int(greedy[1, 2]) + 1) % v)
        dl = jnp.asarray([4, 4, 2], jnp.int32)
        keys = jax.random.split(jax.random.key(0), s)
        nt, na, lpd, lpn = S.ngram_spec_verify(
            lg, window, dl, jnp.zeros((s,)),
            jnp.full((s,), v, jnp.int32), jnp.ones((s,)), keys)
        np.testing.assert_array_equal(np.asarray(na), [4, 2, 2])
        # next token is the target argmax at the break position
        expect = np.asarray(jnp.take_along_axis(
            greedy, na[:, None], axis=1)[:, 0])
        np.testing.assert_array_equal(np.asarray(nt), expect)
        # logprobs follow the full-softmax rescoring convention
        full = jax.nn.log_softmax(lg, axis=-1)
        want = np.asarray(jnp.take_along_axis(
            full[:, :k], window[:, 1:, None], axis=-1)[:, :, 0])
        np.testing.assert_allclose(np.asarray(lpd), want, rtol=1e-6)

    def test_zero_draft_len_is_plain_decode(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg, greedy, window = self._setup(np_rng)
        s = 3
        v = lg.shape[-1]
        dl = jnp.zeros((s,), jnp.int32)
        keys = jax.random.split(jax.random.key(1), s)
        nt, na, _, _ = S.ngram_spec_verify(
            lg, window, dl, jnp.zeros((s,)),
            jnp.full((s,), v, jnp.int32), jnp.ones((s,)), keys)
        np.testing.assert_array_equal(np.asarray(na), 0)
        np.testing.assert_array_equal(
            np.asarray(nt), np.asarray(greedy[:, 0]))

    def test_sampled_rows_preserve_target_distribution(self, np_rng):
        """Empirical check of the Leviathan guarantee for a delta
        proposer: over many seeded trials the emitted first token's
        frequencies match the target softmax whether or not the draft
        agrees, within statistical error."""
        from paddle_tpu.ops import sampling as S

        v = 5
        lg = jnp.asarray(np_rng.randn(1, 2, v), jnp.float32)
        p = np.asarray(jax.nn.softmax(lg[0, 0] / 0.8))
        trials = 4000
        draft = int(np.argsort(p)[-2])  # a likely-but-not-top draft
        window = jnp.asarray([[3, draft]], jnp.int32)

        def one(key):
            nt, na, _, _ = S.ngram_spec_verify(
                lg, window, jnp.ones((1,), jnp.int32),
                jnp.full((1,), 0.8, jnp.float32),
                jnp.full((1,), v, jnp.int32),
                jnp.ones((1,)), key[None])
            # the round's first emitted token: the draft if accepted,
            # else the residual redraw
            return jnp.where(na[0] > 0, window[0, 1], nt[0])

        keys = jax.random.split(jax.random.key(2), trials)
        toks = np.asarray(jax.jit(jax.vmap(one))(keys))
        freq = np.bincount(toks, minlength=v) / trials
        # 4k trials: se ~ sqrt(p(1-p)/n) <= 0.008; allow 4 sigma
        np.testing.assert_allclose(freq, p, atol=0.035)

    def test_greedy_never_accepts_beyond_disagreement(self, np_rng):
        from paddle_tpu.ops import sampling as S

        lg, greedy, window = self._setup(np_rng)
        s, k = 3, 4
        v = lg.shape[-1]
        window = window.at[:, 1].set((greedy[:, 0] + 1) % v)
        keys = jax.random.split(jax.random.key(3), s)
        _, na, _, _ = S.ngram_spec_verify(
            lg, window, jnp.full((s,), k, jnp.int32), jnp.zeros((s,)),
            jnp.full((s,), v, jnp.int32), jnp.ones((s,)), keys)
        np.testing.assert_array_equal(np.asarray(na), 0)
