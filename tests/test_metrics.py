"""Evaluator tests (reference test model: gserver/tests evaluator checks +
hand-computed small cases)."""

import numpy as np
import pytest

from paddle_tpu import metrics as M


def test_classification_error_stream():
    ev = M.ClassificationErrorEvaluator()
    ev.update(np.array([0, 1, 2, 2]), np.array([0, 1, 1, 2]))
    ev.update(np.array([1, 1]), np.array([0, 1]))
    assert ev.result() == pytest.approx(2 / 6)


def test_precision_recall_binary():
    ev = M.PrecisionRecallEvaluator(num_classes=2, positive_label=1)
    # preds: tp=2, fp=1, fn=1, tn=2
    ev.update(np.array([1, 1, 1, 0, 0, 0]), np.array([1, 1, 0, 1, 0, 0]))
    r = ev.result()
    assert r["precision"] == pytest.approx(2 / 3)
    assert r["recall"] == pytest.approx(2 / 3)


def test_precision_recall_from_logits_and_macro():
    ev = M.PrecisionRecallEvaluator(num_classes=3)
    logits = np.eye(3)[[0, 1, 2, 0]] * 5.0  # preds 0,1,2,0
    labels = np.array([0, 1, 2, 1])
    ev.update(logits, labels)
    r = ev.result()
    # class0: p=1/2 r=1; class1: p=1 r=1/2; class2: p=1 r=1
    assert r["precision"] == pytest.approx((0.5 + 1 + 1) / 3)
    assert r["recall"] == pytest.approx((1 + 0.5 + 1) / 3)


def test_confusion_matrix_jax_matches_numpy():
    import jax.numpy as jnp

    pred = np.array([0, 1, 1, 2, 2, 2])
    lab = np.array([0, 1, 2, 2, 2, 0])
    cm = np.asarray(M.confusion_matrix(jnp.asarray(pred), jnp.asarray(lab), 3))
    ref = np.zeros((3, 3), int)
    np.add.at(ref, (lab, pred), 1)
    np.testing.assert_array_equal(cm, ref)
    # streamed through the evaluator via pre-reduced matrix
    ev = M.PrecisionRecallEvaluator(num_classes=3)
    ev.update(cm, None)
    assert ev._cm.sum() == 6


def test_auc_exact_on_separable():
    ev = M.AucEvaluator(num_buckets=1024)
    scores = np.array([0.9, 0.8, 0.7, 0.3, 0.2, 0.1])
    labels = np.array([1, 1, 1, 0, 0, 0])
    ev.update(scores, labels)
    assert ev.result() == pytest.approx(1.0)


def test_auc_approximates_rank_auc():
    rng = np.random.RandomState(0)
    scores = rng.rand(4000)
    labels = (scores + rng.randn(4000) * 0.3 > 0.5).astype(int)
    ev = M.AucEvaluator()
    # stream in two chunks
    ev.update(scores[:2000], labels[:2000])
    ev.update(scores[2000:], labels[2000:])
    # exact AUC by rank statistic
    pos, neg = scores[labels == 1], scores[labels == 0]
    exact = (pos[:, None] > neg[None, :]).mean() \
        + 0.5 * (pos[:, None] == neg[None, :]).mean()
    assert ev.result() == pytest.approx(exact, abs=2e-3)


def test_pnpair():
    ev = M.PnPairEvaluator()
    # query 0: pos 0.9 vs negs 0.1, 0.5 -> 2 right
    # query 1: pos 0.2 vs neg 0.8 -> 1 wrong
    ev.update(np.array([0.9, 0.1, 0.5]), np.array([1, 0, 0]), np.array([0, 0, 0]))
    ev.update(np.array([0.2, 0.8]), np.array([1, 0]), np.array([1, 1]))
    r = ev.result()
    assert r["right"] == 2 and r["wrong"] == 1
    assert r["ratio"] == pytest.approx(2 / 3)


def test_sum_and_column_sum():
    s = M.SumEvaluator()
    s.update(np.array([1.0, 2.0, 3.0]))
    s.update(np.array([4.0]))
    assert s.result() == pytest.approx(10.0)
    c = M.ColumnSumEvaluator()
    c.update(np.array([[1.0, 2.0], [3.0, 4.0]]))
    np.testing.assert_allclose(c.result(), [2.0, 3.0])


# ---- chunk evaluator ----

def _iob(b_or_i, ctype):  # IOB: tag = type*2 + (0 for B, 1 for I)
    return ctype * 2 + b_or_i


def test_extract_chunks_iob():
    O = 2  # 1 chunk type -> outside id = 2
    # B I O B -> chunks (0,0,2), (0,3,4)
    tags = [_iob(0, 0), _iob(1, 0), O, _iob(0, 0)]
    assert M.extract_chunks(tags, "IOB", 1) == [(0, 0, 2), (0, 3, 4)]
    # I at sequence start begins a chunk (untagged-begin convention)
    assert M.extract_chunks([_iob(1, 0), _iob(1, 0)], "IOB", 1) == [(0, 0, 2)]


def test_extract_chunks_ioe():
    # IOE with 1 type: I=0, E=1, outside=2 — I I E is ONE chunk
    assert M.extract_chunks([0, 0, 1], "IOE", 1) == [(0, 0, 3)]
    # E alone ends a single-token chunk; trailing I without E still flushes
    assert M.extract_chunks([1, 2, 0, 0], "IOE", 1) == [(0, 0, 1), (0, 2, 4)]


def test_extract_chunks_iobes():
    # IOBES with 1 type: B=0 I=1 E=2 S=3, outside=4
    assert M.extract_chunks([3, 4, 0, 1, 2], "IOBES", 1) == [(0, 0, 1), (0, 2, 5)]


def test_extract_chunks_plain():
    # plain: runs of same type; outside id = num_types
    assert M.extract_chunks([0, 0, 1, 2, 1], "plain", 2) == [
        (0, 0, 2), (1, 2, 3), (1, 4, 5)]


def test_chunk_f1_stream():
    ev = M.ChunkEvaluator(scheme="IOB", num_chunk_types=1)
    O = 2
    label = np.array([[0, 1, O, 0, O]])
    pred = np.array([[0, 1, O, O, O]])  # finds 1 of 2 chunks, outputs 1
    ev.update(pred, label)
    r = ev.result()
    assert r["precision"] == pytest.approx(1.0)
    assert r["recall"] == pytest.approx(0.5)
    assert r["f1"] == pytest.approx(2 / 3)


# ---- edit distance / CTC ----

def test_edit_distance():
    assert M.edit_distance([1, 2, 3], [1, 2, 3]) == 0
    assert M.edit_distance([1, 2, 3], [1, 3]) == 1
    assert M.edit_distance([], [1, 2]) == 2
    assert M.edit_distance([1, 2], [2, 1]) == 2
    assert M.edit_distance([1, 2, 3, 4], [1, 9, 3]) == 2


def test_ctc_greedy_decode():
    assert M.ctc_greedy_decode([0, 1, 1, 0, 2, 2, 2, 0, 1]) == [1, 2, 1]
    assert M.ctc_greedy_decode([0, 0, 0]) == []


def test_ctc_error_evaluator():
    ev = M.CTCErrorEvaluator(blank=0)
    # frames decode to [1,2,1]; label [1,2,1] -> 0 errors
    ev.update(np.array([[0, 1, 1, 0, 2, 2, 0, 1]]), np.array([[1, 2, 1]]))
    # frames decode to [3]; label [3,4] -> dist 1, len 2
    ev.update(np.array([[3, 3, 0, 0, 0, 0, 0, 0]]), np.array([[3, 4, 0]]))
    r = ev.result()
    assert r["error_rate"] == pytest.approx(1 / 5)
    assert r["seq_error_rate"] == pytest.approx(1 / 2)


# ---- detection mAP ----

def test_detection_map_perfect():
    ev = M.DetectionMAPEvaluator()
    gt = np.array([[1, 0, 0, 10, 10], [2, 20, 20, 30, 30]])
    det = np.array([
        [1, 0.9, 0, 0, 10, 10],
        [2, 0.8, 20, 20, 30, 30],
    ])
    ev.update(det, gt)
    assert ev.result()["mAP"] == pytest.approx(1.0)


def test_detection_map_with_fp_and_miss():
    ev = M.DetectionMAPEvaluator(ap_type="integral")
    gt = np.array([[1, 0, 0, 10, 10], [1, 50, 50, 60, 60]])
    det = np.array([
        [1, 0.9, 0, 0, 10, 10],     # tp
        [1, 0.8, 100, 100, 110, 110],  # fp
    ])
    ev.update(det, gt)
    # recall reaches 0.5 with precision 1 -> integral AP = 0.5
    assert ev.result()["mAP"] == pytest.approx(0.5)


def test_combined_evaluator():
    a = M.ClassificationErrorEvaluator()
    b = M.PrecisionRecallEvaluator(num_classes=2, positive_label=1)
    comb = M.CombinedEvaluator([a, b])
    comb.update(np.array([1, 0]), np.array([1, 1]))
    r = comb.result()
    assert r["classification_error"] == pytest.approx(0.5)
    assert r["precision_recall"]["recall"] == pytest.approx(0.5)
    comb.reset()
    assert a.result() == 0.0


def test_trainer_evaluate_with_evaluators():
    import jax
    from paddle_tpu import nn, optim
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.trainer import Trainer
    import jax.numpy as jnp

    model = nn.Sequential([nn.Dense(8, name="fc", activation="relu"),
                           nn.Dense(3, name="out")])
    tr = Trainer(model, lambda o, y: jnp.mean(losses.softmax_cross_entropy(o, y)),
                 optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    rng = np.random.RandomState(0)
    batches = [(rng.rand(4, 5).astype(np.float32),
                rng.randint(0, 3, 4)) for _ in range(3)]
    ev = M.ClassificationErrorEvaluator()
    res = tr.evaluate(state, lambda: iter(batches), evaluators=[ev])
    assert "classification_error" in res.metrics
    assert 0.0 <= res.metrics["classification_error"] <= 1.0
