"""Multi-host control-plane suite (cluster.lease / cluster.membership
/ cluster.agent + the membership-resolved topology paths).

Fast cases drive the in-process `MembershipService` state machine
under a ManualClock — lease-vs-renew races, epoch fencing, batch
eviction, watch semantics, standby failover — plus the topology
resolvers (`shard_specs_from_view`, `FleetSupervisor` membership
mode, the gang supervisor's membership mirror) with no processes and
no jax. The real-process cases boot per-host agents (idle replicas,
millisecond boots) for the lifecycle and orphan-CHAIN tests, and the
one `heavyweight` chaos test is the acceptance bar: 3 agents with
distinct fake host-ids serving a mid-flight burst, one SIGKILLed —
lease expiry on the injectable clock, one epoch bump, reform at 2
hosts, exactly-once outcomes, counters reconciling, zero orphans,
and the resurrected agent's stale-epoch writes refused. Like the
elastic suite's real-process chaos cases it is heavyweight AND slow
(three replica-process boots don't fit the tier-1 wall clock);
`-m cluster` / `scripts/fault_smoke.sh cluster` runs it.
"""

import os
import signal
import time

import numpy as np
import pytest

import jax

from paddle_tpu.cluster.agent import (EXIT_EVICTED, AgentProcess,
                                      AgentSpec)
from paddle_tpu.cluster.lease import LeaseTable
from paddle_tpu.cluster.membership import (ClusterView,
                                           MembershipClient,
                                           MembershipServer,
                                           MembershipService,
                                           StandbyLink)
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.pserver_client import (PServerClient,
                                                shard_specs_from_view)
from paddle_tpu.serve.fleet import FleetSupervisor, ReplicaSpec
from paddle_tpu.testing.faults import FaultPlan, ManualClock
from paddle_tpu.testing.fleet import TINY, _IdleServer, save_tiny_artifact

pytestmark = [pytest.mark.cluster, pytest.mark.faults]

CFG = T.TransformerConfig(**TINY)

CHILD_ENV = {"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}

IDLE_SPEC = ReplicaSpec(builder="paddle_tpu.testing.fleet:idle_server")


def _proc_gone(pid):
    """True when `pid` is dead (missing or a zombie awaiting reap)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return True
    return state == "Z"


def _await(cond, timeout_s=20.0, poll_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return cond()


# ---------------------------------------------------------------------------
# the shared lease table


def test_lease_renew_honors_registered_ttl():
    """The consumer contract satellite 1 unified: a renewal re-arms
    with the ttl the holder REGISTERED with, not the table default —
    a short-lease holder dies with its short lease."""
    clock = ManualClock()
    table = LeaseTable(default_ttl_s=30.0, clock=clock)
    lease = table.grant("short", ttl_s=5.0)
    clock.advance(3.0)
    assert table.renew("short", lease.token)
    assert table.remaining("short") == pytest.approx(5.0)
    # an explicit ttl override re-arms with the NEW interval from now on
    assert table.renew("short", lease.token, ttl_s=2.0)
    assert table.remaining("short") == pytest.approx(2.0)
    clock.advance(1.0)
    assert table.renew("short")
    assert table.remaining("short") == pytest.approx(2.0)


def test_lease_expiry_vs_renew_race_breaks_toward_eviction():
    """`now >= deadline` refuses the renewal: a holder renewing
    exactly AT its deadline had zero margin, and zero margin is one
    scheduler hiccup from split-brain. Just-in-time (any positive
    margin) still wins."""
    clock = ManualClock()
    table = LeaseTable(default_ttl_s=10.0, clock=clock)
    lease = table.grant("h")
    clock.advance(9.999)
    assert table.renew("h", lease.token)          # margin > 0: lives
    clock.advance(10.0)                           # exactly at deadline
    assert not table.renew("h", lease.token)
    assert table.stats["refused_renewals"] == 1
    assert table.expire() == ["h"]
    assert not table.renew("h", lease.token)      # gone is gone


def test_lease_tokens_are_incarnations():
    """A re-grant is a NEW incarnation: fresh (strictly larger)
    token, and the old token stops renewing immediately — a zombie
    can never pass for its replacement. `install` keeps the local
    counter ahead of replicated tokens."""
    clock = ManualClock()
    table = LeaseTable(default_ttl_s=10.0, clock=clock)
    first = table.grant("h")
    second = table.grant("h")
    assert second.token > first.token
    assert not table.renew("h", first.token)
    assert table.renew("h", second.token)
    table.install("repl", token=100, ttl_s=5.0)
    assert table.grant("later").token > 100
    assert table.alive("repl", 100) and not table.alive("repl", 99)


# ---------------------------------------------------------------------------
# membership: epochs, fencing, eviction, watch


def _svc(ttl=10.0):
    clock = ManualClock()
    return MembershipService(default_ttl_s=ttl, clock=clock), clock


def test_epoch_bumps_on_every_view_change_and_only_those():
    svc, _ = _svc()
    a = svc.register("host-a", {"replicas": [["127.0.0.1", 1]]})
    assert (a["status"], a["epoch"]) == ("ok", 1)
    b = svc.register("host-b")
    assert b["epoch"] == 2
    # a renew is NOT a view change
    assert svc.renew("host-a", a["token"], a["epoch"])["status"] == "ok"
    assert svc.epoch == 2
    # an inventory report IS (consumers resolve endpoints from it)
    r = svc.report("host-a", a["token"], 2,
                   {"replicas": [["127.0.0.1", 9]]})
    assert (r["status"], r["epoch"]) == ("ok", 3)
    assert svc.view().hosts["host-a"]["replicas"] == [["127.0.0.1", 9]]
    # so is a graceful leave
    assert svc.deregister("host-b", b["token"], 3)["epoch"] == 4
    assert "host-b" not in svc.view().hosts


def test_batch_eviction_is_one_view_change():
    """Three hosts expiring together are ONE epoch bump: survivors
    see one new world, not N intermediate ones."""
    svc, clock = _svc(ttl=5.0)
    for i in range(3):
        svc.register(f"host-{i}")
    epoch = svc.epoch
    clock.advance(6.0)
    assert sorted(svc.tick()) == ["host-0", "host-1", "host-2"]
    assert svc.epoch == epoch + 1
    assert svc.view().hosts == {}
    assert svc.tick() == []                       # idempotent
    assert svc.counters()["evictions"] == 3


def test_stale_epoch_fence_refuses_a_resurrected_agent():
    """The acceptance fence: creds from before an eviction are
    refused with `stale_epoch` — before AND after the host
    re-registers — and `register` is the one unfenced way back in."""
    svc, clock = _svc(ttl=5.0)
    reg = svc.register("host-a", {"replicas": [["127.0.0.1", 1]]})
    token, epoch = reg["token"], reg["epoch"]
    svc.register("host-b")                        # the world moves on
    clock.advance(6.0)
    assert svc.tick() == ["host-a", "host-b"]
    # the paused agent wakes up and replays its old stamps
    assert svc.renew("host-a", token, epoch)["status"] == "stale_epoch"
    assert svc.report("host-a", token, epoch,
                      {"replicas": []})["status"] == "stale_epoch"
    # a write stamped with a FUTURE epoch is equally stale
    assert svc.renew("host-a", token,
                     svc.epoch + 7)["status"] == "stale_epoch"
    # re-entry is a visible join: new token, new epoch, view change
    reg2 = svc.register("host-a")
    assert reg2["token"] > token and reg2["epoch"] > epoch
    # ... and the OLD incarnation still cannot write to the new world
    assert svc.report("host-a", token, epoch,
                      {"replicas": []})["status"] == "stale_epoch"
    assert svc.renew("host-a", reg2["token"],
                     reg2["epoch"])["status"] == "ok"
    assert svc.counters()["refused_stale_epoch"] >= 4
    # an unknown host (never evicted) is `expired`, not stale: it
    # simply needs to register first
    assert svc.renew("host-zz", 1, svc.epoch)["status"] == "expired"


def test_wait_view_delivers_exactly_one_view_per_epoch():
    svc, _ = _svc()
    svc.register("host-a")
    svc.register("host-b")
    svc.register("host-c")
    seen = []
    cursor = 0
    while True:
        v = svc.wait_view(cursor, timeout_s=0.05)
        if v is None:
            break
        seen.append((v.epoch, sorted(v.hosts)))
        cursor = v.epoch
    assert seen == [(1, ["host-a"]),
                    (2, ["host-a", "host-b"]),
                    (3, ["host-a", "host-b", "host-c"])]
    # a change arriving while parked wakes the watcher with that view
    import threading
    got = []
    t = threading.Thread(
        target=lambda: got.append(svc.wait_view(3, timeout_s=10.0)))
    t.start()
    svc.register("host-d")
    t.join(10.0)
    assert got and got[0].epoch == 4 and "host-d" in got[0].hosts


def test_lease_margins_track_the_manual_clock():
    svc, clock = _svc(ttl=10.0)
    reg = svc.register("host-a")
    svc.register("host-b")
    clock.advance(8.0)
    assert svc.renew("host-a", reg["token"],
                     reg["epoch"])["status"] == "ok"
    margins = svc.lease_margins()
    assert margins["host-a"] == pytest.approx(10.0)
    assert margins["host-b"] == pytest.approx(2.0)
    clock.advance(4.0)
    assert svc.lease_margins()["host-b"] == pytest.approx(-2.0)
    assert svc.tick() == ["host-b"]


# ---------------------------------------------------------------------------
# replication: log shipping + explicit failover


def test_standby_failover_resumes_the_epoch_sequence():
    """The pserver chain idiom on the control plane: every view
    change ships to the warm standby; promote() is the explicit
    failover — it resumes the epoch sequence past the primary's
    last, and hosts keep their tokens (one renew against the new
    primary and they are current again)."""
    clock = ManualClock()
    primary = MembershipService(default_ttl_s=10.0, clock=clock)
    standby = MembershipService(default_ttl_s=10.0, clock=clock,
                                primary=False)
    sserver = MembershipServer(standby).start()
    try:
        primary.attach_standby(StandbyLink(sserver.addr, clock=clock))
        reg_a = primary.register("host-a",
                                 {"replicas": [["127.0.0.1", 1]]})
        reg_b = primary.register("host-b")
        primary.report("host-a", reg_a["token"], primary.epoch,
                       {"replicas": [["127.0.0.1", 2]]})
        clock.advance(6.0)
        primary.renew("host-b", reg_b["token"], primary.epoch)
        # the standby mirrors state AND epoch through the log alone
        assert standby.epoch == primary.epoch == 3
        assert standby.hosts["host-a"]["token"] == reg_a["token"]
        assert (standby.view().hosts["host-a"]["replicas"]
                == [["127.0.0.1", 2]])
        # primary dies; failover is explicit and IS a view change
        promoted = standby.promote()
        assert promoted["epoch"] == 4 and standby.is_primary
        # host-b renewed at t=6 on the primary; the standby re-armed
        # every lease at promote, so its OLD token renews fine here
        assert standby.renew("host-b", reg_b["token"],
                             4)["status"] == "ok"
        # and the sequence continues past the old primary's epochs
        assert standby.register("host-c")["epoch"] == 5
        assert standby.counters()["failovers"] == 1
    finally:
        sserver.shutdown()


def test_standby_refuses_a_seq_gap_and_primary_survives_link_loss():
    standby = MembershipService(default_ttl_s=10.0, primary=False)
    assert standby.apply_entry(
        {"seq": 1, "kind": "register", "epoch": 1,
         "args": {"host_id": "h", "token": 1, "ttl_s": 5.0,
                  "inventory": {}, "joined_epoch": 1}})["status"] == "ok"
    # seq 3 over a missing 2: refuse, never apply over the hole
    assert standby.apply_entry(
        {"seq": 3, "kind": "evict", "epoch": 2,
         "args": {"hosts": ["h"]}})["status"] == "need_resync"
    assert "h" in standby.hosts                   # nothing applied
    # a dup of an old record is acknowledged and ignored
    assert standby.apply_entry(
        {"seq": 1, "kind": "register", "epoch": 1,
         "args": {"host_id": "h", "token": 9, "ttl_s": 5.0,
                  "inventory": {}, "joined_epoch": 1}})["status"] == "ok"
    assert standby.hosts["h"]["token"] == 1
    # primary side: a dead standby link NEVER blocks mutations
    clock = ManualClock()
    primary = MembershipService(default_ttl_s=10.0, clock=clock)
    dead = MembershipServer(MembershipService(primary=False))
    addr = dead.addr
    dead.shutdown()                               # nothing listens
    primary.attach_standby(StandbyLink(addr, clock=clock, timeout=0.5))
    assert primary.register("host-a")["status"] == "ok"
    assert primary.epoch == 1
    assert primary.counters()["ship_failures"] >= 1


# ---------------------------------------------------------------------------
# the socket layer


def test_membership_server_roundtrip_and_fence_over_the_wire():
    clock = ManualClock()
    svc = MembershipService(default_ttl_s=10.0, clock=clock)
    server = MembershipServer(svc).start()
    try:
        client = MembershipClient(server.addr)
        assert client.ping()["is_primary"] == 1
        reg = client.register("host-a",
                              {"replicas": [["127.0.0.1", 7070]]},
                              ttl_s=5.0)
        assert reg["status"] == "ok" and reg["ttl_s"] == 5.0
        view = client.view()
        assert view.endpoints("replicas") == [
            ("host-a", ("127.0.0.1", 7070))]
        assert client.renew("host-a", reg["token"],
                            reg["epoch"])["status"] == "ok"
        got = client.wait_view(0, timeout_s=1.0)
        assert got is not None and got.epoch == 1
        assert client.wait_view(view.epoch, timeout_s=0.05) is None
        clock.advance(6.0)
        assert client.lease_margins()["host-a"] < 0
        assert client.tick() == ["host-a"]
        # the fence refuses the evicted creds through the same wire
        assert client.renew("host-a", reg["token"],
                            reg["epoch"])["status"] == "stale_epoch"
        assert client.counters()["refused_stale_epoch"] == 1
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# topology resolution: pserver client + fleet supervisor + gang mirror


class _FakeMembership:
    def __init__(self, view):
        self.v = view

    def view(self):
        return self.v


def test_shard_specs_from_view_merges_roles_and_rejects_stale_rows():
    view = ClusterView(epoch=3, hosts={
        "h0": {"shards": [{"shard_id": 0, "row_lo": 0, "row_hi": 8,
                           "endpoints": [["127.0.0.1", 9001]],
                           "role": "primary"}]},
        "h1": {"shards": [{"shard_id": 0, "row_lo": 0, "row_hi": 8,
                           "endpoints": [["127.0.0.1", 9002]],
                           "role": "backup"},
                          {"shard_id": 1, "row_lo": 8, "row_hi": 16,
                           "endpoints": [["127.0.0.1", 9003]]}]},
    })
    specs = shard_specs_from_view(view)
    assert [(s.shard_id, s.row_lo, s.row_hi) for s in specs] == [
        (0, 0, 8), (1, 8, 16)]
    # primaries head the failover chain, backups follow
    assert specs[0].endpoints == [("127.0.0.1", 9001),
                                  ("127.0.0.1", 9002)]
    view.hosts["h1"]["shards"][0]["row_hi"] = 12    # stale inventory
    with pytest.raises(ValueError, match="stale"):
        shard_specs_from_view(view)


def test_pserver_client_resolves_and_refreshes_from_membership():
    """The multi-host pserver path: no hardcoded endpoint list — the
    client builds from the view and re-points failover chains on a
    view change; a changed shard LAYOUT demands a rebuild."""
    v1 = ClusterView(epoch=1, hosts={
        "h0": {"shards": [{"shard_id": 0, "row_lo": 0, "row_hi": 8,
                           "endpoints": [["127.0.0.1", 9001]]}]}})
    mem = _FakeMembership(v1)
    client = PServerClient.from_membership(mem, dim=4)
    assert client.num_rows == 8
    assert client.refresh_topology() is False     # same view: no-op
    mem.v = ClusterView(epoch=2, hosts={
        "h1": {"shards": [{"shard_id": 0, "row_lo": 0, "row_hi": 8,
                           "endpoints": [["127.0.0.1", 9002]]}]}})
    assert client.refresh_topology() is True
    assert client._conns[0].endpoints == [("127.0.0.1", 9002)]
    mem.v = ClusterView(epoch=3, hosts={
        "h1": {"shards": [{"shard_id": 0, "row_lo": 0, "row_hi": 16,
                           "endpoints": [["127.0.0.1", 9002]]}]}})
    with pytest.raises(ValueError, match="layout"):
        client.refresh_topology()
    with pytest.raises(RuntimeError, match="from_membership"):
        PServerClient(shard_specs_from_view(v1), dim=4).refresh_topology()


def test_fleet_supervisor_resolves_roster_from_membership_view():
    """FleetSupervisor membership mode, no processes: the roster
    comes from the view; a host joining is a replica add on the next
    sweep, a lease expiry is `declare_dead` (the router's crash path,
    exactly-once machinery intact) BEFORE any socket error could
    fire; local autoscaling is disabled (capacity is agent-owned)."""
    clock = ManualClock()
    svc = MembershipService(default_ttl_s=10.0, clock=clock)
    reg_a = svc.register("host-a", {"replicas": [["127.0.0.1", 1111]]})
    svc.register("host-b", {"replicas": [["127.0.0.1", 2222]]})
    sup = FleetSupervisor(IDLE_SPEC, min_replicas=1, max_replicas=4,
                          membership=svc, clock=clock)
    sup._wrap_addr = lambda addr: _IdleServer()   # no sockets in-proc
    sup.start()
    assert len(sup.router.replicas) == 2
    assert sup.counters()["hosts_live"] == 2
    assert sup.counters()["membership_epoch"] == 2
    # capacity is the agents' business now
    with pytest.raises(RuntimeError, match="agent-owned"):
        sup.scale_out()
    with pytest.raises(RuntimeError, match="agent-owned"):
        sup.scale_in()
    # a host joins: the very next sweep folds it in
    reg_c = svc.register("host-c", {"replicas": [["127.0.0.1", 3333]]})
    sup.sweep()
    assert sup.stats["replicas_joined"] == 1
    assert len(sup.router.replicas) == 3
    assert sup.procs[2] is None                   # agent-owned: no proc
    # host-b goes silent; a+c keep renewing across the jump
    clock.advance(6.0)
    svc.renew("host-a", reg_a["token"], svc.epoch)
    svc.renew("host-c", reg_c["token"], svc.epoch)
    clock.advance(5.0)                            # b past deadline
    sup.sweep()
    assert sup.stats["hosts_lost"] == 1
    assert sup.stats["view_changes"] == 2         # join + eviction
    assert sup.router.counters()["replicas_lost"] == 1
    assert sup.counters()["hosts_live"] == 2
    assert sup.counters()["replicas_routable"] == 2
    # an empty view refuses to start a fleet at all
    empty = MembershipService(default_ttl_s=10.0, clock=clock)
    with pytest.raises(RuntimeError, match="no replica endpoints"):
        FleetSupervisor(IDLE_SPEC, membership=empty).start()


def test_gang_supervisor_membership_mirror(tmp_path):
    """The gang's fake hosts `{prefix}-{rank}`: registration carries
    rank inventory, observed progress renews, an eviction surfaces as
    a LOST member from the view, and teardown deregisters."""
    from paddle_tpu.parallel.launch import GangSupervisor

    clock = ManualClock()
    svc = MembershipService(default_ttl_s=5.0, clock=clock)
    sup = GangSupervisor(
        "paddle_tpu.testing.fleet:idle_server",
        workdir=str(tmp_path / "w"), checkpoint_dir=str(tmp_path / "c"),
        num_processes=2, total_steps=1, heartbeat_timeout_s=5.0,
        membership=svc, host_prefix="gang")
    sup._membership_register(2, "file:///unused")
    assert sorted(svc.view().hosts) == ["gang-0", "gang-1"]
    assert svc.view().hosts["gang-0"]["rank"] == 0
    assert sup._membership_lost([0, 1]) == []
    # rank 0 progresses (renews); rank 1 goes silent past the ttl
    clock.advance(3.0)
    sup._membership_renew(0)
    clock.advance(3.0)
    assert sup._membership_lost([0, 1]) == [1]
    assert sorted(svc.view().hosts) == ["gang-0"]
    # the mirror's whole point: the eviction becomes a reform trigger
    sup.membership_evictions += 1
    assert sup.counters()["membership_evictions"] == 1
    sup._membership_deregister()
    assert svc.view().hosts == {} and sup._member_creds == {}


# ---------------------------------------------------------------------------
# real processes: agent lifecycle, fencing, the orphan chain


def test_agent_registers_renews_and_fences_on_eviction():
    """One real agent (idle replica, millisecond boot) against a
    real-clock membership server: it registers its inventory, its
    renew loop keeps the lease margin positive, and when a SECOND
    incarnation of its host registers (killing its token), the next
    renew comes back refused and the agent executes fenced teardown:
    replicas SIGKILLed, exit code EXIT_EVICTED."""
    svc = MembershipService(default_ttl_s=30.0)
    server = MembershipServer(svc).start()
    agent = AgentProcess(AgentSpec(
        host_id="host-0", replica_spec=IDLE_SPEC,
        membership_addr=server.addr, ttl_s=2.0,
        renew_interval_s=0.05))
    try:
        info = agent.start().wait_ready(60.0)
        assert info["host_id"] == "host-0" and info["token"] is not None
        assert len(info["replicas"]) == 1 and len(info["pids"]) == 1
        view = svc.view()
        assert view.hosts["host-0"]["replicas"] == info["replicas"]
        # the renew loop holds the margin up (real clocks here)
        assert _await(lambda: svc.counters()["renews"] >= 2, 10.0)
        assert svc.lease_margins()["host-0"] > 0
        # a new incarnation registers: the old agent is now a zombie
        svc.register("host-0", {"replicas": []})
        agent.proc.join(15.0)
        assert agent.exitcode() == EXIT_EVICTED
        assert _await(lambda: all(_proc_gone(p) for p in info["pids"]))
    finally:
        agent.reap()
        server.shutdown()


def test_supervisor_sigkill_takes_down_the_whole_agent_tree():
    """The orphan-CHAIN regression (satellite: the PR14 watchdog,
    chained through the agent tier): SIGKILL the SUPERVISOR — no
    drain, no atexit — and the agents exit on their pipe EOF, then
    the replica GRANDCHILDREN exit on theirs. Three levels deep,
    zero survivors."""
    import multiprocessing
    from paddle_tpu.testing.fleet import orphan_cluster_main

    ctx = multiprocessing.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    sup_proc = ctx.Process(target=orphan_cluster_main,
                           args=(child_conn,))
    sup_proc.start()
    child_conn.close()
    assert parent_conn.poll(60.0), "supervisor never reported pids"
    pids = parent_conn.recv()
    assert len(pids) == 4                   # 2 agents + 2 grandchildren
    assert all(not _proc_gone(pid) for pid in pids)
    os.kill(sup_proc.pid, signal.SIGKILL)   # no cleanup runs
    sup_proc.join(10.0)
    assert _await(lambda: all(_proc_gone(p) for p in pids)), \
        f"agent-tree processes survive their supervisor: {pids}"
    parent_conn.close()


# ---------------------------------------------------------------------------
# THE acceptance chaos test


def _ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


@pytest.mark.heavyweight
@pytest.mark.slow
def test_agent_sigkill_mid_burst_reforms_at_two_hosts(tmp_path):
    """The multi-host acceptance bar: 3 agent processes with distinct
    fake host-ids, each owning one real replica child, topology
    resolved through membership (no endpoint list touches the
    supervisor). One agent is SIGKILLed mid-burst; its lease expires
    on the injectable clock, the epoch bumps ONCE, and the fleet
    reforms at 2 hosts from the VIEW CHANGE — exactly-once outcomes,
    greedy parity, counters reconciling across both process
    boundaries, zero orphans, and the dead host's resurrected
    credentials refused with `stale_epoch`."""
    art = str(tmp_path / "engine.tar")
    save_tiny_artifact(art, buckets=(16,))
    rspec = ReplicaSpec(
        builder="paddle_tpu.testing.fleet:build_tiny_server",
        kwargs=dict(artifact=art, buckets=(16,), max_retries=1),
        env=dict(CHILD_ENV))
    clock = ManualClock()
    svc = MembershipService(default_ttl_s=30.0, clock=clock)
    server = MembershipServer(svc).start()
    agents = {}
    infos = {}
    sup = None
    try:
        for i in range(3):
            host = f"host-{i}"
            agents[host] = AgentProcess(AgentSpec(
                host_id=host, replica_spec=rspec,
                membership_addr=server.addr, ttl_s=5.0,
                renew_interval_s=0.05)).start()
        infos = {h: a.wait_ready(180.0) for h, a in agents.items()}
        assert svc.epoch == 3 and len(svc.view().hosts) == 3
        sup = FleetSupervisor(
            rspec, min_replicas=1, max_replicas=3,
            membership=MembershipClient(server.addr))
        sup.start()
        assert len(sup.router.replicas) == 3
        plan = FaultPlan(cluster_sigkill_at=6,
                         cluster_sigkill_host="host-1")
        plan.wrap_cluster(sup, agents, clock=clock, service=svc)

        params = T.init_params(jax.random.key(0), CFG)
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, CFG.vocab, (4 + i % 5,))
                   .astype(np.int32) for i in range(9)]
        rids = [sup.submit(p, max_new=4) for p in prompts]
        res = sup.run()
        sup.reconcile()                       # the exactly-once audit

        assert plan.count("agentkill") == 1
        c = sup.router.counters()
        # the death arrived as a VIEW CHANGE: one host evicted, its
        # replica declared dead, work redistributed
        assert c["replicas_lost"] == 1
        assert c["redistributed"] >= 1
        # exactly one terminal outcome per request, all completed
        assert sorted(res) == sorted(rids)
        assert all(res[i].outcome == "completed" for i in rids)
        assert all(r.retries == 0 for r in res.values())
        assert c["completed"] == len(rids) == c["fleet_completed"]
        assert c["fleet_shed"] == 0 and c["fleet_failed"] == 0
        # bit-exact greedy parity with the solo decode
        for p, rid in zip(prompts, rids):
            assert res[rid].tokens == _ref_tokens(params, p, 4)
        # the fleet reformed at the surviving-host count
        assert sup.counters()["hosts_live"] == 2
        assert sup.stats["hosts_lost"] == 1
        assert sup.counters()["replicas_routable"] == 2
        # membership counters reconcile: one eviction, one epoch bump
        # for it, survivors' leases healthy
        mc = svc.counters()
        assert mc["evictions"] == 1 and mc["hosts_live"] == 2
        # the supervisor folded at least the eviction's view change
        # (agents keep REPORTING after the burst, so the service
        # epoch may run ahead of the last sweep's)
        assert (mc["epoch"] >= sup.counters()["membership_epoch"]
                >= svc.evicted_at["host-1"])
        margins = svc.lease_margins()
        assert all(margins[h] > 0 for h in ("host-0", "host-2"))
        # zero orphans: the dead agent AND its replica children are
        # gone (watchdog chain, nothing graceful ran)
        victim = infos["host-1"]
        assert _await(lambda: _proc_gone(agents["host-1"].pid))
        assert _await(lambda: all(_proc_gone(p)
                                  for p in victim["pids"]))
        # the resurrected agent's stamps are REFUSED: its world ended
        client = MembershipClient(server.addr)
        replay = client.report("host-1", victim["token"],
                               victim["epoch"],
                               {"replicas": victim["replicas"]})
        assert replay["status"] == "stale_epoch"
        assert "host-1" not in client.view().hosts
    finally:
        if sup is not None:
            sup.shutdown(drain=False)
        for a in agents.values():
            a.stop()
        server.shutdown()
    leaked = [p for h, info in infos.items()
              for p in info["pids"] + [agents[h].pid]
              if not _proc_gone(p)]
    assert not leaked, f"cluster processes outlived shutdown: {leaked}"
