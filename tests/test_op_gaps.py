"""Round-2 op-surface fills: sequence conv / context projection,
block expand, PReLU, interpolation, rotate + the one-line nn wrappers
(reference tests mirrored: gserver/tests/test_LayerGrad.cpp entries for
context_projection/seq conv/blockExpand/prelu/bilinear_interp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gradcheck import directional_grad_check
from paddle_tpu import nn
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import sequence as seq_ops


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)


class TestContextProjection:
    def test_values_centered_window(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 5, 3), jnp.float32)
        lengths = jnp.asarray([5, 3])
        out = seq_ops.context_projection(x, lengths, context_len=3)
        assert out.shape == (2, 5, 9)
        # middle position of seq 0: window [t-1, t, t+1]
        np.testing.assert_allclose(
            np.asarray(out[0, 2]),
            np.concatenate([np.asarray(x[0, 1]), np.asarray(x[0, 2]),
                            np.asarray(x[0, 3])]), rtol=1e-6)
        # first position: left context is zero-padded
        np.testing.assert_allclose(np.asarray(out[0, 0, :3]), 0.0)
        # sequence 1 (len 3): position 2's right context is beyond end
        np.testing.assert_allclose(np.asarray(out[1, 2, 6:]), 0.0)
        # rows past the sequence end are fully zero
        np.testing.assert_allclose(np.asarray(out[1, 3]), 0.0)
        np.testing.assert_allclose(np.asarray(out[1, 4]), 0.0)

    def test_trainable_padding_rows(self, np_rng):
        x = jnp.asarray(np_rng.randn(1, 4, 2), jnp.float32)
        lengths = jnp.asarray([4])
        pads = jnp.asarray(np_rng.randn(2, 2), jnp.float32)  # 1 start, 1 end
        out = seq_ops.context_projection(
            x, lengths, context_len=3, context_start=-1,
            padding_weights=pads)
        # position 0's left slot uses start-pad row 0
        np.testing.assert_allclose(np.asarray(out[0, 0, :2]),
                                   np.asarray(pads[0]), rtol=1e-6)
        # last position's right slot uses end-pad row 0
        np.testing.assert_allclose(np.asarray(out[0, 3, 4:]),
                                   np.asarray(pads[1]), rtol=1e-6)

    def test_grad(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 4, 3))
        lengths = jnp.asarray([4, 2])
        filt = jnp.asarray(np_rng.randn(9, 5))

        def f(p):
            out = seq_ops.sequence_conv(p["x"], lengths, p["f"],
                                        context_len=3)
            return jnp.sum(out ** 2)

        directional_grad_check(f, {"x": x, "f": filt})


class TestBlockExpand:
    def test_shape_and_values(self, np_rng):
        x = jnp.asarray(np_rng.randn(2, 6, 8, 3), jnp.float32)
        out = conv_ops.block_expand(x, (2, 2), stride=2)
        assert out.shape == (2, 3 * 4, 2 * 2 * 3)
        # first block of first image == the top-left 2x2 patch
        got = np.asarray(out[0, 0])
        patch = np.asarray(x[0, :2, :2, :])  # [2,2,3]
        # im2col emits [C, kh, kw] ordering per conv_general_dilated_patches
        want = patch.transpose(2, 0, 1).reshape(-1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_layer_feeds_sequence_pool(self, np_rng):
        model = nn.Sequential([
            nn.BlockExpand((2, 2), name="be"),
            nn.SequencePool("mean", name="pool"),
            nn.Dense(4, name="fc"),
        ])
        params, state = model.init(jax.random.key(0),
                                   ShapeSpec((2, 6, 8, 3)))
        x = jnp.asarray(np_rng.randn(2, 6, 8, 3), jnp.float32)
        out, _ = model.apply(params, state, x)
        assert out.shape == (2, 4)


class TestPReLU:
    def test_values(self):
        x = jnp.asarray([-2.0, -1.0, 0.0, 3.0])
        y = A.prelu(x, 0.1)
        np.testing.assert_allclose(np.asarray(y), [-0.2, -0.1, 0.0, 3.0],
                                   rtol=1e-6)

    def test_layer_learns_alpha(self, np_rng):
        layer = nn.PReLU()
        params, _ = layer.init(jax.random.key(0), ShapeSpec((4, 6)))
        assert params["alpha"].shape == (6,)
        shared = nn.PReLU(channel_shared=True)
        sp, _ = shared.init(jax.random.key(0), ShapeSpec((4, 6)))
        assert sp["alpha"].shape == ()

        x = jnp.asarray(np_rng.randn(4, 6))

        def f(p):
            out, _ = layer._apply(p, {}, x, training=True, rng=None)
            return jnp.sum(out ** 2)

        directional_grad_check(f, params)


class TestInterp:
    def test_bilinear_upscale_invariants(self, np_rng):
        x = jnp.asarray(np_rng.rand(1, 4, 4, 2), jnp.float32)
        out = conv_ops.bilinear_interp(x, (8, 8))
        assert out.shape == (1, 8, 8, 2)
        # bilinear interpolation preserves constants exactly...
        const = jnp.full((1, 4, 4, 1), 0.7, jnp.float32)
        np.testing.assert_allclose(
            np.asarray(conv_ops.bilinear_interp(const, (9, 5))), 0.7,
            rtol=1e-6)
        # ...and stays within the input's range with ~the same mean
        assert float(out.min()) >= float(x.min()) - 1e-6
        assert float(out.max()) <= float(x.max()) + 1e-6
        np.testing.assert_allclose(float(out.mean()), float(x.mean()),
                                   atol=0.05)

    def test_align_corners_endpoints(self, np_rng):
        x = jnp.asarray(np_rng.rand(1, 3, 3, 1), jnp.float32)
        out = conv_ops.bilinear_interp(x, (5, 5), align_corners=True)
        np.testing.assert_allclose(float(out[0, 0, 0, 0]),
                                   float(x[0, 0, 0, 0]), rtol=1e-5)
        np.testing.assert_allclose(float(out[0, 4, 4, 0]),
                                   float(x[0, 2, 2, 0]), rtol=1e-5)

    def test_nearest(self):
        x = jnp.arange(4.0).reshape(1, 2, 2, 1)
        out = conv_ops.nearest_interp(x, (4, 4))
        np.testing.assert_allclose(np.asarray(out[0, :, :, 0]),
                                   [[0, 0, 1, 1], [0, 0, 1, 1],
                                    [2, 2, 3, 3], [2, 2, 3, 3]])

    def test_rotate_roundtrip(self, np_rng):
        x = jnp.asarray(np_rng.rand(2, 3, 5, 4), jnp.float32)
        r = conv_ops.rotate90(x)
        assert r.shape == (2, 5, 3, 4)
        back = conv_ops.rotate90(r, reverse=True)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))


class TestCostWrappers:
    def test_crf_layer_loss_and_decode(self, np_rng):
        B, T, K = 3, 5, 4
        layer = nn.CRF(K)
        emissions = jnp.asarray(np_rng.randn(B, T, K))
        tags = jnp.asarray(np_rng.randint(0, K, (B, T)))
        lengths = jnp.asarray([5, 3, 1])
        params, _ = layer.init(jax.random.key(0),
                               ShapeSpec((B, T, K)),
                               ShapeSpec((B, T), jnp.int32),
                               ShapeSpec((B,), jnp.int32))
        loss, _ = layer._apply(params, {}, emissions, tags, lengths,
                               training=True, rng=None)
        assert loss.shape == (B,) and bool(jnp.all(loss > 0))
        dec_tags, scores = layer.decode(params, emissions, lengths)
        assert dec_tags.shape == (B, T)

        def f(p):
            l, _ = layer._apply(p, {}, emissions, tags, lengths,
                                training=True, rng=None)
            return jnp.sum(l)

        directional_grad_check(f, params)

    def test_ctc_layer(self, np_rng):
        B, T, V, L = 2, 6, 5, 3
        layer = nn.CTC(blank=0)
        logits = jnp.asarray(np_rng.randn(B, T, V))
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        labels = jnp.asarray(np_rng.randint(1, V, (B, L)))
        loss, _ = layer._apply({}, {}, log_probs,
                               jnp.asarray([6, 4]), labels,
                               jnp.asarray([3, 2]), training=True, rng=None)
        assert loss.shape == (B,) and bool(jnp.all(loss > 0))

    def test_nce_layer(self, np_rng):
        B, D, V = 6, 8, 50
        layer = nn.NCE(V, num_samples=5)
        params, _ = layer.init(jax.random.key(0), ShapeSpec((B, D)),
                               ShapeSpec((B,), jnp.int32))
        hidden = jnp.asarray(np_rng.randn(B, D), jnp.float32)
        labels = jnp.asarray(np_rng.randint(0, V, B))
        loss, _ = layer._apply(params, {}, hidden, labels, training=True,
                               rng=jax.random.key(1))
        assert loss.shape == (B,) and np.isfinite(np.asarray(loss)).all()

    def test_additive_attention_layer(self, np_rng):
        B, S, Q, K = 3, 7, 5, 6
        layer = nn.AdditiveAttention(hidden=4)
        params, _ = layer.init(jax.random.key(0), ShapeSpec((B, Q)),
                               ShapeSpec((B, S, K)))
        q = jnp.asarray(np_rng.randn(B, Q), jnp.float32)
        keys = jnp.asarray(np_rng.randn(B, S, K), jnp.float32)
        lengths = jnp.asarray([7, 4, 1])
        ctx, _ = layer._apply(params, {}, q, keys, lengths, training=True,
                              rng=None)
        assert ctx.shape == (B, K)
        # masked positions have no influence: perturb them, same output
        keys2 = np.array(keys)
        keys2[1, 4:] += 100.0
        ctx2, _ = layer._apply(params, {}, q, jnp.asarray(keys2), lengths,
                               training=True, rng=None)
        np.testing.assert_allclose(np.asarray(ctx[1]), np.asarray(ctx2[1]),
                                   rtol=1e-4)

    def test_sequence_conv_layer_grad(self, np_rng):
        layer = nn.SequenceConv(4, context_len=3, trainable_padding=True)
        params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 5, 3)))
        assert "padding" in params
        x = jnp.asarray(np_rng.randn(2, 5, 3))
        lengths = jnp.asarray([5, 2])

        def f(p):
            out, _ = layer._apply(p, {}, x, lengths, training=True, rng=None)
            return jnp.sum(out ** 2)

        directional_grad_check(f, params)


class TestBilinearAndConvShift:
    """reference: operators/bilinear_tensor_product_op.cc,
    operators/conv_shift_op.cc."""

    def test_bilinear_tensor_product_manual(self, np_rng):
        from paddle_tpu.ops import linalg

        x = jnp.asarray(np_rng.randn(3, 4), jnp.float32)
        y = jnp.asarray(np_rng.randn(3, 5), jnp.float32)
        w = jnp.asarray(np_rng.randn(2, 4, 5), jnp.float32)
        b = jnp.asarray(np_rng.randn(2), jnp.float32)
        out = linalg.bilinear_tensor_product(x, y, w, b)
        assert out.shape == (3, 2)
        want = np.stack([
            [np.asarray(x[i]) @ np.asarray(w[k]) @ np.asarray(y[i])
             + float(b[k]) for k in range(2)]
            for i in range(3)])
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)

    def test_bilinear_grad(self, np_rng):
        from gradcheck import directional_grad_check
        from paddle_tpu.ops import linalg

        x = jnp.asarray(np_rng.randn(2, 3), jnp.float32)
        y = jnp.asarray(np_rng.randn(2, 4), jnp.float32)
        params = {"w": jnp.asarray(np_rng.randn(2, 3, 4), jnp.float32)}
        directional_grad_check(
            lambda p: jnp.sum(
                linalg.bilinear_tensor_product(x, y, p["w"]) ** 2), params)

    def test_conv_shift_matches_naive(self, np_rng):
        from paddle_tpu.ops import linalg

        b, m, n = 2, 7, 3
        x = jnp.asarray(np_rng.randn(b, m), jnp.float32)
        y = jnp.asarray(np_rng.randn(b, n), jnp.float32)
        out = np.asarray(linalg.conv_shift(x, y))
        want = np.zeros((b, m), np.float32)
        for bi in range(b):
            for i in range(m):
                for j in range(n):
                    want[bi, i] += float(y[bi, j]) * float(
                        x[bi, (i + j - n // 2) % m])
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


class TestPoolWithIndex:
    """reference: operators/pool_with_index_op.cc + gserver
    MaxPoolWithMaskLayer; unpool round-trip."""

    def test_matches_max_pool_and_indices_point_at_maxima(self, np_rng):
        from paddle_tpu.ops import conv as C

        x = jnp.asarray(np_rng.randn(2, 6, 8, 3), jnp.float32)
        pooled, idx = C.max_pool2d_with_index(x, 2)
        np.testing.assert_allclose(np.asarray(pooled),
                                   np.asarray(C.max_pool2d(x, 2)),
                                   rtol=1e-6)
        # every index points at a cell holding the pooled value
        xa = np.asarray(x)
        pa, ia = np.asarray(pooled), np.asarray(idx)
        n, oh, ow, c = pa.shape
        for b in range(n):
            for i in range(oh):
                for j in range(ow):
                    for ch in range(c):
                        fh, fw = divmod(int(ia[b, i, j, ch]), 8)
                        assert xa[b, fh, fw, ch] == pa[b, i, j, ch]

    def test_unpool_roundtrip_sparse(self, np_rng):
        from paddle_tpu.ops import conv as C

        x = jnp.asarray(np_rng.randn(1, 4, 4, 2), jnp.float32)
        pooled, idx = C.max_pool2d_with_index(x, 2)
        up = C.max_unpool2d(pooled, idx, (4, 4))
        assert up.shape == (1, 4, 4, 2)
        # unpooled holds each max at its original position, zeros elsewhere
        ua, xa = np.asarray(up), np.asarray(x)
        nonzero = ua != 0
        assert nonzero.sum() == 2 * 2 * 2  # one max per window per channel
        np.testing.assert_allclose(ua[nonzero], xa[nonzero], rtol=1e-6)

    def test_with_index_same_padding(self, np_rng):
        from paddle_tpu.ops import conv as C

        x = jnp.asarray(np_rng.randn(1, 5, 5, 1), jnp.float32)
        pooled, idx = C.max_pool2d_with_index(x, 3, stride=2,
                                              padding="SAME")
        np.testing.assert_allclose(
            np.asarray(pooled),
            np.asarray(C.max_pool2d(x, 3, stride=2, padding="SAME")),
            rtol=1e-6)

    def test_all_negative_same_padding_no_zero_leak(self):
        from paddle_tpu.ops import conv as C

        x = jnp.full((1, 5, 5, 1), -1.0, jnp.float32)
        pooled, idx = C.max_pool2d_with_index(x, 3, stride=2,
                                              padding="SAME")
        np.testing.assert_allclose(
            np.asarray(pooled),
            np.asarray(C.max_pool2d(x, 3, stride=2, padding="SAME")),
            rtol=1e-6)
        assert float(np.asarray(pooled).max()) == -1.0
        # indices stay inside the real image
        assert int(np.asarray(idx).max()) < 25

    def test_unpool_overlapping_windows_write_once(self):
        from paddle_tpu.ops import conv as C

        x = jnp.zeros((1, 3, 3, 1), jnp.float32).at[0, 1, 1, 0].set(5.0)
        pooled, idx = C.max_pool2d_with_index(x, 2, stride=1)
        up = C.max_unpool2d(pooled, idx, (3, 3))
        assert float(up[0, 1, 1, 0]) == 5.0  # once, not 4x


    def test_integer_dtype_preserved(self):
        from paddle_tpu.ops import conv as C

        x = jnp.asarray([[[[5], [-3]], [[-7], [2]]]], jnp.int32)
        pooled, idx = C.max_pool2d_with_index(x, 2)
        assert pooled.dtype == jnp.int32
        assert int(pooled[0, 0, 0, 0]) == 5
        np.testing.assert_array_equal(
            np.asarray(pooled), np.asarray(C.max_pool2d(x, 2)))


class TestMiscLayerOps:
    """The remaining small layer types from the reference REGISTER_LAYER
    inventory (gserver/layers): power, sum_to_one, switch_order, trans,
    resize, maxid, sampling_id, scale_sub_region, data_norm, row_conv,
    dot_prod, out_prod, convex_comb, selective_fc, kmax_seq_score."""

    def test_dot_out_prod(self, np_rng):
        from paddle_tpu.ops import linalg as L2

        a = jnp.asarray(np_rng.randn(4, 5), jnp.float32)
        b = jnp.asarray(np_rng.randn(4, 3), jnp.float32)
        d = L2.dot_prod(a, a)
        np.testing.assert_allclose(d[:, 0], jnp.sum(a * a, -1), rtol=1e-6)
        o = L2.out_prod(a, b)
        assert o.shape == (4, 15)
        np.testing.assert_allclose(o[1].reshape(5, 3),
                                   np.outer(a[1], b[1]), rtol=1e-6)

    def test_convex_comb(self, np_rng):
        from paddle_tpu.ops import linalg as L2

        w = jnp.asarray(np_rng.rand(2, 3), jnp.float32)
        x = jnp.asarray(np_rng.randn(2, 12), jnp.float32)
        y = L2.convex_comb(w, x)
        manual = sum(w[:, k:k + 1] * x[:, 4 * k:4 * (k + 1)]
                     for k in range(3))
        np.testing.assert_allclose(y, manual, rtol=1e-5)

    def test_selective_fc(self, np_rng):
        from paddle_tpu.ops import linalg as L2

        x = jnp.asarray(np_rng.randn(3, 6), jnp.float32)
        k = jnp.asarray(np_rng.randn(6, 20), jnp.float32)
        b = jnp.asarray(np_rng.randn(20), jnp.float32)
        sel = jnp.asarray([[0, 5, 19], [1, 1, 2], [7, 3, 11]])
        out = L2.selective_fc(x, k, b, sel)
        full = x @ k + b
        for i in range(3):
            np.testing.assert_allclose(
                out[i], full[i, np.asarray(sel)[i]], rtol=1e-5)

    def test_power_slope_sum_norm(self, np_rng):
        from paddle_tpu.ops import misc as M2

        x = jnp.asarray(np_rng.rand(3, 4) + 0.5, jnp.float32)
        p = jnp.asarray([1.0, 2.0, 0.5])
        np.testing.assert_allclose(M2.power(x, p)[1], np.asarray(x)[1] ** 2,
                                   rtol=1e-5)
        np.testing.assert_allclose(M2.slope_intercept(x, 2.0, 1.0),
                                   np.asarray(x) * 2 + 1, rtol=1e-6)
        s = M2.sum_to_one_norm(x)
        np.testing.assert_allclose(jnp.sum(s, -1), np.ones(3), rtol=1e-5)

    def test_switch_trans_resize_maxid(self, np_rng):
        from paddle_tpu.ops import misc as M2

        x = jnp.asarray(np_rng.randn(2, 4, 5, 3), jnp.float32)
        assert M2.switch_order(x).shape == (2, 3, 4, 5)
        m = jnp.asarray(np_rng.randn(3, 7), jnp.float32)
        np.testing.assert_array_equal(M2.trans(m), np.asarray(m).T)
        assert M2.resize(m, 21).shape == (1, 21)
        ids, vals = M2.maxid(m)
        np.testing.assert_array_equal(ids, np.argmax(np.asarray(m), -1))

    def test_sampling_id_distribution(self):
        from paddle_tpu.ops import misc as M2

        probs = jnp.asarray([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
        ids = M2.sampling_id(jax.random.key(0), probs)
        np.testing.assert_array_equal(ids, [1, 0])

    def test_scale_sub_region(self):
        from paddle_tpu.ops import misc as M2

        x = jnp.ones((1, 4, 4, 2))
        boxes = jnp.asarray([[1, 1, 2, 3, 2, 4]])  # c=1, h=2..3, w=2..4
        y = M2.scale_sub_region(x, boxes, 10.0)
        assert float(y[0, 1, 1, 0]) == 10.0
        assert float(y[0, 1, 1, 1]) == 1.0  # channel 2 untouched
        assert float(y[0, 0, 1, 0]) == 1.0  # row before region untouched
        assert float(jnp.sum(y)) == 32 - 6 + 60  # 6 cells scaled

    def test_data_norm_modes(self, np_rng):
        from paddle_tpu.ops import misc as M2

        x = jnp.asarray(np_rng.randn(16, 3) * 4 + 2, jnp.float32)
        stats = {"mean": jnp.mean(x, 0), "std": jnp.std(x, 0),
                 "min": jnp.min(x, 0), "max": jnp.max(x, 0),
                 "decimal_scale": jnp.asarray([10.0, 10.0, 10.0])}
        z = M2.data_norm(x, stats)
        np.testing.assert_allclose(jnp.mean(z, 0), np.zeros(3), atol=1e-5)
        mm = M2.data_norm(x, stats, mode="min-max")
        assert float(jnp.min(mm)) >= 0 and float(jnp.max(mm)) <= 1

    def test_row_conv_lookahead(self):
        from paddle_tpu.ops import misc as M2

        x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(1, 6, 2))
        w = jnp.asarray([[1.0, 1.0], [1.0, 1.0]])  # ctx 2, sum of 2 frames
        y = M2.row_conv(x, w)
        np.testing.assert_allclose(y[0, 0], x[0, 0] + x[0, 1])
        np.testing.assert_allclose(y[0, 5], x[0, 5])  # last: no lookahead
        # grad check
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(M2.row_conv(x, p["w"]))), {"w": w})

    def test_kmax_seq_score(self):
        from paddle_tpu.ops import sequence as S2

        scores = jnp.asarray([[0.1, 0.9, 0.5, 0.7],
                              [0.8, 0.2, 0.0, 0.0]])
        lengths = jnp.asarray([4, 2])
        ids = S2.kmax_seq_score(scores, lengths, 3)
        np.testing.assert_array_equal(ids[0], [1, 3, 2])
        # seq 1 has only 2 valid: third slot repeats the argmax
        np.testing.assert_array_equal(ids[1], [0, 1, 0])

    def test_data_norm_layer_and_row_conv_layer(self, np_rng):
        from paddle_tpu import nn

        x = jnp.asarray(np_rng.randn(8, 3), jnp.float32)
        layer = nn.DataNorm({"mean": np.zeros(3), "std": np.ones(3)})
        params, state = layer.init(jax.random.key(0), ShapeSpec((8, 3)))
        y, _ = layer.apply(params, state, x, training=False)
        np.testing.assert_allclose(y, x, rtol=1e-6)

        seq = jnp.asarray(np_rng.randn(2, 5, 4), jnp.float32)
        rc = nn.RowConv(3)
        params, state = rc.init(jax.random.key(1), ShapeSpec((2, 5, 4)))
        y, _ = rc.apply(params, state, seq, training=False)
        assert y.shape == (2, 5, 4)


class TestCrossEntropyOverBeam:
    """Globally-normalized beam CE (reference:
    gserver/tests/test_CrossEntropyOverBeamGrad.cpp)."""

    def _data(self):
        from paddle_tpu.ops.beam_search import NEG_INF

        # E=2 steps, B=2 sequences, K=2 beam
        step_scores = jnp.asarray([
            [[1.0, 0.5], [0.4, 0.6]],
            [[0.2, 0.3], [0.1, 0.7]],
        ], jnp.float32)
        parents = jnp.asarray([
            [[0, 0], [0, 0]],
            [[0, 1], [1, 0]],
        ], jnp.int32)
        # seq0: gold survives at pos 0; seq1: gold (pos 1) pruned at step 1
        gold_pos = jnp.asarray([[0, 1], [0, -1]], jnp.int32)
        return step_scores, parents, gold_pos

    def test_matches_manual(self):
        from paddle_tpu.ops import beam_search as BS

        step_scores, parents, gold_pos = self._data()
        loss = BS.cross_entropy_over_beam(step_scores, parents, gold_pos)
        # seq0 paths: p0 = 0.2 + s0[0] = 1.2 ; p1 = 0.3 + s0[1] = 0.8
        # gold = path 0
        l0 = np.log(np.exp(1.2) + np.exp(0.8)) - 1.2
        # seq1 paths: p0 = 0.1 + s0[1] = 0.7 ; p1 = 0.7 + s0[0] = 1.1
        # gold pruned -> extra path with score s0[1] = 0.6
        l1 = np.log(np.exp(0.7) + np.exp(1.1) + np.exp(0.6)) - 0.6
        np.testing.assert_allclose(loss, [l0, l1], rtol=1e-5)

    def test_grad(self):
        from paddle_tpu.ops import beam_search as BS

        step_scores, parents, gold_pos = self._data()
        directional_grad_check(
            lambda p: jnp.sum(BS.cross_entropy_over_beam(
                p["s"], parents, gold_pos)),
            {"s": step_scores})
