"""Serving reliability layer: the chaos harness.

Every degradation path of `serve.ServingServer` — shedding, deadline
expiry mid-decode, slot retry after transient faults, graceful drain,
the native-path circuit breaker — is driven deterministically through
`testing.faults.FaultPlan.wrap_engine` + `ManualClock` (no sleeps, no
wall-clock races), the same prove-it-with-fault-injection discipline
`tests/test_resilience.py` established for training. The capstone is
the mixed-burst chaos test: overflow + deadline storm + native-bridge
fault in one run, with the reconciliation invariant (every submitted
request ends in EXACTLY ONE of completed/expired/shed/failed, counters
== request log, pool keeps serving afterward) asserted end-to-end.
"""

import json

import jax
import numpy as np
import pytest

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.server import (CircuitBreaker, QueueFullError,
                                     ServingServer)
from paddle_tpu.testing.faults import (FaultPlan, ManualClock,
                                       garbage_prompts)

pytestmark = pytest.mark.faults

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


# engines are MODULE-SCOPED and shared across tests/servers: an engine
# is stateless between runs (init_state resets the pool) and its jitted
# prefill/step compiles dominate test cost — sharing amortizes them.
# Fault wrappers (plan.wrap_engine) proxy a shared engine without
# touching it, so even the chaos tests reuse the same compiles.
@pytest.fixture(scope="module")
def eng2(params):
    return DecodeEngine(params, CFG, slots=2, max_len=32)


@pytest.fixture(scope="module")
def eng1(params):
    return DecodeEngine(params, CFG, slots=1, max_len=32)


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def prompts_rng(n, lens, seed=0):
    r = np.random.RandomState(seed)
    return [r.randint(0, 61, (l,)).astype(np.int32)
            for l, _ in zip(list(lens) * n, range(n))]


class TestAdmission:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_completed_requests_match_generate(self, params, eng2):
        """The reliability layer must not perturb the math: a greedy
        request served through the scheduler equals its solo
        generate() decode, like the raw engine pool."""
        srv = ServingServer(eng2, max_queue=8)
        ps = prompts_rng(4, [5, 9, 3, 7], seed=1)
        ids = [srv.submit(p, max_new=10) for p in ps]
        res = srv.run()
        srv.reconcile()
        for rid, p in zip(ids, ps):
            assert res[rid].outcome == "completed"
            assert res[rid].tokens == ref_tokens(params, p, 10)

    def test_queue_overflow_sheds_with_documented_error(self, eng1):
        """max_queue bound: the incoming request, when cheapest to
        retry, is shed with QueueFullError and a 'load shed' result."""
        srv = ServingServer(eng1, max_queue=2)
        srv.submit(prompts_rng(1, [9], seed=2)[0], max_new=2)
        srv.submit(prompts_rng(1, [7], seed=3)[0], max_new=2)
        cheap = prompts_rng(1, [3], seed=4)[0]
        with pytest.raises(QueueFullError, match="queue full"):
            srv.submit(cheap, max_new=2)
        shed = [r for r in srv.results.values() if r.outcome == "shed"]
        assert len(shed) == 1 and "load shed" in shed[0].error
        res = srv.run()
        srv.reconcile()
        assert srv.stats.shed == 1 and srv.stats.completed == 2

    def test_overflow_displaces_cheapest_queued(self, eng1):
        """An expensive incoming request displaces the cheapest QUEUED
        one instead of being dropped itself — shed cost is bounded by
        the smallest prompt in the queue."""
        srv = ServingServer(eng1, max_queue=2)
        srv.submit(prompts_rng(1, [9], seed=2)[0], max_new=2)
        small = srv.submit(prompts_rng(1, [3], seed=4)[0], max_new=2)
        big = srv.submit(prompts_rng(1, [12], seed=5)[0], max_new=2)
        res = srv.run()
        srv.reconcile()
        assert res[small].outcome == "shed"
        assert "displaced" in res[small].error
        assert res[big].outcome == "completed"

    def test_garbage_prompts_rejected_pool_survives(self, params, eng1):
        """Every canonical malformed input fails synchronously with
        ValueError, is ledgered FAILED, and the pool serves real
        traffic afterwards untouched."""
        srv = ServingServer(eng1,
                            max_queue=8, buckets=(8,))
        for name, g in garbage_prompts(61, 8).items():
            with pytest.raises(ValueError):
                srv.submit(g, max_new=2)
        bad_max_new = prompts_rng(1, [4], seed=6)[0]
        with pytest.raises(ValueError, match="max_new"):
            srv.submit(bad_max_new, max_new=0)
        ok = srv.submit(bad_max_new, max_new=3)
        res = srv.run()
        srv.reconcile()
        assert res[ok].outcome == "completed"
        assert res[ok].tokens == ref_tokens(params, bad_max_new, 3)
        assert srv.stats.failed == len(garbage_prompts(61, 8)) + 1
        assert srv.stats.prefills == 1   # no garbage reached the chip


class TestDeadlines:
    def test_expiry_mid_decode_frees_slot_for_queued(self, params, eng1):
        """THE deadline contract: an expired request stops
        mid-generation (partial tokens kept) and its slot serves a
        queued request to the exact greedy completion."""
        clk = ManualClock()
        srv = ServingServer(eng1, max_queue=8,
                            clock=clk)
        ps = prompts_rng(2, [5, 9], seed=7)
        doomed = srv.submit(ps[0], max_new=50, deadline_ms=5)
        patient = srv.submit(ps[1], max_new=4, deadline_ms=None)
        srv.on_step.append(lambda s, step: clk.advance(0.002))
        res = srv.run()
        srv.reconcile()
        assert res[doomed].outcome == "expired"
        assert 0 < len(res[doomed].tokens) < 50       # stopped mid-run
        assert "mid-generation" in res[doomed].error
        assert res[patient].outcome == "completed"    # slot reused
        assert res[patient].tokens == ref_tokens(params, ps[1], 4)

    def test_queued_expiry_costs_no_prefill(self, eng1):
        """A request that dies waiting never reaches the chip."""
        clk = ManualClock()
        srv = ServingServer(eng1, max_queue=8,
                            clock=clk)
        ps = prompts_rng(2, [5, 6], seed=8)
        runner = srv.submit(ps[0], max_new=8)
        doa = srv.submit(ps[1], max_new=8, deadline_ms=4)
        srv.on_step.append(lambda s, step: clk.advance(0.003))
        res = srv.run()
        srv.reconcile()
        assert res[runner].outcome == "completed"
        assert res[doa].outcome == "expired"
        assert res[doa].tokens == [] and "never admitted" in res[doa].error
        assert srv.stats.prefills == 1

    def test_default_deadline_applies(self, eng1):
        clk = ManualClock()
        srv = ServingServer(eng1, max_queue=8,
                            clock=clk, default_deadline_ms=5)
        rid = srv.submit(prompts_rng(1, [5], seed=9)[0], max_new=50)
        srv.on_step.append(lambda s, step: clk.advance(0.004))
        res = srv.run()
        srv.reconcile()
        assert res[rid].outcome == "expired"


class TestRetry:
    def test_decode_fault_requeues_and_completes(self, params, eng2):
        """A transient decode fault evicts in-flight requests to the
        queue; the retry serves them to the exact same tokens (pure
        state + greedy => the fault is invisible in the output)."""
        plan = FaultPlan(serve_decode_error_at=1)
        srv = ServingServer(plan.wrap_engine(eng2),
                            max_queue=8, max_retries=1)
        ps = prompts_rng(2, [5, 9], seed=10)
        ids = [srv.submit(p, max_new=6) for p in ps]
        res = srv.run()
        srv.reconcile()
        assert plan.count("sdecode") == 1
        for rid, p in zip(ids, ps):
            assert res[rid].outcome == "completed"
            assert res[rid].retries == 1
            assert res[rid].tokens == ref_tokens(params, p, 6)
        assert srv.stats.retried == 2

    def test_prefill_fault_requeues_only_that_request(self, eng2):
        plan = FaultPlan(serve_prefill_error_at=0)
        srv = ServingServer(plan.wrap_engine(eng2),
                            max_queue=8, max_retries=1)
        ps = prompts_rng(2, [5, 7], seed=11)
        ids = [srv.submit(p, max_new=4) for p in ps]
        res = srv.run()
        srv.reconcile()
        assert plan.count("sprefill") == 1
        assert res[ids[0]].outcome == "completed"
        assert res[ids[0]].retries == 1
        assert res[ids[1]].outcome == "completed"
        assert res[ids[1]].retries == 0       # bystander untouched
        assert srv.stats.retried == 1

    def test_retry_budget_exhaustion_fails(self, eng2):
        """A fault that keeps firing ends the request FAILED after
        max_retries requeues — never an infinite loop, never silent."""
        plan = FaultPlan(serve_error_first_n=10)
        srv = ServingServer(plan.wrap_engine(eng2),
                            max_queue=8, max_retries=2)
        rid = srv.submit(prompts_rng(1, [5], seed=12)[0], max_new=4)
        res = srv.run()
        srv.reconcile()
        assert res[rid].outcome == "failed"
        assert "retry budget exhausted" in res[rid].error
        assert srv.stats.retried == 2 and srv.stats.failed == 1


class TestDrain:
    def test_drain_finishes_in_flight_sheds_queue(self, params, eng2,
                                                  tmp_path):
        """Graceful drain: in-flight requests COMPLETE, queued ones
        shed, nothing new admitted, report persisted."""
        report = tmp_path / "drain.json"
        srv = ServingServer(eng2, max_queue=8,
                            drain_report_path=str(report))
        ps = prompts_rng(5, [5, 9, 3, 7, 4], seed=13)
        ids = [srv.submit(p, max_new=6) for p in ps]
        srv.on_step.append(
            lambda s, step: s.drain(reason="test") if step == 2
            else None)
        res = srv.run()
        srv.reconcile()
        outcomes = [res[i].outcome for i in ids]
        assert outcomes.count("completed") == 2      # the 2 in-flight
        assert outcomes.count("shed") == 3           # the queue
        assert all("drain" in res[i].error for i in ids
                   if res[i].outcome == "shed")
        # in-flight finished to full length — drain is graceful
        for rid, p in zip(ids[:2], ps[:2]):
            assert res[rid].tokens == ref_tokens(params, p, 6)
        rep = json.loads(report.read_text())
        assert rep["reason"] == "test"
        assert rep["counters"] == srv.counters()
        assert len(rep["requests"]) == 5

    def test_drain_grace_expires_stragglers(self, eng2):
        clk = ManualClock()
        srv = ServingServer(eng2, max_queue=8, clock=clk,
                            drain_grace_s=0.01)
        ids = [srv.submit(p, max_new=30)
               for p in prompts_rng(2, [5, 6], seed=14)]

        def hook(s, step):
            if step == 2:
                s.drain(reason="grace")
            clk.advance(0.004)

        srv.on_step.append(hook)
        res = srv.run()
        srv.reconcile()
        for rid in ids:
            assert res[rid].outcome == "expired"
            assert 0 < len(res[rid].tokens) < 30
            assert "drain grace" in res[rid].error

    def test_sigterm_triggers_drain(self, eng1):
        """install_signal_handlers: SIGTERM mid-run = drain, mirroring
        train/resilience.py's preemption semantics."""
        import os
        import signal

        srv = ServingServer(eng1, max_queue=8,
                            install_signal_handlers=True)
        ids = [srv.submit(p, max_new=5)
               for p in prompts_rng(3, [5, 6, 4], seed=15)]
        srv.on_step.append(
            lambda s, step: os.kill(os.getpid(), signal.SIGTERM)
            if step == 1 else None)
        res = srv.run()
        srv.reconcile()
        assert res[ids[0]].outcome == "completed"
        assert all(res[i].outcome == "shed" for i in ids[1:])
        assert "signal" in srv.drain_report["reason"]

    def test_submit_while_draining_is_shed(self, eng2):
        srv = ServingServer(eng2, max_queue=8)
        srv.drain(reason="pre")
        with pytest.raises(QueueFullError, match="draining"):
            srv.submit(prompts_rng(1, [4], seed=16)[0], max_new=2)
        srv.run()
        srv.reconcile()
        assert srv.stats.shed == 1


class TestCircuitBreaker:
    def test_trips_to_fallback_and_recovers(self, params, eng2):
        """Repeated native faults open the breaker -> pool falls back
        to the pure-JAX engine and completes everything; after the
        cooldown the half-open probe routes traffic back through the
        healed native side and closes the breaker."""
        clk = ManualClock()
        plan = FaultPlan(serve_error_first_n=2)
        native = plan.wrap_engine(eng2, clock=clk)
        breaker = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                                 clock=clk)
        srv = ServingServer(eng2, native_backend=native,
                            breaker=breaker, max_queue=16, clock=clk,
                            max_retries=3)
        ps = prompts_rng(5, [5, 9, 3, 7, 4], seed=17)
        ids = [srv.submit(p, max_new=4) for p in ps[:3]]
        res = srv.run()
        srv.reconcile()
        assert breaker.state == "open" and breaker.trips == 1
        assert plan.count("nativeburst") == 2
        for rid, p in zip(ids, ps[:3]):
            assert res[rid].outcome == "completed"
            assert res[rid].backend == "jax"          # the fallback
            assert res[rid].tokens == ref_tokens(params, p, 4)
        clk.advance(2.0)                              # past cooldown
        ids2 = [srv.submit(p, max_new=4) for p in ps[3:]]
        res2 = srv.run()
        srv.reconcile()
        assert breaker.state == "closed"              # probe passed
        for rid, p in zip(ids2, ps[3:]):
            assert res2[rid].outcome == "completed"
            assert res2[rid].backend == "native"      # recovered
            assert res2[rid].tokens == ref_tokens(params, p, 4)

    def test_failed_probe_reopens(self):
        clk = ManualClock()
        br = CircuitBreaker(failure_threshold=2, cooldown_s=1.0,
                            clock=clk)
        br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open" and not br.allow()
        clk.advance(1.5)
        assert br.state == "half-open" and br.allow()
        br.record_failure()                    # probe fails
        assert br.state == "open" and not br.allow()
        clk.advance(1.5)
        assert br.allow()
        br.record_success()                    # probe passes
        assert br.state == "closed" and br.trips == 1


class TestChaos:
    @pytest.mark.slow
    def test_mixed_burst_reconciles_and_keeps_serving(self, params, eng2):
        """The acceptance-criteria chaos run: one burst mixing queue
        overflow, a deadline storm (injected slot stall burning the
        clock), garbage prompts, and a native-bridge fault burst that
        trips the circuit breaker. Asserts: no request is silently
        dropped (every submitted request ends in exactly one terminal
        outcome), outcome counters reconcile with the request log, and
        the pool serves a clean follow-up wave afterwards."""
        clk = ManualClock()
        # native side: fails its first 2 calls -> breaker (threshold
        # 2) opens; fallback side: decode step 4 stalls 50ms -> every
        # tight deadline in flight or queued burns
        plan_native = FaultPlan(serve_error_first_n=2)
        plan_fb = FaultPlan(serve_stall_at=4, serve_stall_s=0.05)
        native = plan_native.wrap_engine(eng2, clock=clk)
        fallback = plan_fb.wrap_engine(eng2, clock=clk)
        breaker = CircuitBreaker(failure_threshold=2, cooldown_s=30.0,
                                 clock=clk)
        srv = ServingServer(fallback, native_backend=native,
                            breaker=breaker, max_queue=4,
                            max_retries=2, clock=clk, buckets=(16,))

        ps = prompts_rng(8, [5, 9, 3, 7, 4, 6, 8, 5], seed=18)
        submitted, shed_sync, failed_sync = [], 0, 0
        # tight deadlines on half the burst: the stall expires them
        deadlines = [None, 20, None, 20, 20, None, 20, None]
        for p, dl in zip(ps, deadlines):
            try:
                submitted.append(srv.submit(p, max_new=6,
                                            deadline_ms=dl))
            except QueueFullError:
                shed_sync += 1
        # garbage rides the same burst
        for g in garbage_prompts(61, 16).values():
            try:
                srv.submit(g, max_new=4)
            except ValueError:
                failed_sync += 1
        assert shed_sync >= 1                 # overflow actually hit
        assert failed_sync == 6               # all garbage rejected

        res = srv.run()
        srv.reconcile()                       # THE invariant
        # the three fault classes all actually fired
        assert plan_native.count("nativeburst") == 2
        assert plan_fb.count("stall") == 1
        assert breaker.trips == 1
        # every submitted request has exactly one terminal outcome
        assert len(res) == srv.stats.requests == 8 + 6
        c = srv.counters()
        assert c["completed"] >= 1
        assert c["expired"] >= 1              # the deadline storm
        assert c["shed"] >= 1                 # the overflow
        assert c["failed"] == 6               # the garbage
        assert c["retried"] >= 1              # the native fault path
        assert (c["completed"] + c["expired"] + c["shed"]
                + c["failed"]) == c["requests"]
        # completed survivors still match the exact greedy decode
        for rid, p in zip(submitted, ps):
            if rid in res and res[rid].outcome == "completed":
                assert res[rid].tokens == ref_tokens(params, p, 6)

        # the engine keeps serving: a clean follow-up wave completes
        ps2 = prompts_rng(3, [4, 6, 5], seed=19)
        ids2 = [srv.submit(p, max_new=4) for p in ps2]
        res2 = srv.run()
        srv.reconcile()
        for rid, p in zip(ids2, ps2):
            assert res2[rid].outcome == "completed"
            assert res2[rid].tokens == ref_tokens(params, p, 4)


class TestCliServeReliable:
    def test_cli_reliability_flags(self, params, tmp_path):
        """`serve --max-queue` routes through ServingServer: ordered
        per-request lines + the outcomes trailer."""
        from paddle_tpu.cli import main

        cfg_src = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n\n\n"
            "def get_serve_config():\n"
            "    from paddle_tpu.models import transformer as T\n"
            "    cfg = T.TransformerConfig(vocab=61, dim=32,"
            " n_layers=2, n_heads=4, attn_impl='dense')\n"
            "    return {'cfg': cfg,"
            " 'params': T.init_params(jax.random.key(0), cfg),"
            " 'slots': 2, 'max_len': 24}\n")
        cfg_file = tmp_path / "serve_cfg.py"
        cfg_file.write_text(cfg_src)
        prompts = tmp_path / "prompts.txt"
        prompts.write_text("1 2 3 4 5\n7 8 9\n")
        out = tmp_path / "out.txt"
        assert main(["serve", "--config", str(cfg_file),
                     "--prompts", str(prompts), "--max-new", "4",
                     "--max-queue", "4",
                     "--output", str(out)]) == 0
        lines = out.read_text().strip().splitlines()
        assert len(lines) == 3                # 2 requests + trailer
        for line, p in zip(lines, ([1, 2, 3, 4, 5], [7, 8, 9])):
            got = [int(t) for t in line.split()]
            assert got == ref_tokens(params,
                                     np.asarray(p, np.int32), 4)
        assert lines[-1].startswith("# outcomes ")
        assert "completed=2" in lines[-1]
