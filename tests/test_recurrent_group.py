"""Recurrent-group engine tests.

Mirrors the reference's test strategy for RecurrentGradientMachine
(reference: gserver/tests/test_RecurrentGradientMachine.cpp — a
recurrent_group-built LSTM must equal the fused LstmLayer; generation
tests trainer/tests/test_recurrent_machine_generation.cpp compare decode
outputs against a golden/hand-built path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.nn.recurrent_group import (
    FnStep, Memory, RecurrentGroup, RecurrentGroupLayer, gru_group,
    lstm_group, scan_subsequences)
from paddle_tpu.ops import linalg
from paddle_tpu.ops import rnn as rnn_ops


B, T, F, H = 4, 7, 5, 6


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, T, F), jnp.float32)
    lengths = jnp.asarray([7, 4, 6, 1])
    return x, lengths


def test_lstm_topology_equivalence():
    """recurrent_group-built LSTM == fused rnn_ops.lstm (outputs and
    final state), the test_RecurrentGradientMachine.cpp strategy."""
    step, mems = lstm_group(F, H)
    group = RecurrentGroup(step, mems)
    params = group.init(jax.random.key(1), ShapeSpec((B, F)), batch=B)
    x, lengths = _data()

    out_g, final_g = group.run(params, x, lengths)
    out_f, final_f = rnn_ops.lstm(params, x, lengths)

    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_f),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(final_g["h"]),
                               np.asarray(final_f.h), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(final_g["c"]),
                               np.asarray(final_f.c), rtol=1e-6, atol=1e-6)


def test_gru_topology_equivalence_reverse():
    step, mems = gru_group(F, H)
    group = RecurrentGroup(step, mems, reverse=True)
    params = group.init(jax.random.key(2), ShapeSpec((B, F)), batch=B)
    x, lengths = _data(3)
    out_g, final_g = group.run(params, x, lengths)
    out_f, final_f = rnn_ops.gru(params, x, lengths, reverse=True)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_f),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(final_g["h"]), np.asarray(final_f),
                               rtol=1e-6, atol=1e-6)


def test_extern_boot():
    """Boot a memory from a caller value (the reference's boot_layer)."""
    step, mems = gru_group(F, H)
    mems = {"h": Memory(H, boot="extern", dtype=jnp.float32)}
    group = RecurrentGroup(step, mems)
    params = group.init(jax.random.key(0), ShapeSpec((B, F)), batch=B)
    x, lengths = _data()
    h0 = jnp.asarray(np.random.RandomState(9).randn(B, H), jnp.float32)
    out, final = group.run(params, x, lengths, boots={"h": h0})
    out_ref, final_ref = rnn_ops.gru(params, x, lengths, initial_state=h0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=1e-6, atol=1e-6)
    # missing extern boot must raise
    with pytest.raises(Exception):
        group.run(params, x, lengths)
    # unknown boot name must raise
    with pytest.raises(Exception):
        group.run(params, x, lengths, boots={"h": h0, "zz": h0})


def test_statics_visible_every_step():
    """StaticInput equivalent: a non-sequence input the step reads each
    timestep (here: an additive bias chosen per example)."""

    def init_fn(rng, mem_specs, x_specs):
        return {"w": jnp.eye(F, dtype=jnp.float32)}

    def apply_fn(params, mems, x_t, static_bias):
        y = linalg.matmul(x_t, params["w"]) + static_bias + mems["acc"]
        return y, {"acc": y}

    group = RecurrentGroup(FnStep(init_fn, apply_fn),
                           {"acc": Memory(F, dtype=jnp.float32)})
    params = group.init(jax.random.key(0), ShapeSpec((B, F)), batch=B)
    x, lengths = _data()
    bias = jnp.asarray(np.random.RandomState(1).randn(B, F), jnp.float32)
    out, final = group.run(params, x, lengths, statics=(bias,))
    # step t output = cumulative sum of (x_<=t + bias) over valid steps
    expect = np.zeros((B, F), np.float32)
    for i in range(B):
        acc = np.zeros(F, np.float32)
        for t in range(int(lengths[i])):
            acc = acc + np.asarray(x[i, t]) + np.asarray(bias[i])
            np.testing.assert_allclose(np.asarray(out[i, t]), acc, rtol=2e-5,
                                       atol=2e-5)
        expect[i] = acc
    np.testing.assert_allclose(np.asarray(final["acc"]), expect, rtol=2e-5,
                               atol=2e-5)


def test_gradients_flow_through_group():
    """BPTT through the group: autodiff vs numeric directional check."""
    step, mems = lstm_group(F, H)
    group = RecurrentGroup(step, mems)
    params = group.init(jax.random.key(4), ShapeSpec((B, F)), batch=B)
    x, lengths = _data(5)

    def loss(p):
        out, _ = group.run(p, x, lengths)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rngs = jax.random.split(jax.random.key(7), len(leaves))
    dirs = [jax.random.normal(r, l.shape, l.dtype)
            for r, l in zip(rngs, leaves)]
    direction = jax.tree_util.tree_unflatten(treedef, dirs)
    analytic = sum(float(jnp.vdot(a, b)) for a, b in zip(
        jax.tree_util.tree_leaves(g), dirs))
    eps = 1e-3
    plus = jax.tree.map(lambda p, d: p + eps * d, params, direction)
    minus = jax.tree.map(lambda p, d: p - eps * d, params, direction)
    numeric = (float(loss(plus)) - float(loss(minus))) / (2 * eps)
    assert abs(numeric - analytic) / max(abs(numeric), 1e-6) < 5e-3


def test_generation_same_step_as_training():
    """The SAME step definition drives training and generation
    (reference: generateSequence reuses the training frames). A tiny
    language-model group: logits from the group's generate() must equal
    a hand-rolled greedy decode with the same parameters."""
    V, E = 11, 8

    def init_fn(rng, mem_specs, x_specs):
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "gru": rnn_ops.init_gru_params(k1, E, H),
            "out_w": jax.random.normal(k2, (H, V)) * 0.5,
            "embed": jax.random.normal(k3, (V, E)) * 0.5,
        }

    def apply_fn(params, mems, x_t):
        h = rnn_ops.gru_step(params["gru"], x_t, mems["h"])
        logits = linalg.matmul(h, params["out_w"])
        return logits, {"h": h}

    group = RecurrentGroup(FnStep(init_fn, apply_fn),
                           {"h": Memory(H, dtype=jnp.float32)})
    params = group.init(jax.random.key(0), ShapeSpec((B, E)), batch=B)
    embed = lambda toks: jnp.take(params["embed"], toks, axis=0)

    max_len, bos, eos = 6, 1, 0
    tokens, lengths = group.generate(
        params, embed_fn=embed, batch_size=B, vocab_size=V,
        max_len=max_len, bos_id=bos, eos_id=eos, beam_size=1)

    # hand-rolled greedy reference
    h = np.zeros((B, H), np.float32)
    prev = np.full((B,), bos, np.int64)
    done = np.zeros((B,), bool)
    for t in range(max_len):
        x_t = np.asarray(params["embed"])[prev]
        hj = rnn_ops.gru_step(params["gru"], jnp.asarray(x_t), jnp.asarray(h))
        logits = np.asarray(linalg.matmul(hj, params["out_w"]))
        nxt = logits.argmax(-1)
        nxt = np.where(done, eos, nxt)
        done = done | (nxt == eos)
        np.testing.assert_array_equal(np.asarray(tokens[:, t]), nxt)
        h = np.asarray(hj)
        prev = nxt

    # beam_size > 1 path runs and its best beam is no worse than greedy
    btoks, bscores, blens = group.generate(
        params, embed_fn=embed, batch_size=B, vocab_size=V,
        max_len=max_len, bos_id=bos, eos_id=eos, beam_size=3)
    assert btoks.shape == (B, 3, max_len)


def test_nested_subsequences():
    """2-level nested sequences: scan_subsequences == per-subsequence
    run (reference: RecurrentGradientMachine.cpp:706-775 sub-sequence
    recursion)."""
    So, Si = 3, 4
    step, mems = gru_group(F, H)
    group = RecurrentGroup(step, mems)
    params = group.init(jax.random.key(0), ShapeSpec((B, F)), batch=B)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, So, Si, F), jnp.float32)
    inner_len = jnp.asarray(rng.randint(1, Si + 1, (B, So)))

    outs, finals = scan_subsequences(group, params, x, inner_len)
    assert outs.shape == (B, So, Si, H)
    for i in range(B):
        for j in range(So):
            o_ref, f_ref = group.run(params, x[i : i + 1, j],
                                     inner_len[i : i + 1, j])
            np.testing.assert_allclose(np.asarray(outs[i, j]),
                                       np.asarray(o_ref[0]), rtol=1e-5,
                                       atol=1e-5)
            np.testing.assert_allclose(np.asarray(finals["h"][i, j]),
                                       np.asarray(f_ref["h"][0]), rtol=1e-5,
                                       atol=1e-5)


def test_group_layer_in_sequential():
    """RecurrentGroupLayer composes inside Sequential like nn.LSTM."""
    step, mems = lstm_group(16, H)
    model = nn.Sequential([
        nn.Embedding(50, 16, name="emb"),
        RecurrentGroupLayer(step, mems, name="rg"),
        nn.Lambda(lambda x: x.mean(axis=1), name="pool",
                  out_spec_fn=lambda s: ShapeSpec((s.shape[0], s.shape[2]),
                                                  s.dtype)),
        nn.Dense(3, name="fc"),
    ])
    params, state = model.init(jax.random.key(0),
                               ShapeSpec((B, T), jnp.int32))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 50, (B, T)))
    out, _ = model.apply(params, state, toks, training=True,
                         rng=jax.random.key(1))
    assert out.shape == (B, 3)
