"""Speculative decoding in the serving engine: n-gram drafting,
page-pool reserve/commit, and the end-to-end contract — GREEDY
requests served with `speculative=True` yield EXACTLY the baseline
serve()/generate() tokens (acceptance only re-derives what the target
would have said; a rejection redraws from the target itself), while
the whole draft/verify/commit/rollback loop stays transfer-clean
under `jax.transfer_guard("disallow")`.

The acceptance RULE's math (distribution preservation, greedy
argmax-prefix equivalence) is unit-tested per-call in
tests/test_ops.py::TestSpecVerifyRule; this file owns the proposer,
the pool's reserve/commit ledger, and the serve-loop integration."""

import jax
import numpy as np
import pytest

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.paged import PagePool, PoolExhaustedError
from paddle_tpu.serve.policy import SchedulerPolicy
from paddle_tpu.serve.speculative import NGramProposer

pytestmark = pytest.mark.speculative

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def eng(params):
    """ONE engine for the serve tests — serve() resets all state, and
    the per-instance jits (prefill, plain step, spec step) compile
    once for the whole module instead of once per test (tier-1 time
    budget)."""
    return DecodeEngine(params, CFG, slots=2, max_len=48)


def spec_prompts(seed=0):
    """Mixed traffic: repetitive prompts (n-gram hits -> real
    acceptance) beside a novel one (0-draft rounds -> the degrade
    path), different lengths so slots churn."""
    r = np.random.RandomState(seed)
    base = r.randint(0, 61, (6,)).astype(np.int32)
    return [np.concatenate([base, base, base[:3]]).astype(np.int32),
            r.randint(0, 61, (7,)).astype(np.int32),
            np.concatenate([base, base]).astype(np.int32),
            r.randint(0, 61, (5,)).astype(np.int32)]


class TestNGramProposer:
    def test_suffix_match_proposes_continuation(self):
        p = NGramProposer(max_ngram=3)
        #            match v--v            suffix v--v
        hist = [1, 2, 3, 4, 9, 8, 7, 1, 2, 3, 4]
        assert p.propose(hist, 3) == [9, 8, 7]

    def test_most_recent_occurrence_wins(self):
        p = NGramProposer(max_ngram=2)
        hist = [5, 6, 1, 5, 6, 2, 5, 6]
        assert p.propose(hist, 1) == [2]

    def test_deeper_ngram_beats_shallower(self):
        # the 1-gram [4] recurs later with continuation 9, but the
        # 2-gram [3, 4] matches with continuation 7 — depth wins
        p = NGramProposer(max_ngram=2)
        hist = [3, 4, 7, 0, 4, 9, 5, 3, 4]
        assert p.propose(hist, 1) == [7]

    def test_no_match_and_short_history_are_empty(self):
        p = NGramProposer()
        assert p.propose([1, 2, 3, 4], 3) == []      # nothing recurs
        assert p.propose([7], 3) == []               # too short
        assert p.propose([1, 2, 1, 2], 0) == []      # k = 0

    def test_never_proposes_beyond_history(self):
        # the match sits at the very end: fewer than k tokens follow
        p = NGramProposer(max_ngram=1)
        assert p.propose([9, 1, 2, 9], 4) == [1, 2, 9]

    def test_draft_self_extends_through_loops(self):
        # the suffix's most recent occurrence overlaps the history
        # end, so one-shot propose() clips to a single period; draft()
        # re-matches over its own output and fills the budget
        p = NGramProposer()
        assert p.propose([1, 2, 3, 3, 3], 4) == [3]
        assert p.draft([1, 2, 3, 3, 3], 4) == [3, 3, 3, 3]
        assert p.draft([5, 8, 5, 8, 5], 5) == [8, 5, 8, 5, 8]
        assert p.draft([1, 2, 3, 4], 3) == []        # still no match

    def test_validates_ngram_bounds(self):
        with pytest.raises(ValueError):
            NGramProposer(max_ngram=2, min_ngram=3)
        with pytest.raises(ValueError):
            NGramProposer(max_ngram=0)


class TestPoolReserveCommit:
    def _pool(self, **kw):
        kw.setdefault("num_pages", 8)
        kw.setdefault("page_size", 4)
        kw.setdefault("slots", 2)
        kw.setdefault("max_pages_per_slot", 4)
        kw.setdefault("prefix_cache", False)
        return PagePool(**kw)

    def test_reserve_maps_window_blocks_not_pos(self):
        pool = self._pool()
        toks = np.arange(9, dtype=np.int32)
        pool.admit(0, toks, 9)                  # pos 9, blocks 0..2
        # window writes 9..12 -> needs block 3; pos must NOT move
        out = pool.reserve(0, 3)
        assert out == [(3, out[0][1])]
        assert pool.slot_pos[0] == 9
        assert len(pool.slot_pages[0]) == 4
        # window inside mapped blocks: nothing to do
        assert pool.reserve(0, 1) == []
        assert pool.counters()["spec_reserved"] == 1
        pool.release(0)
        pool.reconcile()

    def test_commit_rolls_back_rejected_tail(self):
        pool = self._pool()
        pool.admit(0, np.arange(9, dtype=np.int32), 9)
        pool.reserve(0, 3)                      # block 3 mapped
        in_use = pool.pages_in_use
        added, dropped = pool.commit(0, 1)      # accepted 1: pos 10
        assert (added, dropped) == ([], [3])
        assert pool.slot_pos[0] == 10
        assert pool.pages_in_use == in_use - 1
        assert pool.counters()["spec_rolled_back"] == 1
        pool.reconcile()
        pool.release(0)
        pool.reconcile()

    def test_commit_full_acceptance_keeps_reserved_pages(self):
        pool = self._pool()
        pool.admit(0, np.arange(9, dtype=np.int32), 9)
        pool.reserve(0, 3)
        added, dropped = pool.commit(0, 4)      # pos 13: block 3 live
        assert (added, dropped) == ([], [])
        assert pool.slot_pos[0] == 13
        pool.reconcile()

    def test_commit_plain_round_crosses_boundary(self):
        # a 0-draft round is a plain decode step: commit(slot, 1)
        # must map the next write position's block exactly when it
        # crosses into an unmapped one, like extend()
        pool = self._pool()
        pool.admit(0, np.arange(8, dtype=np.int32), 8)  # pos 8, 3 blks
        for want_pos in (9, 10, 11):
            added, dropped = pool.commit(0, 1)
            assert (added, dropped) == ([], [])
            assert pool.slot_pos[0] == want_pos
        added, dropped = pool.commit(0, 1)      # pos 12 needs block 3
        assert dropped == [] and [b for b, _ in added] == [3]
        assert pool.slot_pos[0] == 12
        pool.reconcile()

    def test_reserve_exhaustion_is_atomic(self):
        pool = self._pool(num_pages=3)
        pool.admit(0, np.arange(9, dtype=np.int32), 9)  # all 3 pages
        before = (pool.slot_pos[0], list(pool.slot_pages[0]),
                  pool.pages_in_use)
        with pytest.raises(PoolExhaustedError):
            pool.reserve(0, 3)
        assert before == (pool.slot_pos[0], list(pool.slot_pages[0]),
                          pool.pages_in_use)
        pool.reconcile()

    def test_rollback_over_shared_blocks_only_drops_refs(self):
        # reserve never maps shared pages (fresh allocs only), but the
        # rollback path must stay refcount-honest when it crosses
        # blocks a slot shares with the prefix cache: commit's decref
        # on a shared page drops ONE ref, freeing nothing
        pool = self._pool(prefix_cache=True)
        toks = np.arange(9, dtype=np.int32)
        pool.admit(0, toks, 9)
        pool.register(0, toks, 9)               # blocks 0,1 published
        pool.admit(1, toks.copy(), 9)           # shares blocks 0,1
        shared = pool.slot_pages[1][1]
        assert shared == pool.slot_pages[0][1]
        in_use = pool.pages_in_use
        pool.slot_pos[1] = 3                    # adversarial rewind
        added, dropped = pool.commit(1, 0)      # keep=1: drop blks 1,2
        assert (added, dropped) == ([], [1, 2])
        # only slot 1's private page was freed; the shared page
        # survives for slot 0 and the cache
        assert pool.pages_in_use == in_use - 1
        assert pool.slot_pages[0][1] == shared
        pool.release(1)
        pool.release(0)
        pool.reconcile()


class TestSpeculativeServe:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_greedy_parity_with_baseline_serve(self, eng):
        ps = spec_prompts()
        want = eng.serve([p.copy() for p in ps], max_new=14)
        base_steps = eng.last_stats.steps
        got = eng.serve([p.copy() for p in ps], max_new=14,
                        speculative=True)
        assert got == want
        st = eng.last_stats
        # the repetitive prompts must actually speculate (real
        # acceptance), and the ledger must reconcile
        assert st.draft_proposed > 0
        assert 0 < st.draft_accepted <= st.draft_proposed
        assert st.spec_rounds == st.steps
        assert st.tokens == sum(len(g) for g in got)
        assert st.spec_rounds < base_steps      # fewer launches

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_eos_and_logprob_parity(self, params, eng):
        ps = spec_prompts(seed=3)[:3]
        # pick an eos that actually fires early: the most common
        # first generated token (probed on the WARM shared engine —
        # same prompt lengths, no fresh compiles)
        firsts = [g[0] for g in
                  eng.serve([p.copy() for p in ps], max_new=1)]
        eos = max(set(firsts), key=firsts.count)
        e = DecodeEngine(params, CFG, slots=2, max_len=48, eos_id=eos)
        want, want_lp = e.serve([p.copy() for p in ps], max_new=10,
                                return_logprobs=True)
        got, got_lp = e.serve([p.copy() for p in ps], max_new=10,
                              return_logprobs=True, speculative=True)
        assert got == want
        for a, b in zip(got_lp, want_lp):
            np.testing.assert_allclose(a, b, rtol=0, atol=2e-5)

    @pytest.mark.slow

    def test_chaos_transfer_guard_parity(self, eng):
        """THE chaos gate: the full speculative loop — host drafting,
        page reserve, the jitted verify round, commit/rollback
        re-maps — under transfer_guard('disallow'), token-identical
        to the plain guarded loop. Any implicit host<->device staging
        in the new path dies here."""
        ps = spec_prompts(seed=5)
        with jax.transfer_guard("disallow"):
            want = eng.serve([p.copy() for p in ps], max_new=12)
            got = eng.serve([p.copy() for p in ps], max_new=12,
                            speculative=True)
        assert got == want

    @pytest.mark.slow  # tier-1 budget guard: drifted past 10s on the
    # 1-vCPU runner; the spec lane still runs it
    def test_sampled_requests_reproducible_and_bounded(self, eng):
        """Sampled speculative serving: draws differ from the plain
        loop's per-token stream (documented round-stream boundary)
        but must be reproducible per seed and respect max_new; greedy
        co-tenants keep exact parity beside them."""
        ps = spec_prompts(seed=7)
        sampling = [{"temperature": 0.8, "top_k": 20, "seed": 11},
                    None,
                    {"temperature": 0.6, "top_p": 0.9, "seed": 12},
                    None]
        runs = [eng.serve([p.copy() for p in ps], max_new=9,
                          sampling=sampling, speculative=True)
                for _ in range(2)]
        assert runs[0] == runs[1]
        assert all(len(g) == 9 for g in runs[0])
        want = eng.serve([p.copy() for p in ps], max_new=9)
        for i in (1, 3):                         # the greedy rows
            assert runs[0][i] == want[i]

    @pytest.mark.slow

    def test_oversubscribed_pool_preempts_and_recovers(self, params):
        """Commit's boundary alloc can exhaust an over-subscribed
        pool mid-round: the loop must preempt/retire through the same
        policy path as the plain loop and still hand every request
        its exact greedy tokens."""
        ps = spec_prompts(seed=9)
        e = DecodeEngine(params, CFG, slots=2, max_len=48,
                         num_pages=7, prefix_cache=False)
        want = e.serve([p.copy() for p in ps], max_new=12)
        got = e.serve([p.copy() for p in ps], max_new=12,
                      speculative=True)
        assert got == want

    def test_speculative_guards(self, params):
        eng = DecodeEngine(params, CFG, slots=2, max_len=32,
                           select_fn=lambda lg, r: lg.argmax(-1))
        with pytest.raises(ValueError, match="select_fn"):
            eng.serve(spec_prompts()[:1], max_new=2, speculative=True)
        wcfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2,
                                   n_heads=4, attn_impl="dense",
                                   attn_window=16)
        wparams = T.init_params(jax.random.key(1), wcfg)
        weng = DecodeEngine(wparams, wcfg, slots=2, max_len=32)
        with pytest.raises(ValueError, match="paged"):
            weng.serve(spec_prompts()[:1], max_new=2, speculative=True)

    def test_draft_len_policy_clamps(self):
        pol = SchedulerPolicy()
        assert pol.draft_len(pos=10, max_len=48, remaining=9) == 4
        assert pol.draft_len(pos=45, max_len=48, remaining=9) == 2
        assert pol.draft_len(pos=47, max_len=48, remaining=9) == 0
        assert pol.draft_len(pos=10, max_len=48, remaining=1) == 0
        assert pol.draft_len(pos=10, max_len=48, remaining=3) == 2


class TestSpeculativeServer:
    def test_server_parity_and_counters(self, eng):
        """ServingServer(speculative=True): same tokens as the plain
        reliability loop, spec ledger in counters() (including the
        float acceptance_rate the obs registry exports as a gauge),
        books reconciled."""
        from paddle_tpu.obs import MetricsRegistry
        from paddle_tpu.serve.server import ServingServer

        ps = spec_prompts()
        base = ServingServer(eng, max_queue=16)
        want = {base.submit(p.copy(), max_new=12): None for p in ps}
        res = base.run()
        base.reconcile()
        want = {rid: res[rid].tokens for rid in want}

        srv = ServingServer(eng, max_queue=16, speculative=True)
        reg = MetricsRegistry()
        srv.bind_metrics(reg)
        ids = [srv.submit(p.copy(), max_new=12) for p in ps]
        res2 = srv.run()
        srv.reconcile()
        for rid, base_rid in zip(ids, want):
            assert res2[rid].outcome == "completed"
            assert res2[rid].tokens == want[base_rid]
        c = srv.counters()
        assert c["spec_rounds"] == srv.stats.steps > 0
        assert 0 < c["draft_accepted"] <= c["draft_proposed"]
        assert c["acceptance_rate"] == pytest.approx(
            c["draft_accepted"] / c["draft_proposed"])
        assert c["spec_reserved"] >= c["spec_rolled_back"] >= 0
        # the whole spec ledger reaches the metrics registry through
        # the bound counters() source
        names = {row["name"]: row["value"]
                 for row in reg.snapshot()["series"]}
        for k in ("serve_draft_proposed", "serve_draft_accepted",
                  "serve_acceptance_rate", "serve_spec_rounds"):
            assert k in names, sorted(names)
        assert names["serve_draft_proposed"] == c["draft_proposed"]

    def test_server_guards(self, params):
        from paddle_tpu.serve.server import ServingServer

        eng = DecodeEngine(params, CFG, slots=2, max_len=32,
                           select_fn=lambda lg, r: lg.argmax(-1))
        with pytest.raises(ValueError, match="select_fn"):
            ServingServer(eng, speculative=True)


class TestSpecFleetChaos:
    def test_midburst_kill_counters_reconcile_exactly_once(self, eng,
                                                           params):
        """THE exactly-once gate for the spec ledger: kill a replica
        mid-burst while every replica serves speculatively. Every
        request still ends completed with its exact greedy tokens,
        and the fleet's draft/acceptance counters equal the dead
        replica's banked contribution plus the survivors' live ones —
        counted once, never lost with the device, never re-added."""
        from paddle_tpu.serve.router import ServingRouter
        from paddle_tpu.serve.server import ServingServer
        from paddle_tpu.testing.faults import FaultPlan, ManualClock

        eng2 = DecodeEngine(params, CFG, slots=2, max_len=48)
        clk = ManualClock()
        plan = FaultPlan(router_kill_decode_at=1)
        servers = [
            ServingServer(plan.wrap_replica_engine(eng, clock=clk),
                          max_queue=16, clock=clk, max_retries=2,
                          speculative=True),
            ServingServer(eng2, max_queue=16, clock=clk,
                          max_retries=2, speculative=True),
        ]
        router = ServingRouter(servers, clock=clk)
        ps = spec_prompts(seed=13)
        ids = [router.submit(p.copy(), max_new=10) for p in ps]
        res = router.run()
        router.reconcile()
        assert plan.count("replicakill") == 1
        for p, rid in zip(ps, ids):
            assert res[rid].outcome == "completed"
            # parity oracle: the warm engine's own speculative serve
            solo = eng.serve([p.copy()], max_new=10)[0]
            assert res[rid].tokens == solo
        c = router.counters()
        # exactly-once: the aggregate equals banked-dead + live sums,
        # re-derived from the primary sources
        live = [rep.server.counters() for rep in router.replicas
                if rep.alive]
        for k in ("draft_proposed", "draft_accepted", "spec_rounds",
                  "spec_reserved", "spec_rolled_back"):
            want = (router._dead_base.get(k, 0)
                    + sum(s[k] for s in live))
            assert c[f"fleet_{k}"] == want, k
        assert c["fleet_acceptance_rate"] == pytest.approx(
            c["fleet_draft_accepted"]
            / max(c["fleet_draft_proposed"], 1))
        assert c["fleet_draft_proposed"] > 0
