"""Pipeline parallelism (GPipe-style scan+ppermute over the pipe axis).

No reference counterpart (SURVEY §2.8: pipeline absent upstream) — this
is the TPU-native extra completing {dp, tp, sp, ep, pp}; correctness is
checked against the sequential stage application and its gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.parallel import pipeline as PP


def _mesh(n=4):
    devs = jax.devices()[:n]
    return jax.sharding.Mesh(np.array(devs), (PP.PIPE_AXIS,))


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_params(s=4, f=6, seed=0):
    r = np.random.RandomState(seed)
    per_stage = [{"w": jnp.asarray(r.randn(f, f) * 0.5, jnp.float32),
                  "b": jnp.asarray(r.randn(f) * 0.1, jnp.float32)}
                 for _ in range(s)]
    return per_stage, PP.stack_stage_params(per_stage)


def _sequential_ref(per_stage, micro_x):
    out = []
    for x in micro_x:
        for p in per_stage:
            x = _stage_fn(p, x)
        out.append(x)
    return jnp.stack(out)


def test_pipeline_forward_matches_sequential():
    mesh = _mesh(4)
    per_stage, stacked = _make_params(4, 6)
    stacked = PP.shard_stage_params(stacked, mesh)
    micro_x = jnp.asarray(np.random.RandomState(1).randn(5, 3, 6),
                          jnp.float32)
    fwd = PP.make_pipeline_forward(_stage_fn, mesh)
    got = jax.jit(fwd)(stacked, micro_x)
    want = _sequential_ref(per_stage, micro_x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_single_microbatch_and_m_less_than_stages():
    mesh = _mesh(4)
    per_stage, stacked = _make_params(4, 5, seed=2)
    stacked = PP.shard_stage_params(stacked, mesh)
    for m in (1, 2):
        micro_x = jnp.asarray(np.random.RandomState(m).randn(m, 2, 5),
                              jnp.float32)
        # graftlint: disable=GL004(each m is a distinct static shape —
        # one deliberate compile per loop iteration)
        got = jax.jit(PP.make_pipeline_forward(_stage_fn, mesh))(
            stacked, micro_x)
        want = _sequential_ref(per_stage, micro_x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential():
    """Autodiff through scan+ppermute equals the plain chain-rule grads."""
    mesh = _mesh(4)
    per_stage, stacked = _make_params(4, 4, seed=3)
    sharded = PP.shard_stage_params(stacked, mesh)
    micro_x = jnp.asarray(np.random.RandomState(4).randn(3, 2, 4),
                          jnp.float32)
    target = jnp.ones((3, 2, 4), jnp.float32)

    fwd = PP.make_pipeline_forward(_stage_fn, mesh)

    def pipe_loss(p):
        return jnp.mean((fwd(p, micro_x) - target) ** 2)

    def seq_loss(stacked_p):
        per = [jax.tree.map(lambda x: x[i], stacked_p) for i in range(4)]
        return jnp.mean((_sequential_ref(per, micro_x) - target) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(sharded)
    g_seq = jax.grad(seq_loss)(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=3e-4, atol=3e-5)


def test_pipeline_train_step_learns():
    mesh = _mesh(4)
    _, stacked = _make_params(4, 4, seed=5)
    stacked = PP.shard_stage_params(stacked, mesh)
    opt = optim.adam(3e-2)
    opt_state = opt.init(stacked)
    micro_x = jnp.asarray(np.random.RandomState(6).randn(4, 2, 4),
                          jnp.float32)
    target = jnp.asarray(np.random.RandomState(7).randn(4, 2, 4) * 0.3,
                         jnp.float32)

    step = PP.make_pipeline_train_step(
        _stage_fn, lambda out, y: jnp.mean((out - y) ** 2), opt, mesh)
    losses = []
    params = stacked
    for i in range(30):
        params, opt_state, loss = step(params, opt_state, micro_x, target,
                                       jnp.asarray(i, jnp.int32))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])
    # stage params stay sharded over the pipe axis through the update
    spec = params["w"].sharding.spec
    assert spec[0] == PP.PIPE_AXIS, spec
