"""Sequence-op tests: packed segment ops vs straightforward per-sequence
numpy computation (the topology-equivalence test style, reference:
gserver/tests/test_RecurrentGradientMachine.cpp comparing nested vs plain)."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.data import batch as B
from paddle_tpu.ops import sequence as S


@pytest.fixture
def packed(np_rng):
    seqs = [np_rng.randn(n, 3).astype(np.float32) for n in [4, 2, 5]]
    sb = B.pack_sequences(seqs, capacity=16, max_seqs=4)
    return seqs, sb


class TestSegmentPooling:
    def test_sum(self, packed):
        seqs, sb = packed
        out = S.sequence_sum(jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids), 4)
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(out[i], s.sum(0), rtol=1e-5)
        np.testing.assert_allclose(out[3], 0.0)

    def test_mean(self, packed):
        seqs, sb = packed
        out = S.sequence_mean(jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids), 4)
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(out[i], s.mean(0), rtol=1e-5)

    def test_max(self, packed):
        seqs, sb = packed
        out = S.sequence_max(jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids), 4)
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(out[i], s.max(0), rtol=1e-5)
        np.testing.assert_allclose(out[3], 0.0)  # empty slot zeroed

    def test_first_last(self, packed):
        seqs, sb = packed
        first = S.sequence_first(
            jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids),
            jnp.asarray(sb.positions), 4,
        )
        last = S.sequence_last(
            jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids),
            jnp.asarray(sb.positions), jnp.asarray(sb.lengths), 4,
        )
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(first[i], s[0], rtol=1e-6)
            np.testing.assert_allclose(last[i], s[-1], rtol=1e-6)

    def test_softmax_per_sequence(self, np_rng):
        seqs = [np_rng.randn(n).astype(np.float32) for n in [3, 5]]
        sb = B.pack_sequences(seqs, capacity=8, max_seqs=2)
        out = S.sequence_softmax(jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids), 2)
        out = np.asarray(out)
        # each segment sums to 1, padding exactly 0
        np.testing.assert_allclose(out[:3].sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(out[3:8].sum(), 1.0, rtol=1e-5)
        e0 = np.exp(seqs[0] - seqs[0].max())
        np.testing.assert_allclose(out[:3], e0 / e0.sum(), rtol=1e-5)

    def test_expand(self, packed):
        seqs, sb = packed
        vals = jnp.asarray(np.arange(4 * 3, dtype=np.float32).reshape(4, 3))
        out = S.sequence_expand(vals, jnp.asarray(sb.segment_ids), 4)
        np.testing.assert_allclose(out[0], vals[0])
        np.testing.assert_allclose(out[4], vals[1])  # second sequence start
        np.testing.assert_allclose(np.asarray(out)[~sb.mask], 0.0)


class TestDenseHelpers:
    def test_pack_to_dense_roundtrip(self, packed):
        seqs, sb = packed
        dense, mask = S.pack_to_dense(
            jnp.asarray(sb.tokens), jnp.asarray(sb.segment_ids),
            jnp.asarray(sb.positions), 4, 6,
        )
        assert dense.shape == (4, 6, 3)
        for i, s in enumerate(seqs):
            np.testing.assert_allclose(dense[i, : len(s)], s, rtol=1e-6)
            assert bool(mask[i, : len(s)].all())
            assert not bool(mask[i, len(s):].any())
        back = S.dense_to_pack(
            dense, jnp.asarray(sb.segment_ids), jnp.asarray(sb.positions), 4
        )
        np.testing.assert_allclose(
            np.asarray(back)[sb.mask], sb.tokens[sb.mask], rtol=1e-6
        )

    def test_dense_pool_modes(self, np_rng):
        x = np_rng.randn(2, 5, 3).astype(np.float32)
        lengths = np.asarray([3, 5], np.int32)
        xs = jnp.asarray(x)
        for mode, ref in [
            ("sum", lambda s: s.sum(0)),
            ("mean", lambda s: s.mean(0)),
            ("max", lambda s: s.max(0)),
            ("last", lambda s: s[-1]),
            ("first", lambda s: s[0]),
        ]:
            out = S.dense_sequence_pool(xs, jnp.asarray(lengths), mode)
            for i, n in enumerate(lengths):
                np.testing.assert_allclose(
                    np.asarray(out)[i], ref(x[i, :n]), rtol=1e-5,
                    err_msg=f"mode {mode} seq {i}",
                )

    def test_pool_unknown_mode(self):
        with pytest.raises(ValueError):
            S.dense_sequence_pool(jnp.ones((1, 2, 3)), jnp.asarray([2]), "nope")
