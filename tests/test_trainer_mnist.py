"""End-to-end convergence: LeNet/MLP on synthetic MNIST — the 'book test'
(reference: python/paddle/v2/fluid/tests/book/test_recognize_digits_mlp.py,
v1_api_demo/mnist/api_train.py)."""

import jax.numpy as jnp
import numpy as np

from paddle_tpu import data, models, nn, optim
from paddle_tpu.data import datasets, reader as R
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses, metrics
from paddle_tpu.train import Trainer, events as E


def _mnist_batches(batch_size=32, n=512, mode="train"):
    r = R.shuffle(datasets.mnist(mode, synthetic_n=n, seed=0), 256, seed=1)
    br = data.batch_reader(r, batch_size)
    feeder = data.DataFeeder()
    return lambda: feeder(br)


def test_mlp_converges():
    model = models.lenet.mlp(10, hidden=(64,))
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)
        ),
        optimizer=optim.adam(1e-3),
        metrics_fn=lambda logits, labels: {"acc": metrics.accuracy(logits, labels)},
        seed=0,
    )
    state = trainer.init_state(ShapeSpec((32, 28, 28, 1)))

    seen = {"first": None, "last": None, "events": 0}

    def handler(ev):
        if isinstance(ev, E.EndIteration):
            if seen["first"] is None:
                seen["first"] = ev.cost
            seen["last"] = ev.cost
            seen["events"] += 1

    state = trainer.train(
        state, _mnist_batches(), num_passes=3, event_handler=handler
    )
    assert seen["events"] > 0
    assert seen["last"] < seen["first"] * 0.5, (seen["first"], seen["last"])

    # eval accuracy on held-out synthetic digits should beat chance by a lot
    res = trainer.evaluate(state, _mnist_batches(mode="test", n=256))
    assert res.metrics["acc"] > 0.5, res


def test_lenet_one_step_runs():
    model = models.lenet.lenet(10, with_bn=True)
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)
        ),
        optimizer=optim.momentum(0.01, mu=0.9),
        seed=0,
    )
    state = trainer.init_state(ShapeSpec((8, 28, 28, 1)))
    batches = _mnist_batches(batch_size=8, n=16)
    state = trainer.train(state, batches, num_passes=1)
    assert int(state.step) == 2  # 16 samples / 8 per batch
    # BN running stats moved
    bn_means = [
        v for name, v in _named(state.model_state) if name.endswith("mean")
    ]
    assert any(float(np.abs(np.asarray(m)).sum()) > 0 for m in bn_means)


def _real_mnist_present() -> bool:
    import os

    from paddle_tpu.data.datasets import _mnist_files

    return all(os.path.exists(p) for p in _mnist_files("train")) and all(
        os.path.exists(p) for p in _mnist_files("test"))


def _run_lenet_convergence(real: bool):
    n = 10_000 if real else 1024
    model = models.lenet.lenet(10, with_bn=False)
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)
        ),
        optimizer=optim.adam(1e-3),
        metrics_fn=lambda logits, labels: {
            "acc": metrics.accuracy(logits, labels)},
        seed=0,
    )
    state = trainer.init_state(ShapeSpec((64, 28, 28, 1)))

    def batches(mode="train", bn=n):
        r = R.firstn(datasets.mnist(mode, synthetic_n=bn, seed=0), bn)
        r = R.shuffle(r, 1024, seed=1)
        feeder = data.DataFeeder()
        return lambda: feeder(data.batch_reader(r, 64))

    state = trainer.train(state, batches(), num_passes=2)
    res = trainer.evaluate(
        state, batches(mode="test", bn=2_000 if real else 512))
    bar = 0.95 if real else 0.9
    assert res.metrics["acc"] >= bar, (
        f"{'real' if real else 'synthetic'} MNIST LeNet accuracy "
        f"{res.metrics['acc']:.4f} below bar {bar}")


def test_lenet_convergence_parity():
    """The BASELINE 'MNIST LeNet convergence parity' target (reference:
    v1_api_demo/mnist/api_train.py trains LeNet to ~99% / the book test
    test_recognize_digits_mlp.py asserts >90% in a few passes).

    Requires real MNIST idx .gz files under PADDLE_TPU_DATA_HOME (see
    README "Real datasets"); SKIPS — loudly, not a lowered-bar pass —
    when they are absent. The always-on synthetic counterpart is
    test_lenet_convergence_synthetic below.
    """
    import pytest

    if not _real_mnist_present():
        pytest.skip(
            "real MNIST idx files not under PADDLE_TPU_DATA_HOME — "
            "parity vs the reference demo needs real data (zero-egress "
            "env cannot download it); see README 'Real datasets'")
    _run_lenet_convergence(real=True)


def test_lenet_convergence_synthetic():
    """Same pipeline on the synthetic surrogate (always runs; bar 0.9)."""
    _run_lenet_convergence(real=False)


def _named(tree, prefix=""):
    for k, v in tree.items():
        name = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            yield from _named(v, name)
        else:
            yield name, v
