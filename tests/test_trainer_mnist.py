"""End-to-end convergence: LeNet/MLP on synthetic MNIST — the 'book test'
(reference: python/paddle/v2/fluid/tests/book/test_recognize_digits_mlp.py,
v1_api_demo/mnist/api_train.py)."""

import jax.numpy as jnp
import numpy as np

from paddle_tpu import data, models, nn, optim
from paddle_tpu.data import datasets, reader as R
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses, metrics
from paddle_tpu.train import Trainer, events as E


def _mnist_batches(batch_size=32, n=512, mode="train"):
    r = R.shuffle(datasets.mnist(mode, synthetic_n=n, seed=0), 256, seed=1)
    br = data.batch_reader(r, batch_size)
    feeder = data.DataFeeder()
    return lambda: feeder(br)


def test_mlp_converges():
    model = models.lenet.mlp(10, hidden=(64,))
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)
        ),
        optimizer=optim.adam(1e-3),
        metrics_fn=lambda logits, labels: {"acc": metrics.accuracy(logits, labels)},
        seed=0,
    )
    state = trainer.init_state(ShapeSpec((32, 28, 28, 1)))

    seen = {"first": None, "last": None, "events": 0}

    def handler(ev):
        if isinstance(ev, E.EndIteration):
            if seen["first"] is None:
                seen["first"] = ev.cost
            seen["last"] = ev.cost
            seen["events"] += 1

    state = trainer.train(
        state, _mnist_batches(), num_passes=3, event_handler=handler
    )
    assert seen["events"] > 0
    assert seen["last"] < seen["first"] * 0.5, (seen["first"], seen["last"])

    # eval accuracy on held-out synthetic digits should beat chance by a lot
    res = trainer.evaluate(state, _mnist_batches(mode="test", n=256))
    assert res.metrics["acc"] > 0.5, res


def test_lenet_one_step_runs():
    model = models.lenet.lenet(10, with_bn=True)
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)
        ),
        optimizer=optim.momentum(0.01, mu=0.9),
        seed=0,
    )
    state = trainer.init_state(ShapeSpec((8, 28, 28, 1)))
    batches = _mnist_batches(batch_size=8, n=16)
    state = trainer.train(state, batches, num_passes=1)
    assert int(state.step) == 2  # 16 samples / 8 per batch
    # BN running stats moved
    bn_means = [
        v for name, v in _named(state.model_state) if name.endswith("mean")
    ]
    assert any(float(np.abs(np.asarray(m)).sum()) > 0 for m in bn_means)


def _named(tree, prefix=""):
    for k, v in tree.items():
        name = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            yield from _named(v, name)
        else:
            yield name, v
