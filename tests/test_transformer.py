"""Transformer LM tests: causality, training signal, KV-cache decode
consistency, and dp x tp sharded-step equivalence on the 8-CPU mesh.

The model has no reference counterpart (the reference predates
transformers); these tests follow the same strategies SURVEY §4 lists —
impl-vs-impl equivalence (KV-cache decode vs teacher forcing, sharded vs
single-device) and gradient checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import transformer as T


CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture
def params():
    return T.init_params(jax.random.key(0), CFG)


def test_shapes_and_finite(params):
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 61, (3, 12)))
    logits = T.apply(params, CFG, toks)
    assert logits.shape == (3, 12, 61)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_causality(params):
    """Logits at position t must not depend on tokens after t."""
    rs = np.random.RandomState(1)
    a = rs.randint(0, 61, (1, 10))
    b = a.copy()
    b[0, 7:] = (b[0, 7:] + 5) % 61  # perturb the future
    la = T.apply(params, CFG, jnp.asarray(a))
    lb = T.apply(params, CFG, jnp.asarray(b))
    np.testing.assert_allclose(la[0, :7], lb[0, :7], rtol=1e-5, atol=1e-5)
    assert float(jnp.max(jnp.abs(la[0, 7:] - lb[0, 7:]))) > 1e-4


def test_loss_mask(params):
    toks = jnp.asarray(np.random.RandomState(2).randint(0, 61, (2, 9)))
    full = T.loss(params, CFG, toks)
    short = T.loss(params, CFG, toks, lengths=jnp.asarray([3, 4]))
    assert np.isfinite(float(full)) and np.isfinite(float(short))
    # a loss() that ignores lengths would return the same value
    assert not np.isclose(float(full), float(short))


def test_overfits_tiny_batch(params):
    """A few adam steps on one repeated batch must cut the loss — the
    training-signal smoke the book tests use (SURVEY §4 e2e row)."""
    from paddle_tpu import optim

    toks = jnp.asarray(np.random.RandomState(3).randint(0, 61, (4, 16)))
    opt = optim.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(lambda p: T.loss(p, CFG, toks))(p)
        p2, s2 = opt.update(g, s, p, jnp.zeros((), jnp.int32))
        return p2, s2, l

    first = None
    for _ in range(30):
        params, opt_state, l = step(params, opt_state)
        if first is None:
            first = float(l)
    assert float(l) < first * 0.7, (first, float(l))


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_generate_matches_teacher_forcing(params):
    """KV-cache greedy decode == argmax over apply() at every step (the
    cache path and the full forward are different codepaths)."""
    prompt = jnp.asarray(np.random.RandomState(4).randint(0, 61, (2, 5)))
    steps = 6
    out = T.generate(params, CFG, prompt, steps)
    assert out.shape == (2, 5 + steps)
    np.testing.assert_array_equal(out[:, :5], prompt)
    cur = prompt
    for _ in range(steps):
        logits = T.apply(params, CFG, cur)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_remat_matches(params):
    toks = jnp.asarray(np.random.RandomState(5).randint(0, 61, (2, 8)))
    cfg_r = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                                attn_impl="dense", remat=True)
    np.testing.assert_allclose(T.loss(params, CFG, toks),
                               T.loss(params, cfg_r, toks), rtol=1e-6)
    g0 = jax.grad(lambda p: T.loss(p, CFG, toks))(params)
    g1 = jax.grad(lambda p: T.loss(p, cfg_r, toks))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_tp_sharded_loss_matches(params):
    """dp x tp over the 8-CPU mesh computes the same loss/grads as one
    device (GSPMD inserts the collectives; TP_RULES shard qkv/fc1 by
    output, proj/fc2 by input, lm_head by vocab)."""
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.parallel import sharding as shard_lib

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, model=4))
    toks = jnp.asarray(np.random.RandomState(6).randint(0, 61, (4, 12)))

    ref_loss = T.loss(params, CFG, toks)
    ref_grad = jax.grad(lambda p: T.loss(p, CFG, toks))(params)

    sh = shard_lib.make_param_shardings(params, mesh, T.TP_RULES)
    p_sharded = jax.device_put(params, sh)
    # at least one leaf actually sharded over the model axis
    specs = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: s.spec, sh,
                               is_leaf=lambda x: hasattr(x, "spec")))
    assert any("model" in str(s) for s in specs)

    # no ambient mesh needed: the sharded params carry NamedShardings
    # and GSPMD propagates/inserts collectives
    l = jax.jit(lambda p: T.loss(p, CFG, toks))(p_sharded)
    g = jax.jit(jax.grad(lambda p: T.loss(p, CFG, toks)))(p_sharded)
    np.testing.assert_allclose(l, ref_loss, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grad),
                    jax.tree_util.tree_leaves(g)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_transformer_serving_artifact(tmp_path, params):
    """The generic StableHLO artifact path serves the transformer LM
    (weights folded; greedy next-token head)."""
    from paddle_tpu.serve import export_compiled_model, load_compiled_model

    path = str(tmp_path / "lm.ptc")
    toks = jnp.asarray(np.random.RandomState(9).randint(0, 61, (2, 12)))

    def next_token_logits(toks):
        return T.apply(params, CFG, toks)[:, -1]

    export_compiled_model(next_token_logits, [toks], path, name="tiny-lm")
    m = load_compiled_model(path)
    got = m.predict(np.asarray(toks))
    want = next_token_logits(toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


class TestContextParallel:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_cp_loss_matches_dense(self):
        """Sequence-sharded (ring attention) transformer loss must equal
        the single-device dense loss — values and gradients."""
        from paddle_tpu.core import mesh as mesh_lib

        cfg = T.TransformerConfig(vocab=64, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense")
        params = T.init_params(jax.random.key(0), cfg)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=1, seq=4),
            devices=jax.devices()[:8])
        # T = 16 sharded positions + 1 for targets
        toks_h = np.random.RandomState(0).randint(0, 64, (4, 17)) \
            .astype(np.int32)
        toks = jax.device_put(
            toks_h, jax.NamedSharding(mesh, jax.sharding.PartitionSpec(
                mesh_lib.DATA_AXIS, None)))
        cp_loss = T.make_context_parallel_loss(
            cfg, mesh, batch_axis=mesh_lib.DATA_AXIS)

        dense = float(T.loss(params, cfg, jnp.asarray(toks_h)))
        cp = float(jax.jit(cp_loss)(params, toks))
        assert abs(dense - cp) < 1e-4, (dense, cp)

        g_dense = jax.grad(lambda p: T.loss(p, cfg, jnp.asarray(toks_h)))(
            params)
        g_cp = jax.jit(jax.grad(cp_loss))(params, toks)
        for a, b in zip(jax.tree_util.tree_leaves(g_dense),
                        jax.tree_util.tree_leaves(g_cp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_cp_with_remat_and_lengths(self):
        from paddle_tpu.core import mesh as mesh_lib

        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  remat=True)
        params = T.init_params(jax.random.key(1), cfg)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=1, model=1, seq=8),
            devices=jax.devices()[:8])
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 32, (2, 25)), jnp.int32)
        lens = jnp.asarray([24, 17])
        cp_loss = T.make_context_parallel_loss(cfg, mesh)
        dense = float(T.loss(params, cfg, toks, lens))
        cp = float(jax.jit(cp_loss)(params, toks, lens))
        assert abs(dense - cp) < 1e-4, (dense, cp)


    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_cp_matches_dense_under_bf16_policy(self):
        """The f32-scores invariant must hold inside ring attention too:
        under the bf16 compute policy CP and dense stay within bf16
        round-off of each other."""
        from paddle_tpu.core import dtypes
        from paddle_tpu.core import mesh as mesh_lib

        cfg = T.TransformerConfig(vocab=64, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense")
        params = T.init_params(jax.random.key(2), cfg)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=1, model=1, seq=8),
            devices=jax.devices()[:8])
        toks = jnp.asarray(
            np.random.RandomState(2).randint(0, 64, (2, 33)), jnp.int32)
        old = dtypes.default_policy()
        dtypes.set_default_policy(dtypes.bf16_compute_policy())
        try:
            cp_loss = T.make_context_parallel_loss(cfg, mesh)
            dense = float(T.loss(params, cfg, toks))
            cp = float(jax.jit(cp_loss)(params, toks))
        finally:
            dtypes.set_default_policy(old)
        assert abs(dense - cp) < 3e-2 * max(1.0, abs(dense)), (dense, cp)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_cp_composes_with_moe(self):
        """Context parallelism and MoE blocks in one model: the seq-
        sharded loss must still equal the single-device loss (routing is
        over the same global token set either way)."""
        from paddle_tpu.core import mesh as mesh_lib

        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_capacity_factor=8.0)
        params = T.init_params(jax.random.key(3), cfg)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=1, model=1, seq=8),
            devices=jax.devices()[:8])
        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 32, (2, 33)), jnp.int32)
        cp_loss = T.make_context_parallel_loss(cfg, mesh)
        dense = float(T.loss(params, cfg, toks))
        cp = float(jax.jit(cp_loss)(params, toks))
        assert abs(dense - cp) < 1e-4, (dense, cp)


class TestSampling:
    CFG = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                              mlp_ratio=2, attn_impl="dense")

    @pytest.mark.slow

    def test_temperature_zero_is_greedy(self):
        params = T.init_params(jax.random.key(0), self.CFG)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (3, 5)), jnp.int32)
        greedy = T.generate(params, self.CFG, prompt, steps=6)
        sampled = T.sample(params, self.CFG, prompt, steps=6,
                           rng=jax.random.key(1), temperature=0.0)
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.asarray(sampled))

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_sampling_deterministic_per_key_and_varies(self):
        params = T.init_params(jax.random.key(0), self.CFG)
        prompt = jnp.zeros((2, 4), jnp.int32)
        a = T.sample(params, self.CFG, prompt, steps=8,
                     rng=jax.random.key(7), temperature=1.5)
        b = T.sample(params, self.CFG, prompt, steps=8,
                     rng=jax.random.key(7), temperature=1.5)
        c = T.sample(params, self.CFG, prompt, steps=8,
                     rng=jax.random.key(8), temperature=1.5)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_top_k_and_top_p_filters(self):
        # direct selector check on a known distribution
        logits = jnp.log(jnp.asarray(
            [[0.5, 0.3, 0.15, 0.05]], jnp.float32))
        draws = []
        sel = T.make_sampler(top_k=2)
        for i in range(64):
            draws.append(int(sel(logits, jax.random.key(i))[0]))
        assert set(draws) <= {0, 1}
        draws = []
        sel = T.make_sampler(top_p=0.6)
        for i in range(64):
            draws.append(int(sel(logits, jax.random.key(i))[0]))
        # nucleus 0.6: token 0 (mass 0.5, preceding 0) and token 1
        # (preceding 0.5 < 0.6) survive; token 2 (preceding 0.8) doesn't
        assert set(draws) <= {0, 1}
        # extreme: tiny top_p keeps only the argmax
        sel = T.make_sampler(top_p=1e-6)
        assert int(sel(logits, jax.random.key(0))[0]) == 0

    def test_sampler_validation_and_combined_filters(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="top_k"):
            T.make_sampler(top_k=0)
        with _pytest.raises(ValueError, match="top_p"):
            T.make_sampler(top_p=0.0)
        with _pytest.raises(ValueError, match="top_p"):
            T.make_sampler(top_p=1.5)
        # combined: nucleus over the top-k-filtered distribution.
        # probs .4/.3/.2/.1 -> top_k=3 renormalizes to .444/.333/.222;
        # top_p=.5 then keeps tokens 0 (preceding 0) and 1 (preceding
        # .444 < .5) but NOT 2 (preceding .777)
        logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
        sel = T.make_sampler(top_k=3, top_p=0.5)
        draws = {int(sel(logits, jax.random.key(i))[0]) for i in range(64)}
        assert draws == {0, 1}, draws

    def test_top_k_beyond_vocab_is_noop(self):
        # k >= vocab must degrade to no filtering, not index OOB
        logits = jnp.log(jnp.asarray(
            [[0.4, 0.3, 0.2, 0.1]], jnp.float32))
        sel = T.make_sampler(top_k=9)
        draws = {int(sel(logits, jax.random.key(i))[0]) for i in range(96)}
        assert draws == {0, 1, 2, 3}, draws

    @pytest.mark.slow

    def test_eos_stops_generation(self):
        """After a row emits eos, every later position is pad."""
        params = T.init_params(jax.random.key(0), self.CFG)
        prompt = jnp.asarray(
            np.random.RandomState(4).randint(0, 32, (4, 5)), jnp.int32)
        # pick the greedy run's own 2nd generated token as "eos" for row0
        free = np.asarray(T.generate(params, self.CFG, prompt, steps=8))
        eos = int(free[0, 5 + 1])
        out = np.asarray(T.generate(params, self.CFG, prompt, steps=8,
                                    eos_id=eos, pad_id=0))
        for b in range(out.shape[0]):
            row = out[b, 5:]
            hits = np.where(row == eos)[0]
            if hits.size:
                assert (row[hits[0] + 1:] == 0).all(), (b, row)
        # row 0 definitely hit it at step 1
        assert (out[0, 5 + 2:] == 0).all(), out[0]


class TestVariableLengthPrompts:
    CFG = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                              mlp_ratio=2, attn_impl="dense")

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_padded_row_matches_solo_run(self):
        """A short prompt decoded inside a padded batch must produce
        exactly the continuation it gets when decoded alone."""
        params = T.init_params(jax.random.key(0), self.CFG)
        r = np.random.RandomState(0)
        long_p = r.randint(1, 32, (1, 8)).astype(np.int32)
        short_p = r.randint(1, 32, (1, 5)).astype(np.int32)

        solo = np.asarray(T.generate(params, self.CFG,
                                     jnp.asarray(short_p), steps=6))
        batch = np.zeros((2, 8), np.int32)
        batch[0] = long_p[0]
        batch[1, :5] = short_p[0]
        lens = jnp.asarray([8, 5], jnp.int32)
        out = np.asarray(T.generate(params, self.CFG, jnp.asarray(batch),
                                    steps=6, prompt_lens=lens))
        # row 1's continuation (cols 8..13) == solo continuation (5..10)
        np.testing.assert_array_equal(out[1, 8:], solo[0, 5:11])
        # row 0 (full length) must match an unpadded batch-of-one run
        full = np.asarray(T.generate(params, self.CFG,
                                     jnp.asarray(long_p), steps=6))
        np.testing.assert_array_equal(out[0, 8:], full[0, 8:])

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_variable_length_sampling_matches_solo(self):
        """sample() forwards prompt_lens: with temperature 0 (greedy)
        the padded short row must equal its solo sampled run."""
        params = T.init_params(jax.random.key(1), self.CFG)
        r = np.random.RandomState(1)
        short_p = r.randint(1, 32, (1, 4)).astype(np.int32)
        batch = np.zeros((2, 7), np.int32)
        batch[0] = r.randint(1, 32, 7)
        batch[1, :4] = short_p[0]
        out = np.asarray(T.sample(
            params, self.CFG, jnp.asarray(batch), steps=5,
            rng=jax.random.key(2), temperature=0.0,
            prompt_lens=jnp.asarray([7, 4], jnp.int32)))
        solo = np.asarray(T.sample(params, self.CFG, jnp.asarray(short_p),
                                   steps=5, rng=jax.random.key(2),
                                   temperature=0.0))
        np.testing.assert_array_equal(out[1, 7:], solo[0, 4:9])

    @pytest.mark.slow

    def test_flash_prefill_matches_dense_prefill(self):
        """attn_impl='flash' + prompt_lens: the prefill rides the Pallas
        kernel's per-row key-length bound and must reproduce the dense
        masked prefill's continuations exactly."""
        import dataclasses as dc
        params = T.init_params(jax.random.key(3), self.CFG)
        r = np.random.RandomState(3)
        batch = np.zeros((2, 8), np.int32)
        batch[0] = r.randint(1, 32, 8)
        batch[1, :5] = r.randint(1, 32, 5)
        lens = jnp.asarray([8, 5], jnp.int32)
        dense = np.asarray(T.generate(params, self.CFG, jnp.asarray(batch),
                                      steps=3, prompt_lens=lens))
        flash_cfg = dc.replace(self.CFG, attn_impl="flash")
        flash = np.asarray(T.generate(params, flash_cfg, jnp.asarray(batch),
                                      steps=3, prompt_lens=lens))
        np.testing.assert_array_equal(flash, dense)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_padded_row_matches_solo_with_moe(self):
        """Pad positions must not claim MoE expert capacity: at a
        no-drop capacity the padded short row still equals its solo
        continuation through sparse blocks."""
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense",
                                  moe_experts=4, moe_capacity_factor=8.0)
        params = T.init_params(jax.random.key(2), cfg)
        r = np.random.RandomState(2)
        short_p = r.randint(1, 32, (1, 5)).astype(np.int32)
        batch = np.zeros((2, 8), np.int32)
        batch[0] = r.randint(1, 32, 8)
        batch[1, :5] = short_p[0]
        out = np.asarray(T.generate(
            params, cfg, jnp.asarray(batch), steps=4,
            prompt_lens=jnp.asarray([8, 5], jnp.int32)))
        solo = np.asarray(T.generate(params, cfg, jnp.asarray(short_p),
                                     steps=4))
        np.testing.assert_array_equal(out[1, 8:], solo[0, 5:9])


class TestBeamDecode:
    CFG = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                              mlp_ratio=2, attn_impl="dense")

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_beam1_equals_greedy(self):
        params = T.init_params(jax.random.key(0), self.CFG)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, 32, (3, 6)), jnp.int32)
        greedy = np.asarray(T.generate(params, self.CFG, prompt, steps=5))
        seqs, scores = T.beam_decode(params, self.CFG, prompt, steps=5,
                                     beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy)

    @pytest.mark.slow

    def test_beam1_int8_equals_greedy_int8(self):
        """Quantized params stream s8 through the beam loop (r5 shared
        _int8_step_params hook); scoring must match int8 greedy
        exactly (both decode on the same dequantized values)."""
        from paddle_tpu.serve import quant

        params = T.init_params(jax.random.key(5), self.CFG)
        qp = quant.quantize_params(params)
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(1, 32, (2, 6)), jnp.int32)
        greedy = np.asarray(T.generate(qp, self.CFG, prompt, steps=5))
        seqs, _ = T.beam_decode(qp, self.CFG, prompt, steps=5,
                                beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_wider_beam_never_scores_worse(self):
        """The best beam's total log-prob must be >= the greedy
        sequence's (verified with score())."""
        params = T.init_params(jax.random.key(1), self.CFG)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, 32, (2, 6)), jnp.int32)
        steps = 6
        greedy = T.generate(params, self.CFG, prompt, steps=steps)
        seqs, scores = T.beam_decode(params, self.CFG, prompt,
                                     steps=steps, beam_size=4)

        def continuation_logprob(full):
            lp, _ = T.score(params, self.CFG, full)
            return np.asarray(lp)[:, -steps:].sum(axis=1)

        greedy_lp = continuation_logprob(greedy)
        best_lp = continuation_logprob(seqs[:, 0])
        assert (best_lp >= greedy_lp - 1e-4).all(), (greedy_lp, best_lp)
        # the engine's own scores agree with independently recomputed
        # log-probs of the returned sequences
        np.testing.assert_allclose(np.asarray(scores[:, 0]), best_lp,
                                   atol=1e-3)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_single_token_prompt(self):
        """t0 == 1 has nothing to prefill: the caches must start empty
        instead of tracing a T=0 sequence through the blocks, and beam-1
        must still equal greedy from the same one-token prompt."""
        params = T.init_params(jax.random.key(3), self.CFG)
        prompt = jnp.asarray([[5], [17]], jnp.int32)
        greedy = np.asarray(T.generate(params, self.CFG, prompt, steps=4))
        seqs, _ = T.beam_decode(params, self.CFG, prompt, steps=4,
                                beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy)
        # wider beam still runs (the r3 advisor flagged the T=0 prefill)
        seqs2, scores2 = T.beam_decode(params, self.CFG, prompt, steps=4,
                                       beam_size=3)
        assert seqs2.shape == (2, 3, 5)
        assert np.isfinite(np.asarray(scores2)).all()

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_eos_finishes_beams(self):
        params = T.init_params(jax.random.key(2), self.CFG)
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(1, 32, (2, 5)), jnp.int32)
        free = np.asarray(T.beam_decode(params, self.CFG, prompt, steps=6,
                                        beam_size=2)[0])
        eos = int(free[0, 0, 5])  # first continuation token of best beam
        seqs, _ = T.beam_decode(params, self.CFG, prompt, steps=6,
                                beam_size=2, eos_id=eos)
        rows = np.asarray(seqs)[0, :, 5:]
        # step-0 candidates are identical to the free run, so SOME beam
        # must emit the free run's first token (= eos) and finish
        assert (rows == eos).any(), rows
        for row in rows:
            hits = np.where(row == eos)[0]
            if hits.size:  # once finished, only eos follows
                assert (row[hits[0]:] == eos).all(), row


class TestGQA:
    """Grouped-query attention: compact KV caches (the decode-bandwidth
    lever), decode ≡ teacher-forced forward, and training."""

    def _cfg(self, kv):
        return T.TransformerConfig(vocab=32, dim=32, n_layers=2,
                                   n_heads=4, n_kv_heads=kv, mlp_ratio=2,
                                   attn_impl="dense")

    def test_invalid_kv_heads_raises(self):
        with pytest.raises(ValueError, match="n_kv_heads"):
            T.init_params(jax.random.key(0), self._cfg(3))

    def test_param_shapes_compact(self):
        cfg = self._cfg(1)
        params = T.init_params(jax.random.key(0), cfg)
        # H*Dh for q + 2 * Hkv*Dh for k/v = 32 + 2*8
        assert params["blocks"][0]["qkv"]["kernel"].shape == (32, 48)

    def test_full_kv_equals_mha_layout(self):
        # n_kv_heads == n_heads must be exactly the MHA parameterization
        cfg = self._cfg(4)
        params = T.init_params(jax.random.key(0), cfg)
        assert params["blocks"][0]["qkv"]["kernel"].shape == (32, 96)

    @pytest.mark.parametrize("kv", [1, 2])
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_decode_matches_forward(self, kv):
        """Greedy decode's token-by-token cached path must reproduce the
        teacher-forced argmax of the full forward — the grouped cached
        einsums against the whole-sequence attention."""
        cfg = self._cfg(kv)
        params = T.init_params(jax.random.key(1), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, 32, (2, 6)), jnp.int32)
        assert_decode_matches_teacher_forcing(params, cfg, prompt, 4)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_beam1_matches_greedy(self):
        cfg = self._cfg(2)
        params = T.init_params(jax.random.key(2), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, 32, (2, 5)), jnp.int32)
        greedy = np.asarray(T.generate(params, cfg, prompt, steps=4))
        seqs, _ = T.beam_decode(params, cfg, prompt, steps=4, beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy)

    def test_trains(self):
        from paddle_tpu import optim
        cfg = self._cfg(2)
        params = T.init_params(jax.random.key(3), cfg)
        opt = optim.adam(3e-3)
        ostate = opt.init(params)
        base = np.random.RandomState(0).randint(0, 16, (8, 1))
        toks = jnp.asarray((base + np.arange(12)) % 16, jnp.int32)

        @jax.jit
        def step(p, o, t, i):
            l, g = jax.value_and_grad(lambda p: T.loss(p, cfg, t))(p)
            p, o = opt.update(g, o, p, i)
            return p, o, l

        first = last = None
        for i in range(40):
            params, ostate, l = step(params, ostate, toks, jnp.asarray(i))
            first = first if first is not None else float(l)
            last = float(l)
        assert last < first * 0.6, (first, last)


class TestSpeculativeDecode:
    """Greedy speculative decoding must produce EXACTLY the target
    model's greedy output — the draft only changes speed. That equality
    holds for any draft, so it's asserted token-for-token."""

    CFG = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                              mlp_ratio=2, attn_impl="dense")

    def _models(self, seed_t=0, seed_d=9):
        target = T.init_params(jax.random.key(seed_t), self.CFG)
        draft_cfg = T.TransformerConfig(vocab=32, dim=8, n_layers=1,
                                        n_heads=2, mlp_ratio=2,
                                        attn_impl="dense")
        draft = T.init_params(jax.random.key(seed_d), draft_cfg)
        return target, draft, draft_cfg

    @pytest.mark.parametrize("k", [1, 3, 5])
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_matches_greedy_with_unrelated_draft(self, k):
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, 32, (1, 6)), jnp.int32)
        want = np.asarray(T.generate(target, self.CFG, prompt, steps=7))
        got = np.asarray(T.speculative_generate(
            target, self.CFG, draft, draft_cfg, prompt, steps=7,
            draft_k=k))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_matches_greedy_with_perfect_draft(self):
        """draft == target: every window fully accepts, so `steps`
        tokens take exactly ceil(steps/(k+1)) rounds — the observable
        that catches a draft-cache gap silently collapsing acceptance —
        and the output still equals plain greedy."""
        target, _, _ = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, 32, (1, 5)), jnp.int32)
        want = np.asarray(T.generate(target, self.CFG, prompt, steps=10))
        got, rounds = T.speculative_generate(
            target, self.CFG, target, self.CFG, prompt, steps=10,
            draft_k=4, return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), want)
        assert int(rounds[0]) == 2, rounds  # ceil(10/5); rounds is [B]

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_gqa_target(self):
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2,
                                  n_heads=4, n_kv_heads=1, mlp_ratio=2,
                                  attn_impl="dense")
        target = T.init_params(jax.random.key(2), cfg)
        _, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(1, 32, (1, 4)), jnp.int32)
        want = np.asarray(T.generate(target, cfg, prompt, steps=5))
        got = np.asarray(T.speculative_generate(
            target, cfg, draft, draft_cfg, prompt, steps=5, draft_k=3))
        np.testing.assert_array_equal(got, want)

    def test_validates_prompt(self):
        target, draft, draft_cfg = self._models()
        with pytest.raises(ValueError, match="prompt"):
            T.speculative_generate(target, self.CFG, draft, draft_cfg,
                                   jnp.zeros((1, 1), jnp.int32), steps=3)

    @pytest.mark.slow

    def test_int8_target_matches_int8_greedy(self):
        """A quantized TARGET must still decode exactly its own int8
        greedy output (s8 streamed through the round loop via the
        shared _int8_step_params hook); the f32 draft only affects
        speed."""
        from paddle_tpu.serve import quant

        target, draft, draft_cfg = self._models()
        qp = quant.quantize_params(target)
        prompt = jnp.asarray(
            np.random.RandomState(6).randint(1, 32, (2, 6)), jnp.int32)
        want = np.asarray(T.generate(qp, self.CFG, prompt, steps=7))
        got = np.asarray(T.speculative_generate(
            qp, self.CFG, draft, draft_cfg, prompt, steps=7, draft_k=3))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_batched_matches_per_row_greedy(self):
        """Rows accept different prefix lengths (different prompts vs
        the same draft) yet each row's output must equal ITS OWN greedy
        decode — the desync case the r4 batch-1 restriction dodged."""
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(1, 32, (3, 6)), jnp.int32)
        got = np.asarray(T.speculative_generate(
            target, self.CFG, draft, draft_cfg, prompt, steps=9,
            draft_k=3))
        for i in range(3):
            want = np.asarray(T.generate(
                target, self.CFG, prompt[i:i + 1], steps=9))
            np.testing.assert_array_equal(got[i:i + 1], want,
                                          err_msg=f"row {i}")

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_batched_mixed_draft_quality(self):
        """One row decodes with a perfect-draft dynamic (target==draft
        would accept everything) while the other disagrees constantly —
        per-row round counts must differ and outputs still match
        per-row greedy."""
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(4).randint(1, 32, (2, 5)), jnp.int32)
        got, rounds = T.speculative_generate(
            target, self.CFG, draft, draft_cfg, prompt, steps=8,
            draft_k=4, return_stats=True)
        assert rounds.shape == (2,)
        assert int(rounds.max()) <= 8
        for i in range(2):
            want = np.asarray(T.generate(
                target, self.CFG, prompt[i:i + 1], steps=8))
            np.testing.assert_array_equal(np.asarray(got)[i:i + 1], want)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_eos_matches_greedy_fill(self):
        """Early-stop parity: pick the eos id that greedy actually
        emits mid-stream, then the speculative output (tokens AND the
        post-eos fill) must equal generate()'s eos output row-for-row,
        and stopped rows must spend fewer rounds than steps."""
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(1, 32, (2, 5)), jnp.int32)
        steps = 10
        plain = np.asarray(T.generate(target, self.CFG, prompt,
                                      steps=steps))
        # an id each row emits somewhere in its continuation (fall back
        # to row 0's 3rd token; rows without it just run full length)
        eos = int(plain[0, prompt.shape[1] + 2])
        want = np.asarray(T.generate(target, self.CFG, prompt,
                                     steps=steps, eos_id=eos))
        got, rounds = T.speculative_generate(
            target, self.CFG, draft, draft_cfg, prompt, steps=steps,
            draft_k=3, eos_id=eos, return_stats=True)
        np.testing.assert_array_equal(np.asarray(got), want)
        assert int(rounds[0]) < steps  # row 0 stopped early


class TestSpeculativeSampling:
    """Speculative SAMPLING must preserve the target's (filtered)
    sampling distribution exactly — the draft changes speed only."""

    CFG = T.TransformerConfig(vocab=16, dim=16, n_layers=2, n_heads=2,
                              mlp_ratio=2, attn_impl="dense")

    def _models(self):
        target = T.init_params(jax.random.key(0), self.CFG)
        draft_cfg = T.TransformerConfig(vocab=16, dim=8, n_layers=1,
                                        n_heads=2, mlp_ratio=2,
                                        attn_impl="dense")
        draft = T.init_params(jax.random.key(9), draft_cfg)
        return target, draft, draft_cfg

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_first_token_distribution_matches_target(self):
        """2000 identical rows, 1 step: the empirical histogram of the
        first sampled token must match the target's filtered softmax at
        the prompt's last position (TV noise at N=2000 is ~0.01/token;
        tolerance 0.05). This is the property the rejection rule
        exists to guarantee — a naive accept-if-likely rule fails it."""
        target, draft, draft_cfg = self._models()
        row = np.random.RandomState(0).randint(1, 16, (1, 4))
        prompt = jnp.asarray(np.repeat(row, 2000, axis=0), jnp.int32)
        out = np.asarray(T.speculative_sample(
            target, self.CFG, draft, draft_cfg, prompt, steps=1,
            rng=jax.random.key(42), draft_k=2, temperature=0.9))
        toks = out[:, 4]
        freq = np.bincount(toks, minlength=16) / 2000.0
        logits = np.asarray(T.apply(
            target, self.CFG, jnp.asarray(row, jnp.int32)))[0, -1]
        want = np.asarray(jax.nn.softmax(
            jnp.asarray(logits, jnp.float32) / 0.9))
        assert np.abs(freq - want).max() < 0.05, (freq, want)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_top_k1_equals_greedy_exactly(self):
        """top_k=1 collapses both filtered distributions to one-hots:
        the sampler must reproduce the target's greedy decode token for
        token, whatever the draft proposes."""
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, 16, (2, 5)), jnp.int32)
        want = np.asarray(T.generate(target, self.CFG, prompt, steps=8))
        got = np.asarray(T.speculative_sample(
            target, self.CFG, draft, draft_cfg, prompt, steps=8,
            rng=jax.random.key(3), draft_k=3, top_k=1))
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_perfect_draft_accepts_everything(self):
        """draft == target => p == q => acceptance probability 1 per
        token: steps tokens must take exactly ceil(steps/(k+1)) rounds
        per row."""
        target, _, _ = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(1, 16, (2, 4)), jnp.int32)
        _, rounds = T.speculative_sample(
            target, self.CFG, target, self.CFG, prompt, steps=10,
            rng=jax.random.key(7), draft_k=4, temperature=0.8,
            return_stats=True)
        np.testing.assert_array_equal(np.asarray(rounds), [2, 2])

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_eos_stops_and_pads(self):
        target, draft, draft_cfg = self._models()
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(1, 16, (2, 4)), jnp.int32)
        steps = 12
        out, rounds = T.speculative_sample(
            target, self.CFG, draft, draft_cfg, prompt, steps=steps,
            rng=jax.random.key(5), draft_k=3, temperature=1.0,
            eos_id=3, pad_id=0, return_stats=True)
        out = np.asarray(out)
        assert out.shape == (2, 4 + steps)
        for r in range(2):
            gen = out[r, 4:]
            hits = np.flatnonzero(gen == 3)
            if hits.size:  # everything after the first eos is pad
                assert (gen[hits[0] + 1:] == 0).all(), gen

    def test_validates_temperature(self):
        target, draft, draft_cfg = self._models()
        with pytest.raises(ValueError, match="temperature"):
            T.speculative_sample(target, self.CFG, draft, draft_cfg,
                                 jnp.zeros((1, 4), jnp.int32), steps=2,
                                 rng=jax.random.key(0), temperature=0.0)


def assert_decode_matches_teacher_forcing(params, cfg, prompt, steps):
    """Cached token-by-token greedy decode must equal the teacher-forced
    argmax of one full forward — THE decode-correctness invariant, used
    by the GQA tests and the cross-feature matrix."""
    t0 = prompt.shape[1]
    out = np.asarray(T.generate(params, cfg, prompt, steps=steps))
    logits = np.asarray(T.apply(params, cfg, jnp.asarray(out)))
    for s in range(steps):
        col = t0 + s
        np.testing.assert_array_equal(
            out[:, col], logits[:, col - 1].argmax(-1),
            err_msg=f"step {s} of {cfg}")


class TestDecodeFeatureMatrix:
    """Cross-feature decode consistency sweep: every combination of
    GQA x MoE x rope-scaling must keep the cached token-by-token decode
    identical to the teacher-forced argmax of the full forward — the
    invariant that catches interactions between features that each pass
    alone."""

    @pytest.mark.parametrize("kv,moe,scaling", [
        (1, 0, "none"), (2, 4, "none"), (1, 4, "ntk"),
        (2, 0, "linear"), (1, 4, "linear"), (4, 4, "ntk"),
    ])
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_decode_matches_teacher_forcing(self, kv, moe, scaling):
        cfg = T.TransformerConfig(
            vocab=32, dim=16, n_layers=2, n_heads=4, n_kv_heads=kv,
            mlp_ratio=2, attn_impl="dense", moe_experts=moe,
            moe_capacity_factor=8.0,  # no drops: decode == forward
            rope_scaling=scaling, rope_factor=2.0)
        params = T.init_params(jax.random.key(kv + moe), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(moe).randint(1, 32, (2, 5)), jnp.int32)
        assert_decode_matches_teacher_forcing(params, cfg, prompt, 4)

    @pytest.mark.parametrize("kv,moe,window,int8", [
        (2, 0, 3, False), (1, 4, 4, False), (2, 0, None, True),
        (1, 0, 3, True), (4, 4, 4, True),
    ])
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_decode_matrix_window_int8(self, kv, moe, window, int8):
        """GQA x MoE x sliding-window x int8: window < t0+steps forces
        the r5 ROLLING ring cache, and int8 forces the in-loop dequant
        — the teacher-forced reference runs on the SAME dequantized
        values, so exact equality must survive both."""
        cfg = T.TransformerConfig(
            vocab=32, dim=16, n_layers=2, n_heads=4, n_kv_heads=kv,
            mlp_ratio=2, attn_impl="dense", moe_experts=moe,
            moe_capacity_factor=8.0, attn_window=window)
        params = T.init_params(jax.random.key(kv + moe + 17), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(kv + moe).randint(1, 32, (2, 6)),
            jnp.int32)
        if not int8:
            assert_decode_matches_teacher_forcing(params, cfg, prompt, 5)
            return
        from paddle_tpu.serve import quant

        qp = quant.quantize_params(params)
        out = np.asarray(T.generate(qp, cfg, prompt, steps=5))
        logits = np.asarray(T.apply(quant.dequantize_params(qp), cfg,
                                    jnp.asarray(out)))
        t0 = prompt.shape[1]
        for s in range(5):
            col = t0 + s
            np.testing.assert_array_equal(
                out[:, col], logits[:, col - 1].argmax(-1),
                err_msg=f"step {s} (kv={kv} moe={moe} window={window})")


class TestSlidingWindowAttention:
    def _cfg(self, window=None):
        return T.TransformerConfig(vocab=32, dim=16, n_layers=2,
                                   n_heads=2, mlp_ratio=2,
                                   attn_impl="dense",
                                   attn_window=window)

    def test_locality(self):
        """A token farther back than the total receptive field
        (window-1 per layer) must not influence the logits; a token
        inside one window must."""
        cfg = self._cfg(window=3)  # 2 layers -> receptive field 5
        params = T.init_params(jax.random.key(0), cfg)
        r = np.random.RandomState(0)
        a = r.randint(1, 32, (1, 12)).astype(np.int32)
        b = a.copy()
        b[0, 2] = (b[0, 2] + 7) % 32  # >receptive-field from pos 11
        la = np.asarray(T.apply(params, cfg, jnp.asarray(a)))
        lb = np.asarray(T.apply(params, cfg, jnp.asarray(b)))
        np.testing.assert_allclose(la[0, -1], lb[0, -1], rtol=1e-5,
                                   atol=1e-5)
        c = a.copy()
        c[0, 10] = (c[0, 10] + 7) % 32  # inside the last window
        lc = np.asarray(T.apply(params, cfg, jnp.asarray(c)))
        assert np.abs(la[0, -1] - lc[0, -1]).max() > 1e-4

    def test_huge_window_equals_full(self):
        params = T.init_params(jax.random.key(1), self._cfg())
        toks = jnp.asarray(
            np.random.RandomState(1).randint(1, 32, (2, 9)), jnp.int32)
        full = np.asarray(T.apply(params, self._cfg(), toks))
        win = np.asarray(T.apply(params, self._cfg(window=1000), toks))
        np.testing.assert_allclose(win, full, rtol=1e-6)

    @pytest.mark.slow
    def test_decode_matches_teacher_forcing(self):
        cfg = self._cfg(window=4)
        params = T.init_params(jax.random.key(2), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(1, 32, (2, 6)), jnp.int32)
        assert_decode_matches_teacher_forcing(params, cfg, prompt, 5)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_beam_and_spec_respect_window(self):
        cfg = self._cfg(window=4)
        params = T.init_params(jax.random.key(3), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(1, 32, (1, 6)), jnp.int32)
        greedy = np.asarray(T.generate(params, cfg, prompt, steps=5))
        seqs, _ = T.beam_decode(params, cfg, prompt, steps=5,
                                beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]), greedy)
        dcfg = self._cfg(window=4)
        draft = T.init_params(jax.random.key(4), dcfg)
        spec = np.asarray(T.speculative_generate(
            params, cfg, draft, dcfg, prompt, steps=5, draft_k=3))
        np.testing.assert_array_equal(spec, greedy)

    def test_varlen_prompts_rejected(self):
        cfg = self._cfg(window=4)
        params = T.init_params(jax.random.key(5), cfg)
        with pytest.raises(ValueError, match="attn_window"):
            T.generate(params, cfg, jnp.zeros((2, 6), jnp.int32),
                       steps=3, prompt_lens=jnp.asarray([6, 4]))

    def test_context_parallel_rejected(self):
        """CP's ring attention has no band plumbing — silently training
        full attention would diverge from every windowed path."""
        from paddle_tpu.core import mesh as mesh_lib
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=1, seq=4),
            devices=jax.devices()[:8])
        with pytest.raises(ValueError, match="attn_window"):
            T.make_context_parallel_loss(self._cfg(window=4), mesh)


class TestRopeScaling:
    """Context extension without new parameters: linear position
    compression and NTK base rescaling."""

    def _cfg(self, **kw):
        return T.TransformerConfig(vocab=32, dim=16, n_layers=1,
                                   n_heads=2, mlp_ratio=2,
                                   attn_impl="dense", **kw)

    def test_factor_one_is_identity(self):
        params = T.init_params(jax.random.key(0), self._cfg())
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (2, 8)), jnp.int32)
        base = np.asarray(T.apply(params, self._cfg(), toks))
        for mode in ("linear", "ntk"):
            same = np.asarray(T.apply(
                params, self._cfg(rope_scaling=mode, rope_factor=1.0),
                toks))
            np.testing.assert_allclose(same, base, rtol=1e-6)

    def test_linear_scaling_matches_compressed_positions(self):
        """factor-f linear scaling at positions p must equal the
        unscaled model at positions p/f (the definition)."""
        cfg = self._cfg()
        params = T.init_params(jax.random.key(1), cfg)
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 32, (2, 8)), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.float32) * 4.0,
                               (2, 8))
        want = np.asarray(T.apply(params, cfg, toks, positions=pos / 4.0))
        got = np.asarray(T.apply(
            params, self._cfg(rope_scaling="linear", rope_factor=4.0),
            toks, positions=pos))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_ntk_decodes_and_differs(self):
        cfg = self._cfg(rope_scaling="ntk", rope_factor=8.0)
        params = T.init_params(jax.random.key(2), cfg)
        toks = jnp.asarray(
            np.random.RandomState(2).randint(1, 32, (1, 6)), jnp.int32)
        out = T.generate(params, cfg, toks, steps=4)
        assert out.shape == (1, 10)
        plain = np.asarray(T.apply(params, self._cfg(), toks))
        scaled = np.asarray(T.apply(params, cfg, toks))
        assert not np.allclose(plain, scaled)

    def test_bad_mode_raises(self):
        cfg = self._cfg(rope_scaling="bogus", rope_factor=2.0)
        params = T.init_params(jax.random.key(3), cfg)
        with pytest.raises(ValueError, match="rope_scaling"):
            T.apply(params, cfg, jnp.zeros((1, 4), jnp.int32))


class TestScore:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_logprobs_and_masking(self):
        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2, n_heads=2,
                                  mlp_ratio=2, attn_impl="dense")
        params = T.init_params(jax.random.key(0), cfg)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (3, 10)), jnp.int32)
        lens = jnp.asarray([10, 7, 4])
        lp, nll = T.score(params, cfg, toks, lens)
        assert lp.shape == (3, 9) and nll.shape == (3,)
        mask = np.arange(1, 10)[None, :] < np.asarray(lens)[:, None]
        assert (np.asarray(lp)[~mask] == 0).all()
        assert (np.asarray(lp)[mask] < 0).all()
        # an untrained model scores near uniform: NLL ~ log(32)
        assert abs(float(nll[0]) - np.log(32)) < 1.0
        # consistency with loss() (unmasked row)
        full_nll = float(T.loss(params, cfg, toks[:1]))
        np.testing.assert_allclose(float(nll[0]), full_nll, rtol=1e-5)


class TestFusedCE:
    """fused_ce_chunk folds the LM-head matmul into a checkpointed
    chunked scan (ops/losses.chunked_lm_head_nll): loss and grads must
    match the plain materialized-logits path exactly (same matmul, just
    chunked lhs), including ragged lengths, non-divisible chunk sizes,
    and the MoE aux term."""

    def _cfg(self, **kw):
        import dataclasses
        return dataclasses.replace(CFG, **kw)

    @pytest.mark.parametrize("chunk", [4, 7, 64])
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_loss_and_grads_match_plain(self, params, chunk):
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 61, (3, 13)), jnp.int32)
        fcfg = self._cfg(fused_ce_chunk=chunk)
        for lens in (None, jnp.asarray([13, 6, 1])):
            a = T.loss(params, CFG, toks, lens)
            b = T.loss(params, fcfg, toks, lens)
            np.testing.assert_allclose(float(a), float(b), rtol=1e-6)
            ga = jax.grad(lambda p: T.loss(p, CFG, toks, lens))(params)
            gb = jax.grad(lambda p: T.loss(p, fcfg, toks, lens))(params)
            for la, lb in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
                np.testing.assert_allclose(la, lb, atol=5e-7)

    def test_with_moe_aux(self):
        import dataclasses
        cfg = dataclasses.replace(CFG, moe_experts=4, moe_every=2,
                                  n_layers=2)
        fcfg = dataclasses.replace(cfg, fused_ce_chunk=8)
        p = T.init_params(jax.random.key(2), cfg)
        toks = jnp.asarray(
            np.random.RandomState(2).randint(0, 61, (2, 9)), jnp.int32)
        np.testing.assert_allclose(float(T.loss(p, cfg, toks)),
                                   float(T.loss(p, fcfg, toks)),
                                   rtol=1e-6)

    def test_trains(self, params):
        from paddle_tpu import optim
        fcfg = self._cfg(fused_ce_chunk=16)
        opt = optim.adam(1e-2)
        state = opt.init(params)
        toks = jnp.asarray(
            np.random.RandomState(3).randint(0, 61, (4, 12)), jnp.int32)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(
                lambda p: T.loss(p, fcfg, toks))(p)
            p, s = opt.update(g, s, p, jnp.zeros((), jnp.int32))
            return p, s, l

        p = params
        p, state, l0 = step(p, state)
        for _ in range(30):
            p, state, l = step(p, state)
        assert float(l) < float(l0) - 0.5


class TestFusedCEComposition:
    """fused_ce_chunk must compose with the other loss-path features:
    score() (gold logp = -nll) and context parallelism (loss_fn
    delegates to loss(), so the chunked scan runs over the
    sequence-sharded hidden)."""

    @pytest.mark.slow

    def test_score_matches_plain(self, params):
        import dataclasses
        fcfg = dataclasses.replace(CFG, fused_ce_chunk=8)
        toks = jnp.asarray(
            np.random.RandomState(5).randint(0, 61, (3, 14)), jnp.int32)
        lens = jnp.asarray([14, 9, 4])
        ga, na = T.score(params, CFG, toks, lens)
        gb, nb = T.score(params, fcfg, toks, lens)
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   atol=5e-6)
        np.testing.assert_allclose(np.asarray(na), np.asarray(nb),
                                   atol=5e-6)

    @pytest.mark.slow

    def test_cp_fused_matches_dense_plain(self):
        import dataclasses

        from paddle_tpu.core import mesh as mesh_lib

        cfg = T.TransformerConfig(vocab=64, dim=16, n_layers=2,
                                  n_heads=2, mlp_ratio=2,
                                  attn_impl="dense")
        fcfg = dataclasses.replace(cfg, fused_ce_chunk=8)
        params = T.init_params(jax.random.key(0), cfg)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshConfig(data=2, model=1, seq=4),
            devices=jax.devices()[:8])
        toks_h = np.random.RandomState(0).randint(0, 64, (4, 17)) \
            .astype(np.int32)
        toks = jax.device_put(
            toks_h, jax.NamedSharding(mesh, jax.sharding.PartitionSpec(
                mesh_lib.DATA_AXIS, None)))
        cp_loss = T.make_context_parallel_loss(
            fcfg, mesh, batch_axis=mesh_lib.DATA_AXIS)
        dense = float(T.loss(params, cfg, jnp.asarray(toks_h)))
        cp = float(jax.jit(cp_loss)(params, toks))
        assert abs(dense - cp) < 1e-4, (dense, cp)


class TestInt8KVCache:
    """kv_cache_dtype="int8": the decode cache stores s8 + per-(pos,
    kv-head) scales, quantized at write, dequantized inside the
    attention reads. Lossy by design — the tests assert near-exact
    token agreement at small configs plus composition with the other
    decode features; the loop-state evidence lives in
    test_compiled_cost.py::TestInt8KVCacheState."""

    def _gen(self, cfg, params, prompt, steps=16, **kw):
        import dataclasses
        q = dataclasses.replace(cfg, kv_cache_dtype="int8")
        a = T.generate(params, cfg, prompt, steps=steps, **kw)
        b = T.generate(params, q, prompt, steps=steps, **kw)
        return a, b

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_tokens_agree_with_fp_cache(self, params):
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, 61, (3, 9)), jnp.int32)
        a, b = self._gen(CFG, params, prompt)
        assert a.shape == b.shape
        agree = float(jnp.mean((a == b).astype(jnp.float32)))
        assert agree >= 0.95, agree

    @pytest.mark.slow

    def test_composes_with_gqa_and_window(self):
        import dataclasses
        cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2,
                                  n_heads=4, n_kv_heads=2,
                                  attn_window=6, attn_impl="dense")
        p = T.init_params(jax.random.key(3), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, 61, (2, 5)), jnp.int32)
        a, b = self._gen(cfg, p, prompt, steps=12)  # rolling ring cache
        agree = float(jnp.mean((a == b).astype(jnp.float32)))
        assert agree >= 0.9, agree

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_composes_with_varlen_prompts_and_int8_weights(self, params):
        from paddle_tpu.serve import quant
        qp = quant.quantize_params(params)
        prompt = jnp.asarray(
            np.random.RandomState(4).randint(0, 61, (3, 8)), jnp.int32)
        lens = jnp.asarray([8, 5, 2])
        a, b = self._gen(CFG, qp, prompt, steps=10, prompt_lens=lens)
        agree = float(jnp.mean((a == b).astype(jnp.float32)))
        assert agree >= 0.9, agree

    def test_sample_path_runs(self, params):
        import dataclasses
        q = dataclasses.replace(CFG, kv_cache_dtype="int8")
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(0, 61, (2, 6)), jnp.int32)
        out = T.sample(params, q, prompt, steps=8,
                       rng=jax.random.key(1), temperature=0.8)
        assert out.shape == (2, 14)

    def test_beam_and_spec_raise(self, params):
        import dataclasses
        q = dataclasses.replace(CFG, kv_cache_dtype="int8")
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="generate"):
            T.beam_decode(params, q, prompt, steps=2)
        with pytest.raises(ValueError, match="generate"):
            T.speculative_generate(params, q, params, q, prompt, steps=2)
        with pytest.raises(ValueError, match="compute|int8"):
            T.generate(params,
                       dataclasses.replace(CFG, kv_cache_dtype="fp4"),
                       prompt, steps=2)
