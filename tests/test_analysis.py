"""Static-analysis pass (graftlint/locklint) + runtime guard tests.

Three layers, mirroring docs/ANALYSIS.md:

1. Per-rule fixture snippets: every rule has a must-flag case AND a
   near-miss it must NOT flag (the false-positive contract is as much
   of the tool's value as the detection).
2. The repo gate itself: `--check` against the committed baseline
   exits 0 — zero unbaselined findings at HEAD — and the two
   locklint-hardened modules stay clean.
3. RecompileGuard/transfer-guard regression tests: the DecodeEngine
   decode loop and the jitted train step compile EXACTLY ONCE and
   hit zero recompiles / zero implicit transfers over 3+ steady-state
   iterations — the "every hot path stays inside one compiled XLA
   program" contract, enforced at runtime.
"""

import textwrap
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis.graftlint import Finding, lint_source
from paddle_tpu.analysis.guards import (RecompileError, RecompileGuard,
                                        no_implicit_transfers,
                                        steady_state)
from paddle_tpu.analysis.locklint import (lint_lock_graph,
                                          lint_locks_source)
from paddle_tpu.analysis.run import (apply_baseline, collect_findings,
                                     run_cli)

pytestmark = pytest.mark.analysis


def rules_of(src):
    return {f.rule for f in lint_source(textwrap.dedent(src), "t.py")}


# -- rule fixtures: one must-flag + one near-miss per rule ---------------


class TestGL001HostSync:
    def test_item_flagged(self):
        assert "GL001" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                return x * x.item()
        """)

    def test_float_of_traced_flagged(self):
        assert "GL001" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                return float(x)
        """)

    def test_numpy_on_traced_flagged(self):
        assert "GL001" in rules_of("""
            import jax, numpy as np
            @jax.jit
            def f(x):
                return np.asarray(x)
        """)

    def test_print_of_traced_flagged(self):
        assert "GL001" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                print(x)
                return x
        """)

    def test_device_get_flagged(self):
        assert "GL001" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                return jax.device_get(x)
        """)

    def test_near_miss_static_print_and_host_float(self):
        # printing shapes (host metadata) in traced code is fine, and
        # float() in plain host code is fine
        assert not rules_of("""
            import jax
            @jax.jit
            def f(x):
                print("shape:", x.shape)
                return x
            def host(loss):
                return float(loss)
        """)


class TestGL002TracedControlFlow:
    def test_if_on_traced_flagged(self):
        assert "GL002" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)

    def test_while_and_assert_flagged(self):
        src_rules = rules_of("""
            import jax
            @jax.jit
            def f(x):
                assert x > 0
                while x.sum() > 0:
                    x = x - 1
                return x
        """)
        assert "GL002" in src_rules

    def test_scan_body_is_traced(self):
        assert "GL002" in rules_of("""
            from jax import lax
            def outer(xs):
                def body(c, x):
                    if x > 0:
                        c = c + x
                    return c, x
                return lax.scan(body, 0.0, xs)
        """)

    def test_near_miss_shape_branch_and_is_none(self):
        # shape/dtype reads are host metadata; `is None` is
        # host-decidable; host functions branch freely
        assert not rules_of("""
            import jax
            @jax.jit
            def f(x, y=None):
                if x.shape[0] > 4:
                    x = x[:4]
                if y is not None:
                    x = x + y
                return x
        """)

    def test_near_miss_lambda_param_taint_is_scoped(self):
        # a host variable sharing a lambda param's name must not be
        # flagged after the lambda (the param taint dies with it)
        assert not rules_of("""
            import jax
            @jax.jit
            def f(x):
                n = 3
                g = lambda n: n + 1
                if n > 2:
                    return g(x)
                return x
        """)

    def test_jit_site_static_argnames_not_tainted(self):
        # the engine idiom: jax.jit(self._impl, static_argnames=...)
        # makes `flag` a compile-time python value — branching on it
        # is the DESIGN, not a bug
        assert not rules_of("""
            import jax
            class E:
                def __init__(self):
                    self._j = jax.jit(self._impl,
                                      static_argnames=("flag",))
                def _impl(self, x, flag):
                    if flag:
                        return x * 2
                    return x
        """)


class TestGL003WeakDtype:
    def test_bare_literal_ctor_flagged(self):
        assert "GL003" in rules_of("""
            import jax.numpy as jnp
            def f():
                return jnp.array(2.0)
        """)

    def test_full_literal_flagged(self):
        assert "GL003" in rules_of("""
            import jax.numpy as jnp
            def f(s):
                return jnp.full(s, 1e-8)
        """)

    def test_undtyped_arange_flagged(self):
        assert "GL003" in rules_of("""
            import jax.numpy as jnp
            def f(t):
                return jnp.arange(t)
        """)

    def test_near_miss_explicit_dtype(self):
        assert not rules_of("""
            import jax.numpy as jnp
            def f(s, t):
                a = jnp.array(2.0, dtype=jnp.float32)
                b = jnp.full(s, 1e-8, jnp.float32)
                c = jnp.arange(t, dtype=jnp.int32)
                d = jnp.asarray(s)       # non-literal payload
                return a, b, c, d
        """)


class TestGL004RecompileHazards:
    def test_jit_in_loop_flagged(self):
        assert "GL004" in rules_of("""
            import jax
            def f(fs, x):
                outs = []
                for g in fs:
                    outs.append(jax.jit(g)(x))
                return outs
        """)

    def test_list_static_argnums_flagged(self):
        assert "GL004" in rules_of("""
            import jax
            def f(g):
                return jax.jit(g, static_argnums=[0, 1])
        """)

    def test_set_iteration_in_traced_flagged(self):
        assert "GL004" in rules_of("""
            import jax
            @jax.jit
            def f(x):
                t = x
                for k in set((1, 2, 3)):
                    t = t + k
                return t
        """)

    def test_near_miss_hoisted_jit_and_sorted_set(self):
        assert not rules_of("""
            import jax
            def build(g):
                return jax.jit(g, static_argnums=(0, 1))
            @jax.jit
            def f(x):
                t = x
                for k in sorted(set((1, 2, 3))):
                    t = t + k
                return t
        """)


class TestGL005TracerLeak:
    def test_store_on_self_flagged(self):
        assert "GL005" in rules_of("""
            import jax
            class A:
                def run(self, x):
                    return jax.jit(self._step)(x)
                def _step(self, x):
                    self.last = x * 2
                    return x
        """)

    def test_append_to_closure_flagged(self):
        assert "GL005" in rules_of("""
            import jax
            acc = []
            @jax.jit
            def f(x):
                acc.append(x * 2)
                return x
        """)

    def test_near_miss_local_accumulator(self):
        # the engine's own idiom: new_caches is bound INSIDE the
        # traced scope, collecting across a nested closure — legal
        assert not rules_of("""
            import jax
            @jax.jit
            def f(pairs, x):
                new_caches = []
                def attn(k, v):
                    new_caches.append((k, v))
                    return x
                for k, v in pairs:
                    x = attn(k, v)
                return x, tuple(new_caches)
        """)

    def test_near_miss_functional_update_api(self):
        # `.update(...)` whose RESULT is used is an optimizer-style
        # functional API, not a dict mutation
        assert not rules_of("""
            import jax
            def make(optimizer):
                @jax.jit
                def step(state, grads):
                    params, opt = optimizer.update(grads, state)
                    return params, opt
                return step
        """)


class TestGL006ImportTimeCompute:
    def test_module_level_flagged(self):
        assert "GL006" in rules_of("""
            import jax.numpy as jnp
            TABLE = jnp.zeros((10,))
        """)

    def test_default_arg_flagged(self):
        assert "GL006" in rules_of("""
            import jax.numpy as jnp
            def f(x, w=jnp.ones((3,))):
                return x * w
        """)

    def test_near_miss_inside_function_and_main_block(self):
        assert not rules_of("""
            import jax.numpy as jnp
            def f():
                return jnp.zeros((10,))
            if __name__ == "__main__":
                print(jnp.zeros((2,)))
        """)

    def test_near_miss_module_level_lambda_body(self):
        # a lambda BODY doesn't run at import — only its construction
        assert not rules_of("""
            import jax.numpy as jnp
            _pad = lambda x: jnp.maximum(x, 0)
            TABLE = {"relu": lambda x: jnp.maximum(x, 0)}
        """)


class TestGL007ObsDiscipline:
    """GL007 only bites inside serve/ and train/ — the modules under
    the obs instrumentation contract."""

    @staticmethod
    def rules_at(src, path):
        return {f.rule
                for f in lint_source(textwrap.dedent(src), path)}

    def test_time_time_flagged_in_serve(self):
        assert "GL007" in self.rules_at("""
            import time
            def step(self):
                t0 = time.time()
                return t0
        """, "paddle_tpu/serve/x.py")

    def test_bare_print_flagged_in_train(self):
        assert "GL007" in self.rules_at("""
            def report(n):
                print(n)
        """, "paddle_tpu/train/x.py")

    def test_near_miss_monotonic_and_other_module(self):
        # the injectable-clock default is fine, and the same bare
        # print outside the instrumented tree is out of scope
        assert "GL007" not in self.rules_at("""
            import time
            def step(self):
                return time.monotonic()
        """, "paddle_tpu/serve/x.py")
        assert "GL007" not in self.rules_at("""
            def report(n):
                print(n)
        """, "paddle_tpu/native/x.py")

    def test_traced_print_stays_gl001(self):
        # print of a traced value is GL001's finding — GL007 must not
        # double-report it
        rules = self.rules_at("""
            import jax
            @jax.jit
            def f(x):
                print(x)
                return x
        """, "paddle_tpu/serve/x.py")
        assert "GL001" in rules and "GL007" not in rules

    def test_disable_with_reason_suppresses(self):
        assert "GL007" not in self.rules_at("""
            def report(n):
                print(n)  # graftlint: disable=GL007(user-facing dump)
        """, "paddle_tpu/train/x.py")


class TestSuppression:
    SRC = """
        import jax
        @jax.jit
        def f(x):
            y = float(x)  # graftlint: disable=GL001({})
            return y
    """

    def test_disable_with_reason_suppresses(self):
        assert not rules_of(self.SRC.format("test exercises the sync"))

    def test_bare_disable_does_not_count(self):
        # the reason is REQUIRED — a naked disable still reports
        assert "GL001" in rules_of(self.SRC.format(""))

    def test_comment_block_above_statement(self):
        assert not rules_of("""
            import jax
            @jax.jit
            def f(x):
                # graftlint: disable=GL001(reason spans the block
                # above the statement)
                y = float(x)
                return y
        """)


# -- locklint -------------------------------------------------------------


LOCKED_SRC = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self.err = None

    def locked_inc(self):
        with self._lock:
            self.n += 1

    def racy_inc(self):{}
        self.n += 1
"""


class TestLocklint:
    def test_mixed_discipline_flagged(self):
        fs = lint_locks_source(LOCKED_SRC.format(""), "t.py")
        assert [f.rule for f in fs] == ["LK001"]
        assert "self.n" in fs[0].message

    def test_holds_lock_annotation_clears(self):
        src = LOCKED_SRC.format(
            "\n        # locklint: holds-lock(caller locks)")
        assert lint_locks_source(src, "t.py") == []

    def test_near_miss_consistently_unlocked(self):
        # no locked mutation site -> no discipline to enforce
        # (single-threaded classes don't get nagged)
        src = LOCKED_SRC.replace(
            "        with self._lock:\n            self.n += 1",
            "        self.n += 1")
        assert lint_locks_source(src, "t.py") == []

    def test_init_is_exempt(self):
        src = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def inc(self):
        with self._lock:
            self.n += 1
"""
        assert lint_locks_source(src, "t.py") == []

    def test_hardened_modules_stay_clean(self):
        # the PR's lock-discipline sweep: the native runtimes and the
        # pserver client must have zero unannotated findings
        fs = collect_findings([
            "paddle_tpu/native/taskqueue.py",
            "paddle_tpu/native/pserver.py",
            "paddle_tpu/serve/server.py",
            "paddle_tpu/parallel/pserver_client.py",
        ], rules=["LK001"])
        assert fs == [], [str(f) for f in fs]


class TestHAMasterSnapshotErrorRegression:
    """The genuine race locklint surfaced: HAMaster._loop wrote
    last_snapshot_error OUTSIDE _snap_lock (a stale failure could
    overwrite a newer success), and a failed MANUAL checkpoint()
    recorded nothing. Now checkpoint() itself records under the
    lock."""

    def test_manual_checkpoint_failure_records_error(self, tmp_path):
        from paddle_tpu.native.taskqueue import HAMaster

        ha = HAMaster(str(tmp_path), interval_s=0)  # no cadence thread
        try:
            orig = ha.queue.snapshot
            ha.queue.snapshot = lambda path: (_ for _ in ()).throw(
                OSError("disk full"))
            with pytest.raises(OSError):
                ha.checkpoint()
            assert "disk full" in ha.last_snapshot_error
            ha.queue.snapshot = orig
            ha.checkpoint()
            assert ha.last_snapshot_error is None
            assert ha.last_snapshot_time is not None
        finally:
            ha.stop(final_snapshot=False)


# -- graftlock: the LK002-LK005 concurrency rules -------------------------


def lk(src, rules, path="t.py"):
    return [f.rule for f in lint_locks_source(
        textwrap.dedent(src), path, rules=rules)]


class TestLK002LockOrderCycles:
    CYCLE = """
        import threading
        class A:
            def __init__(self):
                self._router = threading.Lock()
                self._pool = threading.Lock()
            def fwd(self):
                with self._router:
                    with self._pool:
                        pass
            def rev(self):
                with self._pool:
                    with self._router:
                        pass
    """

    def test_must_flag_inverted_order(self):
        fs = lint_lock_graph(
            {"a.py": textwrap.dedent(self.CYCLE)})
        assert [f.rule for f in fs] == ["LK002"]
        # the message names the full cycle and both sites
        assert "A._router" in fs[0].message
        assert "A._pool" in fs[0].message
        assert "opposite order" in fs[0].message

    def test_near_miss_same_order_twice(self):
        src = self.CYCLE.replace(
            """            def rev(self):
                with self._pool:
                    with self._router:""",
            """            def rev(self):
                with self._router:
                    with self._pool:""")
        assert src != self.CYCLE     # the replace must have landed
        assert lint_lock_graph({"a.py": textwrap.dedent(src)}) == []

    def test_cycle_via_method_call_chain(self):
        # fwd holds router and CALLS a helper that takes pool; rev
        # inverts — the edge comes from the call chain, not a
        # lexical nested with
        src = """
            import threading
            class A:
                def __init__(self):
                    self._router = threading.Lock()
                    self._pool = threading.Lock()
                def fwd(self):
                    with self._router:
                        self._grab()
                def _grab(self):
                    with self._pool:
                        pass
                def rev(self):
                    with self._pool:
                        with self._router:
                            pass
        """
        fs = lint_lock_graph({"a.py": textwrap.dedent(src)})
        assert [f.rule for f in fs] == ["LK002"]

    def test_cross_module_cycle_via_typed_attr(self):
        # serve-side class holds its lock and calls into a cluster-
        # side class that locks; a back-path inverts — only the
        # MERGED graph sees it
        m1 = """
            import threading
            from m2 import Lease
            class Member:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._lease = Lease()
                def tick(self):
                    with self._lock:
                        self._lease.renew()
                def poke(self):
                    with self._lock:
                        pass
        """
        m2 = """
            import threading
            from m1 import Member
            class Lease:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._member = Member()
                def renew(self):
                    with self._mu:
                        pass
                def back(self):
                    with self._mu:
                        self._member.poke()
        """
        fs = lint_lock_graph({"m1.py": textwrap.dedent(m1),
                              "m2.py": textwrap.dedent(m2)})
        assert [f.rule for f in fs] == ["LK002"]
        msg = fs[0].message
        assert "Member._lock" in msg and "Lease._mu" in msg
        # each module alone has no cycle
        assert lint_lock_graph({"m1.py": textwrap.dedent(m1)}) == []
        assert lint_lock_graph({"m2.py": textwrap.dedent(m2)}) == []

    RE_SRC = """
        import threading
        class R:
            def __init__(self):
                self._mu = threading.{}()
            def outer(self):
                with self._mu:
                    self.inner()
            def inner(self):
                with self._mu:
                    pass
    """

    def test_plain_lock_self_cycle_is_deadlock(self):
        fs = lint_lock_graph(
            {"r.py": textwrap.dedent(self.RE_SRC.format("Lock"))})
        assert [f.rule for f in fs] == ["LK002"]
        assert "self-deadlock" in fs[0].message

    def test_rlock_self_cycle_is_reentrancy_not_flagged(self):
        assert lint_lock_graph(
            {"r.py": textwrap.dedent(self.RE_SRC.format("RLock"))}
        ) == []

    def test_suppression_applies(self):
        src = textwrap.dedent(self.CYCLE).replace(
            "with self._pool:\n            with self._router:",
            "with self._pool:\n            # locklint: disable="
            "LK002(order probe fixture)\n            "
            "with self._router:")
        assert src != textwrap.dedent(self.CYCLE)
        assert lint_lock_graph({"a.py": src}) == []

    def test_repo_graph_has_no_cycles(self):
        # the tentpole's standing guarantee: the sanctioned orders in
        # docs/RELIABILITY.md are acyclic at HEAD
        fs = [f for f in collect_findings(["paddle_tpu"],
                                          rules=["LK002"])]
        assert fs == [], [str(f) for f in fs]


class TestLK003BlockingUnderLock:
    def test_must_flag_socket_write_under_lock(self):
        assert lk("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = None
                def bad(self):
                    with self._lock:
                        self._sock.sendall(b"x")
        """, ["LK003"]) == ["LK003"]

    def test_near_miss_snapshot_then_write_outside(self):
        assert lk("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = None
                def good(self):
                    with self._lock:
                        data = b"x"
                    self._sock.sendall(data)
        """, ["LK003"]) == []

    def test_wait_without_timeout_flagged_with_timeout_clean(self):
        src = """
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._ev = threading.Event()
                def f(self):
                    with self._lock:
                        self._ev.wait({})
        """
        assert lk(src.format(""), ["LK003"]) == ["LK003"]
        assert lk(src.format("timeout=1.0"), ["LK003"]) == []

    def test_condition_wait_on_own_lock_is_the_cv_idiom(self):
        # Condition.wait RELEASES the lock — the one .wait() that is
        # sanctioned under it
        assert lk("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Condition()
                def f(self):
                    with self._lock:
                        self._lock.wait()
        """, ["LK003"]) == []

    def test_jit_callable_under_lock(self):
        src = """
            import threading, jax
            class J:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._step = jax.jit(lambda x: x)
                def bad(self, x):
                    with self._lock:
                        return self._step(x)
        """
        assert lk(src, ["LK003"]) == ["LK003"]

    def test_transitive_through_same_class_call(self):
        fs = lint_locks_source(textwrap.dedent("""
            import threading, time
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                def outer(self):
                    with self._lock:
                        self._helper()
                def _helper(self):
                    time.sleep(0.1)
        """), "t.py", rules=["LK003"])
        assert [f.rule for f in fs] == ["LK003"]
        assert "_helper" in fs[0].message

    def test_suppression_applies(self):
        assert lk("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = None
                def f(self):
                    with self._lock:
                        # locklint: disable=LK003(ACK-after-tail
                        # ordering requires the send under the lock)
                        self._sock.sendall(b"x")
        """, ["LK003"]) == []


class TestLK004ThreadLifecycle:
    def test_must_flag_fire_and_forget(self):
        assert lk("""
            import threading
            def spawn():
                threading.Thread(target=print).start()
        """, ["LK004"]) == ["LK004"]

    def test_near_miss_daemon(self):
        assert lk("""
            import threading
            def spawn():
                threading.Thread(target=print, daemon=True).start()
        """, ["LK004"]) == []

    def test_near_miss_bound_and_joined(self):
        assert lk("""
            import threading
            class W:
                def start(self):
                    self._t = threading.Thread(target=print)
                    self._t.start()
                def stop(self):
                    self._t.join(timeout=1.0)
        """, ["LK004"]) == []

    def test_listcomp_fanout_join_loop_is_clean(self):
        # the idiomatic shape test_native_runtime uses
        assert lk("""
            import threading
            def fan():
                ts = [threading.Thread(target=print)
                      for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
        """, ["LK004"]) == []

    def test_holds_lock_target_flagged(self):
        # a FRESH thread holds nothing: a holds-lock annotated
        # target run as a thread body is a contradiction
        fs = lint_locks_source(textwrap.dedent("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                def spawn(self):
                    self._t = threading.Thread(target=self._body,
                                               daemon=True)
                # locklint: holds-lock(callers lock first)
                def _body(self):
                    pass
        """), "t.py", rules=["LK004"])
        assert [f.rule for f in fs] == ["LK004"]
        assert "holds-lock" in fs[0].message


class TestLK005SignalSafety:
    def test_must_flag_handler_taking_lock(self):
        fs = lint_locks_source(textwrap.dedent("""
            import signal, threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                def install(self):
                    def handler(signum, frame):
                        self.drain()
                    signal.signal(signal.SIGTERM, handler)
                def drain(self):
                    with self._lock:
                        pass
        """), "t.py", rules=["LK005"])
        assert [f.rule for f in fs] == ["LK005"]
        assert "self._lock" in fs[0].message

    def test_must_flag_handler_logging(self):
        assert lk("""
            import logging, signal
            log = logging.getLogger(__name__)
            def handler(signum, frame):
                log.warning("got %d", signum)
            def install():
                signal.signal(signal.SIGTERM, handler)
        """, ["LK005"]) == ["LK005"]

    def test_near_miss_flag_only_handler(self):
        assert lk("""
            import signal
            class S:
                def install(self):
                    def handler(signum, frame):
                        self._pending = signum
                    signal.signal(signal.SIGTERM, handler)
        """, ["LK005"]) == []

    def test_hardened_signal_surfaces_stay_clean(self):
        # the PR's fix sweep: every signal handler in the package
        # defers to a flag (http_edge, server, resilience)
        fs = collect_findings(["paddle_tpu"], rules=["LK005"])
        assert fs == [], [str(f) for f in fs]


class TestLockSweptModulesStayClean:
    def test_fix_sweep_holds(self):
        # the ISSUE's fix-sweep targets, under every LK rule the
        # per-file pass runs — anything new here must be fixed or
        # land in the baseline with a written reason
        fs = collect_findings([
            "paddle_tpu/serve/http_edge.py",
            "paddle_tpu/serve/transport.py",
            "paddle_tpu/serve/router.py",
            "paddle_tpu/cluster/membership.py",
            "paddle_tpu/serve/shm_arena.py",
        ], rules=["LK001", "LK003", "LK004", "LK005"])
        assert fs == [], [str(f) for f in fs]


# -- LockOrderGuard: the runtime half of graftlock ------------------------


@pytest.mark.locks
class TestLockOrderGuard:
    def test_inversion_raises_naming_both_sites(self):
        from paddle_tpu.analysis.guards import (LockOrderError,
                                                LockOrderGuard)

        with LockOrderGuard(raise_on_violation=False) as g:
            a, b = threading.Lock(), threading.Lock()

            def fwd():
                with a:
                    with b:
                        pass

            def rev():
                with b:
                    with a:
                        pass

            for fn in (fwd, rev):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
        assert len(g.violations) == 1
        msg = g.violations[0]
        assert "lock order inverted" in msg
        assert "test_analysis.py" in msg     # both sites named
        # raise_on_violation=True surfaces it as LockOrderError from
        # __exit__ even when a worker thread swallowed it
        with pytest.raises(LockOrderError, match="inverted"):
            with LockOrderGuard() as g2:
                a, b = threading.Lock(), threading.Lock()
                for first, second in ((a, b), (b, a)):
                    def run(x=first, y=second):
                        try:
                            with x:
                                with y:
                                    pass
                        except LockOrderError:
                            pass        # swallowed in the worker
                    t = threading.Thread(target=run)
                    t.start()
                    t.join()

    def test_cycle_across_three_threads(self):
        # no PAIR is ever inverted — only the 3-cycle A->B->C->A is
        # wrong; DFS reachability must catch it
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard(raise_on_violation=False) as g:
            a, b, c = (threading.Lock(), threading.Lock(),
                       threading.Lock())

            def run(x, y):
                with x:
                    with y:
                        pass

            for x, y in ((a, b), (b, c), (c, a)):
                t = threading.Thread(target=run, args=(x, y))
                t.start()
                t.join()
        assert len(g.violations) == 1
        assert "established" in g.violations[0]

    def test_rlock_reentrancy_not_flagged(self):
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard() as g:
            r = threading.RLock()
            with r:
                with r:
                    with r:
                        pass
        assert g.violations == []

    def test_plain_lock_self_deadlock_raises_instead_of_hanging(self):
        from paddle_tpu.analysis.guards import (LockOrderError,
                                                LockOrderGuard)

        try:
            with LockOrderGuard() as g:
                l = threading.Lock()
                l.acquire()
                try:
                    with pytest.raises(LockOrderError,
                                       match="self-deadlock"):
                        l.acquire()
                finally:
                    l.release()
        except LockOrderError:
            pass                     # __exit__ re-raise, expected
        assert len(g.violations) == 1

    def test_held_while_blocking_report(self):
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard(max_held_s=0.05) as g:
            l = threading.Lock()
            with l:
                time.sleep(0.12)
        assert len(g.held_reports) == 1
        rep = g.held_reports[0]
        assert rep["held_s"] > 0.05 and rep["bound_s"] == 0.05
        assert "test_analysis.py" in rep["acquired_at"]

    def test_trylock_records_no_edge(self):
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard() as g:
            a, b = threading.Lock(), threading.Lock()

            def try_side():
                with a:
                    if b.acquire(blocking=False):
                        b.release()

            def rev():
                with b:
                    with a:
                        pass

            for fn in (try_side, rev):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
        assert g.violations == []

    def test_condition_event_queue_built_under_guard_work(self):
        import queue

        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard() as g:
            cv = threading.Condition()
            done = []

            def waiter():
                with cv:
                    cv.wait(timeout=2.0)
                    done.append(1)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join()
            ev = threading.Event()
            ev.set()
            assert ev.wait(0.1)
            q = queue.Queue()
            q.put(1)
            assert q.get() == 1
        assert done == [1] and g.violations == []

    def test_locks_survive_guard_exit(self):
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard():
            l = threading.Lock()
        with l:                      # tracking off, lock still works
            pass
        assert threading.Lock is not type(l)  # patch restored

    def test_single_active_guard(self):
        from paddle_tpu.analysis.guards import LockOrderGuard

        with LockOrderGuard():
            with pytest.raises(RuntimeError, match="already active"):
                with LockOrderGuard():
                    pass


# -- baseline mechanics ---------------------------------------------------


class TestBaseline:
    def F(self, rule="GL001", path="a.py", func="f", line=1):
        return Finding(rule, path, line, 0, func, "m")

    def test_counts_cover_and_excess_reports(self):
        base = {("GL001", "a.py", "f"):
                {"rule": "GL001", "path": "a.py", "func": "f",
                 "count": 1, "reason": "r"}}
        un, stale = apply_baseline([self.F(line=1)], base)
        assert un == [] and stale == []
        un, _ = apply_baseline([self.F(line=1), self.F(line=9)], base)
        assert len(un) == 1 and un[0].line == 9

    def test_stale_entries_surface(self):
        base = {("GL001", "gone.py", "f"):
                {"rule": "GL001", "path": "gone.py", "func": "f",
                 "count": 1, "reason": "r"}}
        un, stale = apply_baseline([], base)
        assert un == [] and stale == [("GL001", "gone.py", "f")]

    def test_repo_gate_is_green(self, capsys):
        # THE acceptance criterion: zero unbaselined findings at HEAD
        rc = run_cli(["--check"])
        out = capsys.readouterr().out
        assert rc == 0, out

    def test_explain_prints_catalog_entry(self, capsys):
        for rid in ("GL001", "LK002", "lk003"):  # case-insensitive
            assert run_cli(["--explain", rid]) == 0
            out = capsys.readouterr().out
            assert rid.upper() in out
            assert "bad:" in out and "good:" in out

    def test_explain_unknown_rule_errors(self, capsys):
        with pytest.raises(SystemExit):
            run_cli(["--explain", "LK999"])

    def test_stale_prune_report_grouped_per_rule(self, tmp_path,
                                                 capsys):
        import json as _json

        # a file with one real LK003 finding, and a baseline holding
        # that entry plus two stale ones under different rules
        src = tmp_path / "mod.py"
        src.write_text(textwrap.dedent("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = None
                def f(self):
                    with self._lock:
                        self._sock.sendall(b"x")
        """))
        rel = str(src)
        from paddle_tpu.analysis.run import _rel
        rel = _rel(str(src))
        base = tmp_path / "base.json"
        base.write_text(_json.dumps({"version": 1, "entries": [
            {"rule": "LK003", "path": rel, "func": "S.f",
             "count": 1, "reason": "r", "message": "m"},
            {"rule": "LK003", "path": rel, "func": "S.gone",
             "count": 1, "reason": "r", "message": "m"},
            {"rule": "LK001", "path": rel, "func": "S.old",
             "count": 1, "reason": "r", "message": "m"},
        ]}))
        rc = run_cli(["--check", "--baseline", str(base), str(src)])
        out = capsys.readouterr().out
        assert rc == 0, out          # the live finding is covered
        assert "stale baseline entries to prune (2" in out
        # grouped per rule, each naming its keys
        assert "LK001" in out and "S.old" in out
        assert "S.gone" in out


# -- runtime guards: the two hottest loops --------------------------------


def _small_cfg():
    from paddle_tpu.models import transformer as T

    return T.TransformerConfig(vocab=31, dim=16, n_layers=1,
                               n_heads=2, attn_impl="dense")


class TestRecompileGuardUnit:
    def test_catches_recompile_and_names_it(self):
        f = jax.jit(lambda x: x * 2)
        f(jnp.ones((3,), jnp.float32))
        with pytest.raises(RecompileError):
            with RecompileGuard(name="unit"):
                f(jnp.ones((5,), jnp.float32))   # new shape: compile

    def test_steady_state_passes(self):
        f = jax.jit(lambda x: x * 2)
        x = jnp.ones((4,), jnp.float32)
        f(x)
        with RecompileGuard(name="unit") as g:
            for _ in range(3):
                f(x)
        assert g.compiles == 0

    def test_transfer_guard_bites_on_implicit_h2d(self):
        f = jax.jit(lambda x: x + 1)
        f(jnp.ones((4,), jnp.float32))
        with pytest.raises(Exception):
            with no_implicit_transfers():
                f(np.ones((4,), np.float32))     # implicit transfer
        # explicit staging passes
        with no_implicit_transfers():
            f(jax.device_put(np.ones((4,), np.float32)))


class TestDecodeLoopSteadyState:
    """ISSUE acceptance: the decode loop compiles exactly once, then
    zero recompiles and zero implicit transfers over 3+ steady
    iterations — including a page-boundary crossing (the host-side
    page map update must not re-stage anything)."""

    def test_decode_step_compiles_once_then_never(self):
        from paddle_tpu.serve.engine import DecodeEngine

        from paddle_tpu.models import transformer as T

        cfg = _small_cfg()
        params = T.init_params(jax.random.key(0), cfg)
        # page_size 4 + a 3-token prompt => the guarded steady window
        # below crosses a page boundary
        eng = DecodeEngine(params, cfg, slots=2, max_len=16,
                           page_size=4)
        state = eng.init_state()
        r = np.random.RandomState(0)
        state = eng.prefill(
            state, 0, r.randint(0, 31, (3,)).astype(np.int32))
        with RecompileGuard(max_compiles=64, name="warmup") as warm:
            state, *_ = eng.decode_step(state)
            state = eng.ensure_decode_page(state, 0)
        assert warm.compiles >= 1        # the ONE compile happened...
        with steady_state("decode loop", transfers="disallow") as g:
            for _ in range(4):           # ...and never again
                state, toks, lps, was, fin = eng.decode_step(state)
                state = eng.ensure_decode_page(state, 0)
                jax.device_get((toks, lps, was, fin))  # explicit: ok
        assert g.compiles == 0

    def test_int8_kernel_dispatch_adds_zero_compiles(self):
        """ISSUE 12 acceptance: routing an int8 pool through the
        ragged dispatcher (`ragged_impl` pinned to the kernel) must
        add ZERO steady-state compiles — the dequant-fused walk is
        baked into the one decode program at warmup, same as the jnp
        gather it replaced, and page-boundary churn must not re-trace
        the tuple-arena plumbing."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve.engine import DecodeEngine

        cfg = T.TransformerConfig(vocab=31, dim=16, n_layers=1,
                                  n_heads=2, attn_impl="dense",
                                  kv_cache_dtype="int8")
        params = T.init_params(jax.random.key(0), cfg)
        eng = DecodeEngine(params, cfg, slots=2, max_len=16,
                           page_size=4, ragged_impl="pallas")
        state = eng.init_state()
        r = np.random.RandomState(0)
        state = eng.prefill(
            state, 0, r.randint(0, 31, (3,)).astype(np.int32))
        with RecompileGuard(max_compiles=64, name="int8 warmup") as warm:
            state, *_ = eng.decode_step(state)
            state = eng.ensure_decode_page(state, 0)
        assert warm.compiles >= 1
        with steady_state("int8 kernel decode loop",
                          transfers="disallow") as g:
            for _ in range(4):
                state, toks, lps, was, fin = eng.decode_step(state)
                state = eng.ensure_decode_page(state, 0)
                jax.device_get((toks, lps, was, fin))
        assert g.compiles == 0

    def test_full_serve_is_transfer_clean(self):
        """`serve --transfer-guard`'s contract: the WHOLE serve path —
        pool init (explicit device_put staging), admission, decode,
        retire — runs under disallow with greedy parity intact."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve.engine import DecodeEngine

        cfg = _small_cfg()
        params = T.init_params(jax.random.key(0), cfg)
        eng = DecodeEngine(params, cfg, slots=2, max_len=16)
        r = np.random.RandomState(0)
        p = r.randint(0, 31, (5,)).astype(np.int32)
        with no_implicit_transfers():
            got = eng.serve([p], max_new=4, buckets=(8,))
        ref = T.generate(params, cfg, jnp.asarray(p)[None, :],
                         steps=4)
        assert got[0] == [int(t)
                          for t in np.asarray(ref[0, len(p):])]

    def test_served_second_wave_is_compile_free(self):
        """After one serve() wave warmed every body (prefill bucket,
        step, retire), a second wave over the same bucket must not
        compile anything — the continuous-batching promise."""
        from paddle_tpu.serve.engine import DecodeEngine

        from paddle_tpu.models import transformer as T

        cfg = _small_cfg()
        params = T.init_params(jax.random.key(0), cfg)
        eng = DecodeEngine(params, cfg, slots=2, max_len=16)
        r = np.random.RandomState(1)
        mk = lambda n: [r.randint(0, 31, (5,)).astype(np.int32)
                        for _ in range(n)]
        eng.serve(mk(2), max_new=4, buckets=(8,))         # warm wave
        with RecompileGuard(name="second serve wave") as g:
            got = eng.serve(mk(3), max_new=4, buckets=(8,))
        assert g.compiles == 0
        assert len(got) == 3 and all(len(t) for t in got)


class TestTrainStepSteadyState:
    def test_train_step_compiles_once_then_never(self):
        from paddle_tpu import models, optim
        from paddle_tpu.nn.module import ShapeSpec
        from paddle_tpu.ops import losses
        from paddle_tpu.train import Trainer

        trainer = Trainer(
            models.lenet.mlp(10, hidden=(16,)),
            loss_fn=lambda lo, la: jnp.mean(
                losses.softmax_cross_entropy(lo, la)),
            optimizer=optim.sgd(0.1), seed=0)
        state = trainer.init_state(ShapeSpec((8, 28, 28, 1)))
        r = np.random.RandomState(0)
        # the ONE sanctioned per-step transfer is the input batch —
        # staged EXPLICITLY, which is what lets transfers="disallow"
        # hold for everything else
        batch = jax.device_put((
            r.randn(8, 28, 28, 1).astype(np.float32),
            r.randint(0, 10, (8,)).astype(np.int32)))
        rng = jax.random.key(0)
        with RecompileGuard(max_compiles=64, name="warmup") as warm:
            rng, step_rng = jax.random.split(rng)
            state, loss, _ = trainer._train_step(
                state, step_rng, (batch[0],), (batch[1],))
        assert warm.compiles >= 1
        with steady_state("train step", transfers="disallow") as g:
            for _ in range(3):
                # Trainer.train's own per-step idiom: split stays on
                # device, so the ONLY transfer is the explicit batch
                rng, step_rng = jax.random.split(rng)
                state, loss, _ = trainer._train_step(
                    state, step_rng, (batch[0],), (batch[1],))
        assert g.compiles == 0
        assert np.isfinite(float(loss))
