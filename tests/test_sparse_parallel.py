"""Sharded sparse-embedding + collectives tests on the 8-device CPU mesh
(reference test model: gserver/tests/test_CompareSparse.cpp compares
sparse-remote vs dense training in-process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.parallel import (
    ShardedEmbedding,
    collectives,
    compat,
    rowwise_sgd_update,
    shard_rows,
    sharded_embedding_bag,
    sharded_lookup,
    unique_rows_grad,
)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 virtual devices")

# host/device memory spaces differ per backend: TPU has pinned_host +
# device; XLA:CPU exposes only unpinned_host (compat.memory_kind
# degrades the offload shardings there, so the kinds below are what
# "host table" / "device rows" can legitimately look like)
HOST_KINDS = ("pinned_host", "unpinned_host")
DEV_KINDS = ("device", "unpinned_host", None)


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2, model=4))


def _table(vocab=32, dim=6, seed=0):
    return jax.random.normal(jax.random.key(seed), (vocab, dim), jnp.float32)


def test_sharded_lookup_matches_dense(mesh):
    table = _table()
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 32, (5, 7)))
    got = sharded_lookup(sharded, ids, mesh)
    want = jnp.take(table, ids, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_sharded_lookup_under_jit(mesh):
    table = shard_rows(_table(), mesh)
    ids = jnp.asarray([0, 31, 7, 16])
    fn = jax.jit(lambda t, i: sharded_lookup(t, i, mesh))
    np.testing.assert_allclose(
        np.asarray(fn(table, ids)),
        np.asarray(jnp.take(_table(), jnp.asarray([0, 31, 7, 16]), axis=0)),
        rtol=1e-6)


def test_sharded_lookup_gradient_matches_dense(mesh):
    """Backward through the sharded lookup == dense scatter-add grads
    (the SelectedRows semantics check)."""
    table = _table()
    ids = jnp.asarray([1, 1, 5, 31])
    cot = jax.random.normal(jax.random.key(1), (4, 6), jnp.float32)

    def dense_loss(t):
        return jnp.vdot(jnp.take(t, ids, axis=0), cot)

    def sharded_loss(t):
        return jnp.vdot(sharded_lookup(t, ids, mesh), cot)

    g_dense = jax.grad(dense_loss)(table)
    g_sharded = jax.grad(sharded_loss)(shard_rows(table, mesh))
    np.testing.assert_allclose(
        np.asarray(g_sharded), np.asarray(g_dense), rtol=1e-6)


@pytest.mark.slow


def test_sharded_bag_combiners(mesh):
    table = _table()
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray([0, 3, 3, 9, 20])
    seg = jnp.asarray([0, 0, 1, 1, 1])
    for combiner in ("sum", "mean", "sqrtn"):
        got = sharded_embedding_bag(sharded, ids, seg, 2, mesh,
                                    combiner=combiner)
        vecs = jnp.take(table, ids, axis=0)
        sums = jax.ops.segment_sum(vecs, seg, num_segments=2)
        counts = jnp.asarray([2.0, 3.0])[:, None]
        want = {"sum": sums, "mean": sums / counts,
                "sqrtn": sums / jnp.sqrt(counts)}[combiner]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)
    with pytest.raises(ValueError, match="combiner"):
        sharded_embedding_bag(sharded, ids, seg, 2, mesh, combiner="bogus")


def test_rowwise_sgd_update_sharded_matches_dense(mesh):
    table = _table()
    ids = jnp.asarray([2, 2, 17, 30])  # duplicate rows must both apply
    grads = jax.random.normal(jax.random.key(2), (4, 6), jnp.float32)
    want = rowwise_sgd_update(table, ids, grads, 0.1)  # dense path
    got = rowwise_sgd_update(shard_rows(table, mesh), ids, grads, 0.1, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    # untouched rows unchanged
    np.testing.assert_allclose(np.asarray(got)[0], np.asarray(table)[0])


def test_unique_rows_grad():
    ids = jnp.asarray([4, 4, 9, 4])
    grads = jnp.ones((4, 3), jnp.float32)
    uids, summed = unique_rows_grad(ids, grads, max_unique=4)
    got = {int(i): np.asarray(summed)[k] for k, i in enumerate(np.asarray(uids))}
    np.testing.assert_allclose(got[4], [3, 3, 3])
    np.testing.assert_allclose(got[9], [1, 1, 1])


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_sharded_embedding_module_end_to_end(mesh):
    """Tiny sparse-embedding training loop: loss decreases and only
    touched rows move (the test_CompareSparse equivalence idea)."""
    emb = ShardedEmbedding(vocab=30, dim=4, mesh=mesh, init_scale=0.1)
    table = emb.init(jax.random.key(0))
    assert table.shape[0] % 4 == 0  # padded to the axis
    target = jax.random.normal(jax.random.key(3), (4,), jnp.float32)
    ids = jnp.asarray([1, 7, 19])

    def loss_fn(t):
        vecs = emb.lookup(t, ids)
        return jnp.mean((vecs - target) ** 2)

    before = float(loss_fn(table))
    t0 = np.asarray(table).copy()
    for _ in range(20):
        row_grads = jax.grad(
            lambda t: loss_fn(t))(table)  # dense grad for the check below
        touched = jnp.take(row_grads, ids, axis=0)
        table = emb.apply_row_grads(table, ids, touched, lr=0.5)
    after = float(loss_fn(table))
    assert after < before * 0.5, (before, after)
    # untouched rows identical
    t1 = np.asarray(table)
    untouched = [i for i in range(30) if i not in (1, 7, 19)]
    np.testing.assert_allclose(t1[untouched], t0[untouched])


def test_shard_rows_requires_divisible(mesh):
    with pytest.raises(ValueError, match="divisible"):
        shard_rows(_table(vocab=30), mesh)


# ---- collectives ----

def test_device_all_reduce_mean(mesh):
    x = jnp.arange(16, dtype=jnp.float32).reshape(2, 8)
    x_sharded = jax.device_put(
        x, jax.NamedSharding(mesh, P("data")))
    got = collectives.device_all_reduce_mean(x_sharded, mesh)
    want = np.broadcast_to(np.asarray(x).mean(0, keepdims=True), (2, 8))
    np.testing.assert_allclose(np.asarray(got), want)


def test_collectives_in_shard_map(mesh):
    """reduce_scatter then all_gather round-trips to all_reduce."""

    def body(x):
        rs = collectives.reduce_scatter(x, "data")
        return collectives.all_gather(rs, "data")

    fn = compat.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                          out_specs=P("data"))
    x = jnp.arange(32, dtype=jnp.float32).reshape(4, 8)
    got = fn(x)
    # per data-shard: full sum broadcast
    want = np.asarray(x).reshape(2, 2, 8).sum(0, keepdims=True)
    want = np.broadcast_to(want, (2, 2, 8)).reshape(4, 8)
    np.testing.assert_allclose(np.asarray(got), want)


def test_ppermute_ring(mesh):
    def body(x):
        return collectives.ppermute_ring(x, "data", shift=1)

    fn = compat.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                          out_specs=P("data"))
    x = jnp.asarray([[1.0], [2.0]])
    got = np.asarray(fn(x)).reshape(-1)
    np.testing.assert_allclose(got, [2.0, 1.0])


def test_broadcast_from(mesh):
    x = jnp.asarray([[10.0], [20.0]])  # shard0=10, shard1=20 on data axis
    x = jax.device_put(x, jax.NamedSharding(mesh, P("data")))
    got = collectives.device_broadcast_from(x, mesh, source=1)
    np.testing.assert_allclose(np.asarray(got).reshape(-1), [20.0])


# ---- all-to-all exchange path (round-2: VERDICT item 4) ----------------

@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_lookup_matches_dense(mesh):
    from paddle_tpu.parallel import alltoall_lookup

    table = _table()
    sharded = shard_rows(table, mesh)
    # ids sharded over the model axis: size divisible by 4
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 32, 24))
    got = alltoall_lookup(sharded, ids, mesh)
    want = jnp.take(table, ids, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_lookup_out_of_range_zero(mesh):
    from paddle_tpu.parallel import alltoall_lookup

    table = _table()
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray([0, -1, 31, 32, 5, -7, 12, 99])
    got = alltoall_lookup(sharded, ids, mesh)
    want = np.take(np.asarray(table), np.clip(np.asarray(ids), 0, 31), axis=0)
    want[[1, 3, 5, 7]] = 0.0
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_lookup_skewed_ids(mesh):
    """Worst-case routing: every id owned by one shard — the default
    capacity (K/n) must still be lossless."""
    from paddle_tpu.parallel import alltoall_lookup

    table = _table()
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 8, 16))  # shard 0 only
    got, overflow = alltoall_lookup(sharded, ids, mesh, return_overflow=True)
    want = jnp.take(table, ids, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    assert int(overflow) == 0


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_capacity_overflow_detected(mesh):
    from paddle_tpu.parallel import alltoall_lookup

    table = _table()
    sharded = shard_rows(table, mesh)
    ids = jnp.zeros((16,), jnp.int32)  # all to shard 0, 4 per device
    got, overflow = alltoall_lookup(sharded, ids, mesh, capacity=1,
                                    return_overflow=True)
    # 4 model shards hold 4 ids each, all owned by shard 0: capacity 1
    # keeps one per shard, drops 3 per shard
    assert int(overflow) == 4 * 3
    # kept slots still correct
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(table[0]),
                               rtol=1e-6)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_lookup_grad_flows_to_table(mesh):
    """Autodiff through the owner-routed exchange: table gradient equals
    the dense lookup's scatter-add gradient."""
    from paddle_tpu.parallel import alltoall_lookup

    table = _table(16, 4)
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 16, 8))
    w = jnp.asarray(np.random.RandomState(4).randn(8, 4), jnp.float32)

    def loss_sharded(tab):
        return jnp.sum(alltoall_lookup(tab, ids, mesh) * w)

    def loss_dense(tab):
        return jnp.sum(jnp.take(tab, ids, axis=0) * w)

    g_sharded = jax.grad(loss_sharded)(sharded)
    g_dense = jax.grad(loss_dense)(table)
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_alltoall_push_row_grads_matches_dense(mesh):
    from paddle_tpu.parallel import alltoall_push_row_grads

    table = _table(32, 6)
    sharded = shard_rows(table, mesh)
    rng = np.random.RandomState(5)
    ids = jnp.asarray(rng.randint(0, 32, 16))
    grads = jnp.asarray(rng.randn(16, 6), jnp.float32)
    lr = 0.3
    new = alltoall_push_row_grads(sharded, ids, grads, lr, mesh)
    want = np.array(table)
    for i, g in zip(np.asarray(ids), np.asarray(grads)):
        want[i] -= lr * g
    np.testing.assert_allclose(np.asarray(new), want, rtol=1e-5, atol=1e-6)


def test_unique_rows_grad_overflow_flag():
    ids = jnp.asarray([1, 2, 3, 4, 1])
    grads = jnp.ones((5, 2))
    _, _, overflow = unique_rows_grad(ids, grads, max_unique=2,
                                      return_overflow=True)
    assert int(overflow) == 2  # 4 distinct ids, bound 2
    _, _, ok = unique_rows_grad(ids, grads, max_unique=4,
                                return_overflow=True)
    assert int(ok) == 0


def test_alltoall_exchange_volume_in_hlo(mesh):
    """The micro-bench claim (VERDICT item 4): compiled HLO's all-to-all
    traffic is ∝ K·D while the psum path all-reduces shards·K·D."""
    from paddle_tpu.parallel import alltoall_lookup

    table = _table(32, 8)
    sharded = shard_rows(table, mesh)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 32, 16))
    k, d, n = 16, 8, 4

    def count_bytes(hlo_text, opname):
        import re
        total = 0
        for m in re.finditer(
                r"(\w+)\[([\d,]*)\][^\n]*" + opname + r"\(", hlo_text):
            dt, shape = m.group(1), m.group(2)
            size = 1
            for s in shape.split(","):
                if s:
                    size *= int(s)
            width = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4}.get(dt, 4)
            total += size * width
        return total

    a2a_hlo = jax.jit(
        lambda t, i: alltoall_lookup(t, i, mesh)).lower(sharded, ids) \
        .compile().as_text()
    psum_hlo = jax.jit(
        lambda t, i: sharded_lookup(t, i, mesh)).lower(sharded, ids) \
        .compile().as_text()

    a2a_bytes = count_bytes(a2a_hlo, "all-to-all")
    ar_bytes = count_bytes(psum_hlo, "all-reduce")
    # vector traffic per device: a2a ~ K/n * D * 4B (+ id ints);
    # psum all-reduce ~ K * D * 4B
    assert a2a_bytes > 0 and ar_bytes > 0
    assert a2a_bytes <= (k * d * 4) + (k * 4) * 2  # ≤ K·D + id traffic
    assert ar_bytes >= k * d * 4  # the psum path moves the full K·D per shard


class TestHostOffloadEmbedding:
    """The >HBM-table story (SURVEY §7 hard part; reference analog:
    SparsePrefetchRowCpuMatrix host-RAM tables with row pulls)."""

    def _emb(self, vocab=32, dim=4):
        from paddle_tpu.parallel.sparse import HostOffloadEmbedding

        return HostOffloadEmbedding(vocab, dim, init_scale=0.1)

    def test_table_lives_in_host_memory(self):
        emb = self._emb()
        table = emb.init(jax.random.key(0))
        assert table.sharding.memory_kind in HOST_KINDS

    def test_lookup_matches_dense_and_lands_on_device(self):
        emb = self._emb()
        table = emb.init(jax.random.key(0))
        ids = jnp.asarray([3, 7, 3, 31])
        rows = jax.jit(emb.lookup)(table, ids)
        assert rows.sharding.memory_kind in DEV_KINDS
        host_np = np.asarray(jax.device_get(table))
        np.testing.assert_allclose(np.asarray(rows), host_np[np.asarray(ids)],
                                   rtol=1e-6)

    def test_row_sparse_update_touches_only_rows(self):
        emb = self._emb()
        table = emb.init(jax.random.key(0))
        before = np.asarray(jax.device_get(table))
        ids = jnp.asarray([2, 2, 5, -1])  # dup + padding id
        grads = jnp.ones((4, 4), jnp.float32)
        new_table = emb.update(
            table, ids, grads, jnp.asarray(0.5, jnp.float32))
        assert new_table.sharding.memory_kind in HOST_KINDS
        after = np.asarray(jax.device_get(new_table))
        np.testing.assert_allclose(after[2], before[2] - 2 * 0.5, rtol=1e-5)
        np.testing.assert_allclose(after[5], before[5] - 0.5, rtol=1e-5)
        untouched = [i for i in range(32) if i not in (2, 5)]
        np.testing.assert_allclose(after[untouched], before[untouched])

    def test_train_step_end_to_end(self):
        """Gradient flows through the host gather: differentiate at the
        gathered rows (CTR-style) and push row grads back."""
        emb = self._emb(vocab=16, dim=3)
        table = emb.init(jax.random.key(0))
        ids = jnp.asarray([1, 4, 9])
        target = jnp.ones((3, 3), jnp.float32)

        @jax.jit
        def grads(table):
            rows = emb.lookup(table, ids)

            def loss_fn(r):
                return jnp.mean((r - target) ** 2)

            return jax.value_and_grad(loss_fn)(rows)

        def step(table):
            loss, row_g = grads(table)
            new_table = emb.update(
                table, ids, row_g, jnp.asarray(1.0, jnp.float32))
            return new_table, loss

        losses = []
        for _ in range(40):
            table, loss = step(table)
            losses.append(float(loss))
        assert table.sharding.memory_kind in HOST_KINDS
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
