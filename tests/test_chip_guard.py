"""Wedge discipline, enforced: every runnable repo script that can pull
in jax must be chip-safe.

The TPU sits behind a single-claim relay; a killed claimant wedges the
chip for hours (it cost the entire round-3 measurement session —
benchmarks/results_v5e1.md). The container's TPU plugin outranks the
``JAX_PLATFORMS=cpu`` env var at jax-config level, so a script is only
safe if it does one of:

  * import ``scripts.cpu_guard`` (pins cpu unconditionally), or
  * mirror the env request into the config
    (``jax.config.update("jax_platforms", "cpu")``), or
  * declare itself a DELIBERATE chip claimant with a ``# chip-bench``
    marker comment.

Package modules (paddle_tpu/) and tests are exempt: they don't run as
entry points, and tests/conftest.py already double-guards the suite.
"""

import pathlib
import re
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# importing paddle_tpu transitively imports jax, so scripts reaching
# for either are in scope
_PULLS_IN_JAX = re.compile(
    r"^\s*(import jax\b|from jax\b|import paddle_tpu\b|from paddle_tpu\b)",
    re.M)
_SAFE = (
    "scripts.cpu_guard",                      # unconditional cpu pin
    'jax.config.update("jax_platforms", "cpu")',  # env-mirror pattern
    "jax.config.update('jax_platforms', 'cpu')",
    "# chip-bench",                           # deliberate chip claimant
)
# non-entry-point trees: package modules and the pytest suite (the
# conftest double-guards the latter); everything else in the repo is
# treated as runnable
_EXEMPT_PARTS = {"paddle_tpu", "tests", ".git", ".claude", "__pycache__"}


def test_every_jax_script_is_guarded_or_marked():
    offenders = []
    for path in sorted(REPO.rglob("*.py")):
        rel = path.relative_to(REPO)
        if _EXEMPT_PARTS & set(rel.parts[:-1]):
            continue
        text = path.read_text()
        if not _PULLS_IN_JAX.search(text):
            continue
        if not any(s in text for s in _SAFE):
            offenders.append(str(rel))
    assert not offenders, (
        "scripts can pull in jax with no cpu guard, no jax_platforms "
        "cpu config mirror, and no '# chip-bench' marker (a killed "
        f"chip claimant wedges the relay for hours): {offenders}")


def test_cpu_guard_pins_cpu_in_clean_process():
    """The prelude must force cpu in a process whose env does NOT ask
    for it (conftest pins this process, so an in-process assert would
    be vacuous). Reading jax.config doesn't initialize a backend, so
    the child never touches the chip even if the guard were broken."""
    import os

    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    out = subprocess.run(
        [sys.executable, "-c",
         "import scripts.cpu_guard, os, jax; "
         "print(os.environ['JAX_PLATFORMS'], jax.config.jax_platforms)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == ["cpu", "cpu"], out.stdout
