"""Flash attention (Pallas, interpret mode on CPU) vs dense reference —
the cross-backend equivalence strategy of the reference's
test_NetworkCompare.cpp applied to the TPU kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.flash_attention import flash_attention
from paddle_tpu.parallel.ring_attention import dense_attention


def _qkv(np_rng, b=2, t=48, h=2, d=16, t_kv=None):
    t_kv = t if t_kv is None else t_kv
    q = jnp.asarray(np_rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(np_rng.randn(b, t_kv, h, d), jnp.float32)
    v = jnp.asarray(np_rng.randn(b, t_kv, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(np_rng, causal):
    q, k, v = _qkv(np_rng)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_non_divisible_lengths(np_rng):
    # T not a multiple of the block: tail masking must be exact
    q, k, v = _qkv(np_rng, t=37, t_kv=53)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_cross_attention_shapes(np_rng):
    q, k, v = _qkv(np_rng, t=8, t_kv=24)
    out = flash_attention(q, k, v, block_q=8, block_k=8)
    assert out.shape == q.shape


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(np_rng, causal):
    q, k, v = _qkv(np_rng, b=1, t=24, h=2, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=8, block_k=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_jit_composes(np_rng):
    q, k, v = _qkv(np_rng, b=1, t=16, h=1, d=8)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=8,
                                                block_k=8))
    out = f(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bad_rank_raises(np_rng):
    with pytest.raises(ValueError, match="B, T, H, D"):
        flash_attention(jnp.zeros((4, 8, 3)), jnp.zeros((4, 8, 3)),
                        jnp.zeros((4, 8, 3)))


def _masked_dense(q, k, v, lens, causal):
    """key_lens as a dense mask, via the ONE canonical dense impl."""
    b, tq, tk = q.shape[0], q.shape[1], k.shape[1]
    key_ok = jnp.arange(tk)[None, :] < lens[:, None]
    return dense_attention(
        q, k, v, causal=causal,
        mask=jnp.broadcast_to(key_ok[:, None, :], (b, tq, tk)))


@pytest.mark.parametrize("causal", [False, True])
def test_key_lens_matches_masked_dense(np_rng, causal):
    """Per-row key-length bound (variable-length right-padded prefill)
    vs a key-masked dense reference — rows attend only [0, lens[b])."""
    q, k, v = _qkv(np_rng, b=3, t=24, h=2, d=8)
    lens = jnp.asarray([24, 13, 5], jnp.int32)  # incl. non-block-aligned
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          key_lens=lens)
    ref = _masked_dense(q, k, v, lens, causal)
    # rows past their length see garbage queries attending real keys —
    # only positions with at least one valid key are meaningful; here
    # every QUERY row is compared (the mask bounds keys, not queries)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_key_lens_grads_match_masked_dense(np_rng):
    q, k, v = _qkv(np_rng, b=2, t=16, h=1, d=8)
    lens = jnp.asarray([16, 7], jnp.int32)

    gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
        q, k, v, causal=True, block_q=8, block_k=8, key_lens=lens) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(
        _masked_dense(q, k, v, lens, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_key_lens_zero_and_overlong_rows(np_rng):
    """lens=0 rows must output exactly 0 (not the mean of v — NEG_INF
    is finite so an unmasked p would be exp(0)=1 everywhere), matching
    the backward's zero grads; lens>Tkv clamps to the no-mask result."""
    q, k, v = _qkv(np_rng, b=3, t=8, h=1, d=8)
    lens = jnp.asarray([0, 8, 100], jnp.int32)
    out = flash_attention(q, k, v, block_q=8, block_k=8, key_lens=lens)
    np.testing.assert_array_equal(np.asarray(out[0]), 0.0)
    ref = dense_attention(q[1:], k[1:], v[1:])
    np.testing.assert_allclose(np.asarray(out[1:]), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


class TestSlidingWindow:
    def _windowed_dense(self, q, k, v, window):
        tq, tk = q.shape[1], k.shape[1]
        qpos = jnp.arange(tq)[:, None] + (tk - tq)
        mask = (qpos >= jnp.arange(tk)[None, :]) & \
            (qpos - jnp.arange(tk)[None, :] < window)
        return dense_attention(
            q, k, v,
            mask=jnp.broadcast_to(mask, (q.shape[0], tq, tk)))

    @pytest.mark.parametrize("window", [1, 5, 16])
    def test_matches_windowed_dense(self, np_rng, window):
        q, k, v = _qkv(np_rng, b=2, t=40, h=2, d=8)
        out = flash_attention(q, k, v, causal=True, block_q=8,
                              block_k=8, window=window)
        ref = self._windowed_dense(q, k, v, window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_huge_window_equals_full_causal(self, np_rng):
        q, k, v = _qkv(np_rng, b=1, t=24, h=2, d=8)
        out = flash_attention(q, k, v, causal=True, block_q=8,
                              block_k=8, window=10_000)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_windowed_dense(self, np_rng):
        q, k, v = _qkv(np_rng, b=1, t=24, h=1, d=8)
        gf = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=8, block_k=8,
            window=6) ** 2), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(lambda q, k, v: jnp.sum(
            self._windowed_dense(q, k, v, 6) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_validation(self, np_rng):
        q, k, v = _qkv(np_rng, b=1, t=8, h=1, d=8)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, window=4)
        with pytest.raises(ValueError, match="window"):
            flash_attention(q, k, v, causal=True, window=0)

    def test_window_composes_with_key_lens(self, np_rng):
        """All three kernel masks at once — per-row length bound,
        causal, band — including a short row whose band lies entirely
        past its length for late queries."""
        q, k, v = _qkv(np_rng, b=2, t=24, h=1, d=8)
        lens = jnp.asarray([24, 7], jnp.int32)
        window = 5
        out = flash_attention(q, k, v, causal=True, block_q=8,
                              block_k=8, key_lens=lens, window=window)
        qpos = jnp.arange(24)[:, None]
        kpos = jnp.arange(24)[None, :]
        mask = (qpos >= kpos) & (qpos - kpos < window)
        ref = dense_attention(
            q, k, v,
            mask=jnp.broadcast_to(mask, (2, 24, 24))
            & (kpos < lens[:, None, None]))
        # rows/queries with at least one in-band valid key must match;
        # row 1 queries from pos len+window-1 = 11 on have NO valid key
        # (band (q-5, q] ∩ kpos<7 empty) -> kernel returns 0 by contract
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(out[1, :11]),
                                   np.asarray(ref[1, :11]),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(out[1, 11:]), 0.0)


def test_key_lens_shape_validated(np_rng):
    q, k, v = _qkv(np_rng, b=2, t=8, h=1, d=8)
    with pytest.raises(ValueError, match="key_lens"):
        flash_attention(q, k, v, key_lens=jnp.asarray([8, 8, 8]))
