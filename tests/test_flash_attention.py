"""Flash attention (Pallas, interpret mode on CPU) vs dense reference —
the cross-backend equivalence strategy of the reference's
test_NetworkCompare.cpp applied to the TPU kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.flash_attention import flash_attention
from paddle_tpu.parallel.ring_attention import dense_attention


def _qkv(np_rng, b=2, t=48, h=2, d=16, t_kv=None):
    t_kv = t if t_kv is None else t_kv
    q = jnp.asarray(np_rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(np_rng.randn(b, t_kv, h, d), jnp.float32)
    v = jnp.asarray(np_rng.randn(b, t_kv, h, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense(np_rng, causal):
    q, k, v = _qkv(np_rng)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_non_divisible_lengths(np_rng):
    # T not a multiple of the block: tail masking must be exact
    q, k, v = _qkv(np_rng, t=37, t_kv=53)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_cross_attention_shapes(np_rng):
    q, k, v = _qkv(np_rng, t=8, t_kv=24)
    out = flash_attention(q, k, v, block_q=8, block_k=8)
    assert out.shape == q.shape


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(np_rng, causal):
    q, k, v = _qkv(np_rng, b=1, t=24, h=2, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=8, block_k=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_jit_composes(np_rng):
    q, k, v = _qkv(np_rng, b=1, t=16, h=1, d=8)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=8,
                                                block_k=8))
    out = f(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bad_rank_raises(np_rng):
    with pytest.raises(ValueError, match="B, T, H, D"):
        flash_attention(jnp.zeros((4, 8, 3)), jnp.zeros((4, 8, 3)),
                        jnp.zeros((4, 8, 3)))
