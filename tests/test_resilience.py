"""Fault-injection resilience suite (train.resilience + testing.faults
+ the hardened master/data path).

Every test here proves a RECOVERY PATH end-to-end against a
deterministic injected fault — the in-process analog of the reference's
Go runtime tests (reference: go/master/service_internal_test.go kills
trainers mid-pass; trainer/tests run real pservers on localhost). The
three acceptance scenarios from the resilience issue:
  1. preemption (SIGTERM) -> drain save -> restart -> params identical
     to an uninterrupted run;
  2. injected NaN step skipped/rolled back, training completes with
     finite params (rollback reaches the fault-free run's params);
  3. master killed and restarted (HAMaster) mid-pass with a live
     MasterClient -> no lost or duplicated records.
"""

import json
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn, optim
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.testing import FaultError, FaultPlan
from paddle_tpu.train import (
    DivergenceError,
    Preempted,
    ResilientTrainer,
    Trainer,
    Watchdog,
)

pytestmark = pytest.mark.faults


def _model():
    return nn.Sequential([nn.Dense(8, name="fc", activation="relu"),
                          nn.Dense(3, name="out")])


def _loss(o, y):
    return jnp.mean(losses.softmax_cross_entropy(o, y))


def _batches(n=6, seed=0):
    r = np.random.RandomState(seed)
    return [(r.rand(4, 5).astype(np.float32), r.randint(0, 3, 4))
            for _ in range(n)]


def _run(ckpt_dir, factory, *, num_passes=2, event_handler=None, **kw):
    """Fresh Trainer (same seed) + ResilientTrainer over `factory` —
    the restart-the-process idiom, minus the process."""
    tr = Trainer(_model(), _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    rt = ResilientTrainer(tr, str(ckpt_dir), **kw)
    return rt, rt.run(state, factory, num_passes=num_passes,
                      event_handler=event_handler)


def _trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---- acceptance 1: preemption-safe resume ------------------------------

def test_preempt_resume_identical_params(tmp_path):
    """Train, SIGTERM mid-run (drain save fires, Preempted raised),
    restart via a fresh Trainer+ResilientTrainer on the same dir: the
    final params must be IDENTICAL to an uninterrupted run — steps,
    data order and per-step rng all resume exactly."""
    batches = _batches()
    _, ref = _run(tmp_path / "ref", lambda: iter(batches),
                  checkpoint_every_n_batches=2)

    plan = FaultPlan(preempt_at=7)   # mid-pass-1
    with pytest.raises(Preempted) as ei:
        _run(tmp_path / "pre", plan.wrap_batches(lambda: iter(batches)),
             checkpoint_every_n_batches=2)
    assert plan.count("preempt") == 1
    assert ei.value.step == 7        # drained exactly at the boundary

    rt2, resumed = _run(tmp_path / "pre", lambda: iter(batches),
                        checkpoint_every_n_batches=2)
    assert rt2.restored_step == 7
    assert int(resumed.step) == int(ref.step) == 12
    _trees_equal(resumed.params, ref.params)
    _trees_equal(resumed.opt_state, ref.opt_state)


def test_run_resilient_preempt_restart_roundtrip(tmp_path):
    """The one-call entry point: train, SIGTERM mid-run, call
    run_resilient AGAIN with identical arguments (the restarted-process
    idiom) — it resumes and reaches the uninterrupted run's params."""
    from paddle_tpu.train import run_resilient

    batches = _batches()
    kw = dict(input_spec=ShapeSpec((4, 5)), num_passes=2,
              checkpoint_every_n_batches=3, seed=0)

    ref = run_resilient(_model(), _loss, optim.sgd(0.1),
                        lambda: iter(batches),
                        checkpoint_dir=str(tmp_path / "ref"), **kw)

    plan = FaultPlan(preempt_at=5)
    with pytest.raises(Preempted):
        run_resilient(_model(), _loss, optim.sgd(0.1),
                      plan.wrap_batches(lambda: iter(batches)),
                      checkpoint_dir=str(tmp_path / "pre"), **kw)
    out = run_resilient(_model(), _loss, optim.sgd(0.1),
                        lambda: iter(batches),
                        checkpoint_dir=str(tmp_path / "pre"), **kw)
    assert int(out.step) == int(ref.step)
    _trees_equal(out.params, ref.params)


def test_resume_without_faults_is_noop(tmp_path):
    """A second run over a COMPLETED checkpoint dir restores the final
    step and replays nothing (no extra optimizer updates)."""
    batches = _batches()
    _, first = _run(tmp_path / "d", lambda: iter(batches))
    rt, again = _run(tmp_path / "d", lambda: iter(batches))
    assert rt.restored_step == int(first.step)
    assert int(again.step) == int(first.step)
    _trees_equal(again.params, first.params)


# ---- acceptance 2: divergence guard ------------------------------------

def test_nan_step_rollback_converges(tmp_path):
    """An injected all-NaN batch (NaN loss AND grads) is detected, the
    last checkpoint re-restored and the batch replayed (fault fires
    once): training completes with the SAME params as the fault-free
    run — the rollback fully repaired the poisoned update."""
    batches = _batches()
    _, ref = _run(tmp_path / "ref", lambda: iter(batches),
                  checkpoint_every_n_batches=1)

    plan = FaultPlan(nan_batch_at=3)
    rt, out = _run(tmp_path / "nan",
                   plan.wrap_batches(lambda: iter(batches)),
                   checkpoint_every_n_batches=1,
                   bad_step_policy="rollback")
    assert plan.count("nan") == 1
    assert [(b.step, b.action, b.reason) for b in rt.bad_steps] == [
        (3, "rollback", "non-finite loss")]
    assert int(out.step) == int(ref.step)
    _trees_equal(out.params, ref.params)


def test_nan_step_skip_policy(tmp_path):
    """skip: the poisoned update is discarded (params stay finite) but
    the step counter still advances — step must stay == batches
    consumed or every later resume cursor desyncs."""
    batches = _batches()
    plan = FaultPlan(nan_batch_at=2)
    rt, out = _run(tmp_path / "skip",
                   plan.wrap_batches(lambda: iter(batches)),
                   bad_step_policy="skip")
    assert [(b.step, b.action) for b in rt.bad_steps] == [(2, "skip")]
    # 12 batches consumed -> step 12, one of them a no-op update
    assert int(out.step) == 12
    for leaf in jax.tree_util.tree_leaves(out.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_skip_then_preempt_resume_is_exact(tmp_path):
    """The interaction that desyncs naive cursors: a skipped batch
    followed by a preemption. Because skip advances the step counter,
    the resumed run replays NOTHING already applied and reaches the
    same params as the same faults without a preemption."""
    batches = _batches()
    # reference: same NaN skip, NO preemption
    plan_a = FaultPlan(nan_batch_at=2)
    _, ref = _run(tmp_path / "a",
                  plan_a.wrap_batches(lambda: iter(batches)),
                  bad_step_policy="skip", checkpoint_every_n_batches=2)
    # same skip, then SIGTERM at batch 7, then resume
    plan_b = FaultPlan(nan_batch_at=2, preempt_at=7)
    with pytest.raises(Preempted) as ei:
        _run(tmp_path / "b",
             plan_b.wrap_batches(lambda: iter(batches)),
             bad_step_policy="skip", checkpoint_every_n_batches=2)
    assert ei.value.step == 7        # counter == batches consumed
    rt, out = _run(tmp_path / "b", lambda: iter(batches),
                   bad_step_policy="skip",
                   checkpoint_every_n_batches=2)
    assert int(out.step) == int(ref.step) == 12
    _trees_equal(out.params, ref.params)


def test_divergence_budget_hard_fails(tmp_path):
    """Persistently-NaN data exhausts max_bad_steps and raises
    DivergenceError instead of looping forever."""
    r = np.random.RandomState(0)
    nan_batches = [(np.full((4, 5), np.nan, np.float32),
                    r.randint(0, 3, 4)) for _ in range(6)]
    with pytest.raises(DivergenceError) as ei:
        _run(tmp_path / "div", lambda: iter(nan_batches),
             max_bad_steps=2, bad_step_policy="skip")
    assert len(ei.value.bad_steps) == 3
    assert ei.value.bad_steps[-1].action == "fail"


def test_bad_step_budget_resets_on_new_progress(tmp_path):
    """The budget bounds CLUSTERED failures, not the run's lifetime:
    scattered transient faults separated by enough healthy new steps
    each see a fresh budget."""
    batches = _batches(n=12)
    poisoned = list(batches)
    for i in (2, 9):    # two faults, 6 healthy steps apart
        x, y = poisoned[i]
        poisoned[i] = (np.full_like(x, np.nan), y)
    rt, out = _run(tmp_path / "reset", lambda: iter(poisoned),
                   num_passes=1, bad_step_policy="skip",
                   max_bad_steps=1, bad_step_reset_after=3)
    assert len(rt.bad_steps) == 2       # both absorbed
    assert int(out.step) == 12
    # without the reset window the second fault would have been fatal
    with pytest.raises(DivergenceError):
        _run(tmp_path / "noreset", lambda: iter(poisoned),
             num_passes=1, bad_step_policy="skip",
             max_bad_steps=1, bad_step_reset_after=None)


def test_rollback_with_lr_backoff(tmp_path):
    """lr_backoff shrinks the effective LR on each rollback; training
    still completes and records the recovery."""
    batches = _batches()
    plan = FaultPlan(nan_batch_at=2)
    rt, out = _run(tmp_path / "bo",
                   plan.wrap_batches(lambda: iter(batches)),
                   checkpoint_every_n_batches=1,
                   bad_step_policy="rollback", lr_backoff=0.5)
    assert rt._lr_scale == 0.5
    assert int(out.step) == 12
    for leaf in jax.tree_util.tree_leaves(out.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_loss_spike_detection(tmp_path):
    """A finite-but-exploding loss (scaled-up inputs) trips the
    EMA-relative spike guard, not just the NaN check."""
    batches = _batches()
    spiked = list(batches)
    x, y = spiked[4]
    spiked[4] = (x * 1e6, y)     # finite, huge loss
    rt, out = _run(tmp_path / "spike", lambda: iter(spiked),
                   bad_step_policy="skip", loss_spike_factor=100.0)
    assert any("spike" in b.reason for b in rt.bad_steps)
    assert int(out.step) == 12   # skipped batch still ticks the counter
    for leaf in jax.tree_util.tree_leaves(out.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_event_parity_with_trainer(tmp_path):
    """ResilientTrainer must feed handlers the same event protocol as
    Trainer.train: BeginPass / BeginIteration / EndIteration / EndPass
    in order — including BeginPass for a pass a resume lands mid-way
    through."""
    batches = _batches()

    def record(evs):
        def h(ev):
            evs.append(type(ev).__name__ + (
                f":{ev.pass_id}" if hasattr(ev, "pass_id") else ""))
        return h

    evs = []
    tr = Trainer(_model(), _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    rt = ResilientTrainer(tr, str(tmp_path / "ev"))
    rt.run(state, lambda: iter(batches), num_passes=1,
           event_handler=record(evs))
    assert evs[0] == "BeginPass:0" and evs[-1] == "EndPass:0"
    assert evs[1:3] == ["BeginIteration:0", "EndIteration:0"]
    assert evs.count("BeginIteration:0") == 6

    # preempt mid-pass-1, resume: the resumed run must still open
    # pass 1 with BeginPass before its first executed iteration
    plan = FaultPlan(preempt_at=8)
    with pytest.raises(Preempted):
        _run(tmp_path / "ev2", plan.wrap_batches(lambda: iter(batches)),
             checkpoint_every_n_batches=2)
    evs2 = []
    tr2 = Trainer(_model(), _loss, optim.sgd(0.1))
    st2 = tr2.init_state(ShapeSpec((4, 5)))
    rt2 = ResilientTrainer(tr2, str(tmp_path / "ev2"))
    rt2.run(st2, lambda: iter(batches), num_passes=2,
            event_handler=record(evs2))
    assert evs2[0] == "BeginPass:1"          # resumed INTO pass 1
    assert "BeginIteration:1" in evs2
    assert evs2[-1] == "EndPass:1"
    assert "BeginPass:0" not in evs2         # fully-consumed pass


def test_all_checkpoints_corrupt_fails_loudly(tmp_path):
    """Checkpoints exist but none restores (e.g. the model changed
    under the same --checkpoint-dir): run() must REFUSE rather than
    silently restart from scratch — retention would otherwise
    garbage-collect the intact old run."""
    batches = _batches()
    _run(tmp_path / "d", lambda: iter(batches))
    # a DIFFERENT architecture against the same directory
    other = nn.Sequential([nn.Dense(13, name="wide", activation="relu"),
                           nn.Dense(3, name="out")])
    tr = Trainer(other, _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    rt = ResilientTrainer(tr, str(tmp_path / "d"))
    with pytest.raises(RuntimeError, match="none is restorable"):
        rt.run(state, lambda: iter(batches), num_passes=1)


def test_record_reader_at_least_once_mode(tmp_path):
    """exactly_once=False (the reference Go client's ordering) still
    delivers the full pass for a healthy single worker."""
    from paddle_tpu.native.taskqueue import (MasterClient, MasterServer,
                                             TaskQueue)

    path = _write_dataset(tmp_path, n=20, per_chunk=5)
    q = TaskQueue()
    q.add_file_chunks(path, chunks_per_task=1)
    q.start()
    with MasterServer(q) as srv:
        cli = MasterClient(port=srv.port, timeout=2.0)
        got = sorted(json.loads(r)["i"] for r in
                     cli.record_reader(exactly_once=False)())
        cli.close()
    assert got == list(range(20))


# ---- checkpoint-write faults -------------------------------------------

def test_checkpoint_write_failure_tolerated(tmp_path):
    """An OSError on a cadence save is absorbed: training continues,
    the gap is visible in .save_errors, and a later save lands."""
    batches = _batches()
    tr = Trainer(_model(), _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    from paddle_tpu.train import CheckpointManager

    plan = FaultPlan(checkpoint_error_at=1)
    mgr = plan.wrap_checkpoint_manager(
        CheckpointManager(str(tmp_path / "c"), max_to_keep=3))
    rt = ResilientTrainer(tr, str(tmp_path / "c"),
                          checkpoint_every_n_batches=2,
                          checkpoint_manager=mgr)
    out = rt.run(state, lambda: iter(batches), num_passes=2)
    assert plan.count("ckpt") == 1
    assert len(rt.save_errors) == 1
    assert int(out.step) == 12
    assert mgr.latest_step() == 12    # later saves were durable


def test_drain_save_retries_through_oserror(tmp_path):
    """The preemption drain save retries a transient OSError — the
    final checkpoint must not be lost to one flaky write."""
    batches = _batches()
    tr = Trainer(_model(), _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    from paddle_tpu.train import CheckpointManager

    # save #0 is the step-0 anchor; the drain save (#1) fails once,
    # its in-drain retry succeeds
    plan = FaultPlan(checkpoint_error_at=1, preempt_at=3)
    mgr = plan.wrap_checkpoint_manager(
        CheckpointManager(str(tmp_path / "c")))
    rt = ResilientTrainer(tr, str(tmp_path / "c"),
                          checkpoint_manager=mgr)
    with pytest.raises(Preempted) as ei:
        rt.run(state, plan.wrap_batches(lambda: iter(batches)),
               num_passes=2)
    assert ei.value.step == 3
    assert plan.count("ckpt") == 1
    assert mgr.latest_step() == 3     # the retry made it durable


# ---- watchdog ----------------------------------------------------------

def test_watchdog_fires_on_stall():
    fired = []
    wd = Watchdog(0.2, lambda elapsed: fired.append(elapsed),
                  poll_s=0.02)
    wd.start()
    try:
        deadline = time.time() + 5
        while not fired and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert wd.fired and fired and fired[0] >= 0.2


def test_watchdog_petting_prevents_fire():
    fired = []
    with Watchdog(0.3, lambda e: fired.append(e), poll_s=0.02) as wd:
        for _ in range(10):
            time.sleep(0.05)
            wd.pet()
    assert not fired and not wd.fired


def test_watchdog_in_training_loop(tmp_path):
    """Wired through ResilientTrainer: a healthy run pets it every
    step and it never fires."""
    fired = []
    rt, out = _run(tmp_path / "wd", lambda: iter(_batches()),
                   watchdog_timeout_s=30.0,
                   watchdog_on_timeout=lambda e: fired.append(e))
    assert int(out.step) == 12 and not fired


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        Watchdog(0.0)


# ---- acceptance 3: master death + reader path --------------------------

def _write_dataset(tmp_path, n=60, per_chunk=5):
    from paddle_tpu.native import write_records

    path = str(tmp_path / "train.rio")
    write_records(path, [json.dumps({"i": i}).encode()
                         for i in range(n)], records_per_chunk=per_chunk)
    return path


def test_master_kill_restart_no_lost_or_duplicated_records(tmp_path):
    """A MasterClient streaming records survives its master being
    killed and replaced (HAMaster recover-on-start on the same port):
    the pass completes with EVERY record delivered exactly once —
    finished tasks stay finished (snapshot), the in-flight lease
    returns to todo, and the client's backoff-reconnect carries the
    RPCs across the blackout."""
    from paddle_tpu.native.taskqueue import HAMaster, MasterClient

    path = _write_dataset(tmp_path)
    port = _free_port()
    snap = str(tmp_path / "snaps")

    m1 = HAMaster(snap, port=port, interval_s=0)
    m1.queue.add_file_chunks(path, chunks_per_task=1)
    m1.queue.start()

    cli = MasterClient(port=port, timeout=2.0, retries=10,
                       backoff_base=0.05, backoff_max=0.5, seed=0)
    it = cli.record_reader()()
    got = [json.loads(next(it))["i"] for _ in range(27)]  # mid-task

    m1.checkpoint()                  # durable state at the kill point
    m1.stop(final_snapshot=False)    # master dies

    holder = {}

    def restart():
        time.sleep(0.3)              # blackout the client must ride out
        holder["m2"] = HAMaster(snap, port=port, interval_s=0)
        holder["m2"].queue.start()

    t = threading.Thread(target=restart)
    t.start()
    try:
        got += [json.loads(r)["i"] for r in it]
    finally:
        t.join()
        holder["m2"].stop(final_snapshot=False)
        cli.close()
    assert sorted(got) == list(range(60))     # nothing lost
    assert len(got) == len(set(got))          # nothing duplicated


def test_record_reader_fails_lease_and_repulls(tmp_path, monkeypatch):
    """A task whose read blows up (flaky disk/NFS) is lease-failed and
    re-pulled instead of killing the pass — full coverage, no dups."""
    from paddle_tpu import native
    from paddle_tpu.native import recordio
    from paddle_tpu.native.taskqueue import (MasterClient, MasterServer,
                                             TaskQueue)

    path = _write_dataset(tmp_path, n=30, per_chunk=5)
    q = TaskQueue(timeout_ms=60000, max_retries=3)
    q.add_file_chunks(path, chunks_per_task=1)
    q.start()

    real = recordio.RecordReader
    state = {"failed": False}

    class Flaky(real):
        def __init__(self, *a, **kw):
            if not state["failed"]:
                state["failed"] = True
                raise FaultError("injected task-read failure")
            super().__init__(*a, **kw)

    monkeypatch.setattr(recordio, "RecordReader", Flaky)
    with MasterServer(q) as srv:
        cli = MasterClient(port=srv.port, timeout=2.0)
        got = sorted(json.loads(r)["i"]
                     for r in cli.record_reader(max_task_failures=2)())
        cli.close()
    assert state["failed"]           # the fault actually fired
    assert got == list(range(30))


def test_record_reader_gives_up_after_budget(tmp_path, monkeypatch):
    from paddle_tpu.native import recordio
    from paddle_tpu.native.taskqueue import (MasterClient, MasterServer,
                                             TaskQueue)

    path = _write_dataset(tmp_path, n=10, per_chunk=5)
    q = TaskQueue(timeout_ms=60000, max_retries=10)
    q.add_file_chunks(path, chunks_per_task=1)
    q.start()

    class AlwaysBroken:
        def __init__(self, *a, **kw):
            raise FaultError("injected: permanently broken reader")

    monkeypatch.setattr(recordio, "RecordReader", AlwaysBroken)
    with MasterServer(q) as srv:
        cli = MasterClient(port=srv.port, timeout=2.0)
        with pytest.raises(FaultError):
            list(cli.record_reader(max_task_failures=2)())
        cli.close()


def test_master_client_survives_injected_connection_drop(tmp_path):
    """FaultPlan.wrap_master_client: the socket is torn down right
    before an RPC; the client's reconnect must carry the call with the
    server still up."""
    from paddle_tpu.native.taskqueue import (MasterClient, MasterServer,
                                             TaskQueue, TaskStatus)

    q = TaskQueue()
    q.add_task(b"alpha")
    q.add_task(b"beta")
    q.start()
    with MasterServer(q) as srv:
        cli = FaultPlan(master_drop_at=1).wrap_master_client(
            MasterClient(port=srv.port, timeout=2.0, seed=3))
        seen = []
        while True:
            st, tid, payload = cli.get_task()   # call #1 hits the drop
            if st != TaskStatus.OK:
                break
            seen.append(payload)
            cli.finish_task(tid)
        assert sorted(seen) == [b"alpha", b"beta"]
        assert q.counts()["done"] == 2
        cli.close()


def test_master_client_unreachable_raises_not_hangs():
    """A dead address must fail with ConnectionError after the bounded
    retry schedule — never block forever (every socket op has a default
    timeout now)."""
    from paddle_tpu.native.taskqueue import MasterClient

    port = _free_port()     # nothing listening here
    t0 = time.time()
    with pytest.raises((ConnectionError, OSError)):
        MasterClient(port=port, timeout=0.5, retries=1,
                     backoff_base=0.01, backoff_max=0.05)
    assert time.time() - t0 < 10


# ---- data.reader.retrying ----------------------------------------------

def test_retrying_reader_recovers_transient_fault():
    from paddle_tpu.data import reader as R

    items = list(range(10))
    plan = FaultPlan(reader_error_at=4)
    attempts = []
    r = R.retrying(plan.wrap_reader(lambda: iter(items)),
                   max_retries=2, backoff_base=0.001, seed=0,
                   retryable=(FaultError,),
                   on_retry=lambda n, e: attempts.append((n, str(e))))
    got = list(r())
    assert attempts and plan.count("reader") == 1
    # a plain in-memory reader replays from the start (documented):
    # partial first attempt + one full replay
    assert got == items[:4] + items


def test_retrying_reader_exhausts_budget():
    from paddle_tpu.data import reader as R

    def always_fails():
        raise FaultError("permanent")
        yield  # pragma: no cover

    r = R.retrying(always_fails, max_retries=2, backoff_base=0.001,
                   retryable=(FaultError,))
    with pytest.raises(FaultError):
        list(r())


def test_retrying_budget_is_consecutive():
    """Yield progress resets the retry budget — scattered transient
    faults across a long stream must not accumulate to a kill."""
    from paddle_tpu.data import reader as R

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        base = calls["n"] * 100
        yield base
        if calls["n"] < 4:           # fails after one yield, 3 times
            raise FaultError("transient")
        yield base + 1

    got = list(R.retrying(flaky, max_retries=1, backoff_base=0.001,
                          retryable=(FaultError,))())
    assert calls["n"] == 4 and got[-1] == 401


# ---- CLI wiring --------------------------------------------------------

def test_cli_exposes_resilience_flags():
    from paddle_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["train", "--config", "x.py", "--checkpoint-dir", "/tmp/c",
         "--checkpoint-every", "5", "--bad-step-policy", "skip",
         "--max-bad-steps", "7", "--lr-backoff", "0.5",
         "--watchdog-timeout", "120"])
    assert args.checkpoint_dir == "/tmp/c"
    assert args.checkpoint_every == 5
    assert args.bad_step_policy == "skip"
    assert args.max_bad_steps == 7
    assert args.lr_backoff == 0.5
    assert args.watchdog_timeout == 120.0


# ---- PR 3 satellites: event parity + corrupt-latest drain save ---------

def _collect_events(tmp_path, plan, policy, subdir):
    from paddle_tpu.train import events as E

    events = []
    rt, out = _run(tmp_path / subdir,
                   plan.wrap_batches(lambda: iter(_batches())),
                   num_passes=1, bad_step_policy=policy,
                   checkpoint_every_n_batches=2,
                   event_handler=events.append)
    begins = [(e.pass_id, e.batch_id) for e in events
              if isinstance(e, E.BeginIteration)]
    ends = [(e.pass_id, e.batch_id, e.outcome) for e in events
            if isinstance(e, E.EndIteration)]
    return begins, ends, out


def test_bad_step_skip_closes_iteration_events(tmp_path):
    """Event parity: the skip path must emit a closing EndIteration
    (carrying the fault outcome) — consumers never see an unclosed
    iteration."""
    begins, ends, _ = _collect_events(
        tmp_path, FaultPlan(nan_batch_at=2), "skip", "ev-skip")
    assert len(begins) == len(ends) == 6
    assert [(p, b) for p, b, _ in ends] == begins
    assert [o for _, b, o in ends if b == 2] == ["skip"]
    assert all(o == "ok" for _, b, o in ends if b != 2)


def test_bad_step_rollback_closes_iteration_events(tmp_path):
    """Rollback unwinds the drive loop — but not before closing the
    iteration whose step went bad. Replayed iterations get their own
    Begin/End pairs, so counts stay equal."""
    begins, ends, _ = _collect_events(
        tmp_path, FaultPlan(nan_batch_at=2), "rollback", "ev-rb")
    assert len(begins) == len(ends)
    assert [(p, b) for p, b, _ in ends] == begins
    outcomes = [o for _, b, o in ends if b == 2]
    # first visit rolled back, the replay (fault spent) is healthy
    assert outcomes == ["rollback", "ok"]


def test_divergence_failure_closes_iteration_events(tmp_path):
    """Even the hard-fail arm (budget spent -> DivergenceError) closes
    its iteration with outcome 'fail'."""
    from paddle_tpu.train import events as E

    events = []
    plan = FaultPlan(nan_batch_at=1, once=False)   # every replay is bad
    with pytest.raises(DivergenceError):
        _run(tmp_path / "ev-fail",
             plan.wrap_batches(lambda: iter(_batches())),
             num_passes=1, bad_step_policy="skip", max_bad_steps=0,
             event_handler=events.append)
    begins = [e for e in events if isinstance(e, E.BeginIteration)]
    ends = [e for e in events if isinstance(e, E.EndIteration)]
    assert len(begins) == len(ends)
    assert ends[-1].outcome == "fail"


def test_drain_save_overwrites_corrupt_latest_step(tmp_path):
    """The PR 2 known finding: a known-corrupt NEWEST checkpoint must
    not satisfy the latest-step save dedupe. After a fallback-restore
    past it, the replayed run's final save must WRITE (overwriting the
    corpse), so a third run restores the true final step instead of
    falling back again."""
    import os

    from paddle_tpu.train import restore_with_fallback

    batches = _batches()
    _, ref = _run(tmp_path / "c", lambda: iter(batches), num_passes=1,
                  checkpoint_every_n_batches=2)
    final_step = int(ref.step)

    # corrupt the newest committed step the way a power cut does:
    # commit marker present, array files truncated
    step_dir = os.path.join(str(tmp_path / "c"), str(final_step))
    assert os.path.isdir(step_dir)
    for root, _dirs, files in os.walk(step_dir):
        for fn in files:
            if fn.endswith((".json", "metadata")):
                continue
            with open(os.path.join(root, fn), "wb"):
                pass

    rt2, out = _run(tmp_path / "c", lambda: iter(batches), num_passes=1,
                    checkpoint_every_n_batches=2)
    assert rt2.restored_step == final_step - 2      # fell back past it
    assert int(out.step) == final_step

    # the replayed final step is now DURABLE: a fresh manager restores
    # it directly (no fallback), with the reference run's params
    tr = Trainer(_model(), _loss, optim.sgd(0.1))
    template = tr.init_state(ShapeSpec((4, 5)))
    from paddle_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "c"))
    bad = []
    restored, step = restore_with_fallback(mgr, template, bad_steps=bad)
    assert step == final_step
    assert bad == []
    _trees_equal(restored.params, ref.params)
    mgr.close()
