"""Tests for SSD detection ops, NCE, hierarchical sigmoid, maxout,
multiplex, conv3d (reference test model: gserver/tests/test_LayerGrad.cpp
covers MultiBoxLoss/PriorBox/NCE/hsigmoid/maxout variants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import conv, detection, linalg, sampling
from tests.gradcheck import directional_grad_check


# ---- prior boxes / box codec ----

def test_prior_boxes_shapes_and_range():
    pb = detection.prior_boxes((2, 2), (64, 64), min_sizes=[16.0],
                               max_sizes=[32.0], aspect_ratios=[2.0])
    # per cell: 1 min + 1 max + 2 ratio boxes = 4
    assert pb.shape == (2 * 2 * 4, 4)
    assert (pb >= 0).all() and (pb <= 1).all()
    # first cell's min box centered at (0.25, 0.25)
    np.testing.assert_allclose(pb[0], [0.25 - 0.125, 0.25 - 0.125,
                                       0.25 + 0.125, 0.25 + 0.125])


def test_box_encode_decode_roundtrip():
    priors = jnp.asarray([[0.1, 0.1, 0.4, 0.5], [0.5, 0.5, 0.9, 0.8]])
    gt = jnp.asarray([[0.15, 0.12, 0.45, 0.52], [0.48, 0.52, 0.88, 0.79]])
    deltas = detection.encode_boxes(gt, priors)
    back = detection.decode_boxes(deltas, priors)
    np.testing.assert_allclose(np.asarray(back), np.asarray(gt), atol=1e-6)


def test_iou_values():
    a = jnp.asarray([[0.0, 0.0, 1.0, 1.0]])
    b = jnp.asarray([[0.0, 0.0, 0.5, 1.0], [1.0, 1.0, 2.0, 2.0]])
    got = np.asarray(detection.iou(a, b))
    np.testing.assert_allclose(got, [[0.5, 0.0]], atol=1e-6)


def test_match_priors_forced_and_threshold():
    priors = jnp.asarray([
        [0.0, 0.0, 0.2, 0.2],   # overlaps gt0 strongly
        [0.5, 0.5, 0.7, 0.7],   # overlaps gt1 weakly
        [0.8, 0.8, 1.0, 1.0],   # no overlap
    ])
    gt = jnp.asarray([[0.0, 0.0, 0.2, 0.2], [0.55, 0.55, 0.95, 0.95]])
    valid = jnp.asarray([True, True])
    match = np.asarray(detection.match_priors(priors, gt, valid, 0.5))
    assert match[0] == 0
    assert match[1] == 1  # forced: best prior for gt1 even if IoU < thresh
    assert match[2] in (-1, 1)


def test_multibox_loss_decreases_with_better_preds():
    priors = jnp.asarray(detection.prior_boxes((4, 4), (64, 64),
                                               min_sizes=[24.0]))
    n = priors.shape[0]
    gt = jnp.asarray([[0.1, 0.1, 0.35, 0.35]])
    labels = jnp.asarray([1])
    valid = jnp.asarray([True])
    match = detection.match_priors(priors, gt, valid, 0.5)
    perfect_loc = detection.encode_boxes(
        jnp.broadcast_to(gt[0], (n, 4)), priors)
    perfect_conf = jnp.where(
        (match >= 0)[:, None], jnp.asarray([[-5.0, 5.0]]),
        jnp.asarray([[5.0, -5.0]]))
    good = detection.multibox_loss(perfect_loc, perfect_conf, priors,
                                   gt, labels, valid)
    bad = detection.multibox_loss(jnp.zeros((n, 4)), jnp.zeros((n, 2)),
                                  priors, gt, labels, valid)
    assert float(good) < float(bad)


def test_multibox_loss_gradcheck():
    priors = jnp.asarray(detection.prior_boxes((2, 2), (32, 32),
                                               min_sizes=[12.0]))
    n = priors.shape[0]
    gt = jnp.asarray([[0.2, 0.2, 0.6, 0.6]])
    rng = np.random.RandomState(0)
    x = {"loc": jnp.asarray(rng.randn(n, 4) * 0.1),
         "conf": jnp.asarray(rng.randn(n, 3) * 0.1)}

    def f(p):
        return detection.multibox_loss(
            p["loc"], p["conf"], priors, gt, jnp.asarray([1]),
            jnp.asarray([True]))

    directional_grad_check(f, x, rtol=5e-3)


def test_match_priors_padded_gt_cannot_clobber():
    """A padded (invalid) GT's argmax lands on prior 0; it must not erase
    prior 0's real match."""
    priors = jnp.asarray([[0.0, 0.0, 0.2, 0.2], [0.6, 0.6, 0.8, 0.8]])
    gt = jnp.asarray([[0.0, 0.0, 0.22, 0.22], [0.0, 0.0, 0.0, 0.0]])
    valid = jnp.asarray([True, False])
    match = np.asarray(detection.match_priors(priors, gt, valid, 0.5))
    assert match[0] == 0
    assert match[1] == -1


def test_nms_mask_suppression_chain():
    # A(0.9) suppresses B(0.8); B would suppress C(0.7) but B is gone;
    # A does not overlap C -> keep A and C
    boxes = jnp.asarray([
        [0.0, 0.0, 0.4, 0.4],
        [0.2, 0.2, 0.6, 0.6],
        [0.42, 0.42, 0.8, 0.8],
    ])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    keep = np.asarray(detection.nms_mask(boxes, scores, iou_threshold=0.1))
    np.testing.assert_array_equal(keep, [True, False, True])


def test_detection_output_end_to_end():
    priors = jnp.asarray(detection.prior_boxes((4, 4), (64, 64),
                                               min_sizes=[24.0]))
    n = priors.shape[0]
    # target box near prior 0's cell
    target = jnp.asarray([0.05, 0.05, 0.3, 0.3])
    loc = detection.encode_boxes(jnp.broadcast_to(target, (n, 4)), priors)
    conf = jnp.full((n, 2), -3.0).at[:, 0].set(3.0)
    conf = conf.at[0].set(jnp.asarray([-3.0, 3.0]))  # prior 0 confident class 1
    classes, scores, boxes = detection.detection_output(
        loc, conf, priors, num_classes=2, top_k=5)
    assert classes.shape == (5,) and boxes.shape == (5, 4)
    assert int(classes[0]) == 1
    assert float(scores[0]) > 0.9
    np.testing.assert_allclose(np.asarray(boxes[0]), np.asarray(target),
                               atol=1e-5)


# ---- NCE / hsigmoid ----

def test_nce_loss_prefers_true_class():
    rng = np.random.RandomState(0)
    v, d, b, s = 50, 8, 4, 10
    weights = jnp.asarray(rng.randn(v, d) * 0.1)
    bias = jnp.zeros((v,))
    hidden = jnp.asarray(rng.randn(b, d))
    labels = jnp.asarray([3, 7, 11, 13])
    noise = jnp.asarray(rng.randint(0, v, (b, s)))
    base = sampling.nce_loss(weights, bias, hidden, labels, noise)
    assert base.shape == (b,)
    # push true-class weights toward hidden -> loss must drop
    better = weights.at[labels].add(0.5 * hidden)
    improved = sampling.nce_loss(better, bias, hidden, labels, noise)
    assert float(improved.mean()) < float(base.mean())


def test_nce_loss_gradcheck():
    rng = np.random.RandomState(1)
    v, d, b, s = 12, 4, 3, 5
    x = {"w": jnp.asarray(rng.randn(v, d) * 0.3),
         "b": jnp.asarray(rng.randn(v) * 0.1),
         "h": jnp.asarray(rng.randn(b, d) * 0.3)}
    labels = jnp.asarray([1, 5, 9])
    noise = jnp.asarray(rng.randint(0, v, (b, s)))

    def f(p):
        return sampling.nce_loss(p["w"], p["b"], p["h"], labels, noise).sum()

    directional_grad_check(f, x)


def test_nce_with_sampler_correction():
    rng = np.random.RandomState(2)
    v, d, b, s = 20, 4, 2, 6
    weights = jnp.asarray(rng.randn(v, d) * 0.1)
    bias = jnp.zeros((v,))
    hidden = jnp.asarray(rng.randn(b, d))
    labels = jnp.asarray([0, 1])
    key = jax.random.key(0)
    noise = sampling.log_uniform_sample(key, s, v, shape=(b,))
    assert noise.shape == (b, s) and (np.asarray(noise) < v).all()
    probs = sampling.log_uniform_prob(jnp.arange(v), v)
    assert float(probs.sum()) == pytest.approx(1.0, abs=1e-5)
    out = sampling.nce_loss(weights, bias, hidden, labels, noise,
                            noise_probs=probs)
    assert np.isfinite(np.asarray(out)).all()


def test_binary_tree_codes():
    ids, signs = sampling.build_binary_tree_codes(4)
    # 4 classes: 3 internal nodes, depth 2; every leaf has a full path
    assert ids.shape == (4, 2)
    assert (ids >= 0).all()
    # root decisions split classes 0,1 (left) vs 2,3 (right)
    assert signs[0, 0] == signs[1, 0] != signs[2, 0]


def test_hsigmoid_sums_to_one():
    """Sum over classes of exp(log P(class)) == 1 for a proper tree."""
    rng = np.random.RandomState(3)
    num_classes, d = 8, 5
    ids, signs = sampling.build_binary_tree_codes(num_classes)
    w = jnp.asarray(rng.randn(num_classes - 1, d) * 0.3)
    b = jnp.asarray(rng.randn(num_classes - 1) * 0.1)
    h = jnp.asarray(rng.randn(2, d))
    logp = sampling.hsigmoid_predict(w, b, h, ids, signs)
    totals = np.exp(np.asarray(logp)).sum(-1)
    np.testing.assert_allclose(totals, [1.0, 1.0], rtol=1e-5)
    # loss == -logp at the label
    labels = jnp.asarray([2, 6])
    loss = sampling.hsigmoid_loss(w, b, h, labels, ids, signs)
    np.testing.assert_allclose(
        np.asarray(loss),
        -np.asarray(logp)[np.arange(2), np.asarray(labels)], rtol=1e-5)


def test_hsigmoid_gradcheck():
    rng = np.random.RandomState(4)
    num_classes, d = 6, 4
    ids, signs = sampling.build_binary_tree_codes(num_classes)
    x = {"w": jnp.asarray(rng.randn(num_classes - 1, d) * 0.3),
         "b": jnp.asarray(rng.randn(num_classes - 1) * 0.1),
         "h": jnp.asarray(rng.randn(3, d) * 0.3)}
    labels = jnp.asarray([0, 3, 5])

    def f(p):
        return sampling.hsigmoid_loss(p["w"], p["b"], p["h"], labels,
                                      ids, signs).sum()

    directional_grad_check(f, x)


# ---- maxout / multiplex / conv3d ----

def test_maxout():
    x = jnp.asarray([[1.0, 5.0, 2.0, 8.0]])
    np.testing.assert_allclose(np.asarray(conv.maxout(x, 2)), [[5.0, 8.0]])
    with pytest.raises(ValueError, match="divisible"):
        conv.maxout(x, 3)


def test_multiplex():
    a = jnp.asarray([[1.0, 1.0], [2.0, 2.0]])
    b = jnp.asarray([[3.0, 3.0], [4.0, 4.0]])
    out = linalg.multiplex(jnp.asarray([1, 0]), a, b)
    np.testing.assert_allclose(np.asarray(out), [[3.0, 3.0], [2.0, 2.0]])


def test_conv3d_matches_manual():
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(1, 3, 4, 4, 2))
    k = jnp.asarray(rng.randn(2, 2, 2, 2, 3))
    y = conv.conv3d(x, k, padding="VALID")
    assert y.shape == (1, 2, 3, 3, 3)
    # one output element by hand
    want = float((np.asarray(x)[0, :2, :2, :2] * np.asarray(k)[..., 0]).sum())
    assert float(y[0, 0, 0, 0, 0]) == pytest.approx(want, rel=1e-5)


def test_pool3d():
    x = jnp.arange(16.0).reshape(1, 2, 2, 4, 1)
    mx = conv.max_pool3d(x, (2, 2, 2))
    assert mx.shape == (1, 1, 1, 2, 1)
    np.testing.assert_allclose(np.asarray(mx).reshape(-1), [13.0, 15.0])
    av = conv.avg_pool3d(x, (2, 2, 2))
    np.testing.assert_allclose(np.asarray(av).reshape(-1), [6.5, 8.5])
    # padded average excludes the padding from the divisor (exclusive avg)
    ones = jnp.ones((1, 2, 2, 2, 1))
    av_pad = conv.avg_pool3d(ones, 2, stride=1, padding=1)
    np.testing.assert_allclose(np.asarray(av_pad), np.ones_like(av_pad))


def test_detection_output_pads_to_top_k():
    # 3 priors, 2 classes -> (C-1)*cap = 3 candidates < top_k = 100
    priors = jnp.asarray([[0.1, 0.1, 0.3, 0.3],
                          [0.4, 0.4, 0.6, 0.6],
                          [0.7, 0.7, 0.9, 0.9]])
    loc = jnp.zeros((3, 4))
    conf = jnp.zeros((3, 2))
    classes, scores, boxes = detection.detection_output(
        loc, conf, priors, num_classes=2, top_k=100)
    assert classes.shape == (100,)
    assert scores.shape == (100,)
    assert boxes.shape == (100, 4)


def test_match_priors_duplicate_best_prior_deterministic():
    # two valid GTs whose best prior is the same: highest GT index wins
    priors = jnp.asarray([[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0]])
    gt = jnp.asarray([[0.0, 0.0, 0.5, 0.5], [0.01, 0.01, 0.5, 0.5]])
    valid = jnp.asarray([True, True])
    match = np.asarray(detection.match_priors(priors, gt, valid, 0.99))
    assert match[0] == 1
