"""Nested (2-level) sequence ops, device prefetch, CTR sparse model."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import data, optim
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.data.batch import pack_sequences
from paddle_tpu.data.feeder import prefetch_to_device
from paddle_tpu.models.ctr import CTRModel
from paddle_tpu.ops import sequence as S


def _nested_batch():
    # outer 0 holds subseqs [1,2,3] and [4,5]; outer 1 holds [6]
    seqs = [np.asarray([1.0, 2, 3]), np.asarray([4.0, 5]),
            np.asarray([6.0])]
    return pack_sequences(seqs, capacity=8, max_seqs=4,
                          outer_ids=[0, 0, 1])


def test_outer_of_inner_map():
    b = _nested_batch()
    m = np.asarray(S.outer_of_inner_map(
        jnp.asarray(b.segment_ids), jnp.asarray(b.outer_segment_ids), 4))
    assert list(m[:3]) == [0, 0, 1]
    assert m[3] >= 2  # empty inner slot -> sentinel


def test_nested_pool():
    b = _nested_batch()
    ooi = S.outer_of_inner_map(
        jnp.asarray(b.segment_ids), jnp.asarray(b.outer_segment_ids), 4)
    out = np.asarray(S.nested_pool(
        jnp.asarray(b.tokens), jnp.asarray(b.segment_ids), ooi, 4, 2,
        inner_mode="mean", outer_mode="mean"))
    # outer0: mean(mean(1,2,3)=2, mean(4,5)=4.5) = 3.25; outer1: 6
    np.testing.assert_allclose(out[:2], [3.25, 6.0], rtol=1e-6)

    out_sum = np.asarray(S.nested_pool(
        jnp.asarray(b.tokens), jnp.asarray(b.segment_ids), ooi, 4, 2,
        inner_mode="sum", outer_mode="sum"))
    np.testing.assert_allclose(out_sum[:2], [6 + 9, 6.0], rtol=1e-6)


def test_expand_and_first_subseq():
    b = _nested_batch()
    ooi = S.outer_of_inner_map(
        jnp.asarray(b.segment_ids), jnp.asarray(b.outer_segment_ids), 4)
    outer_vals = jnp.asarray([10.0, 20.0])
    inner = np.asarray(S.expand_outer_to_inner(outer_vals, ooi))
    assert list(inner[:3]) == [10.0, 10.0, 20.0]
    assert inner[3] == 0.0  # invalid slot zeroed

    inner_vals = jnp.asarray([1.0, 2.0, 3.0, 99.0])
    firsts = np.asarray(S.first_subseq_of_outer(inner_vals, ooi, 2))
    assert list(firsts) == [1.0, 3.0]


def test_prefetch_to_device_order_and_exhaustion():
    src = [jnp.asarray([i]) for i in range(5)]
    got = [int(x[0]) for x in prefetch_to_device(iter(src), size=2)]
    assert got == [0, 1, 2, 3, 4]
    assert list(prefetch_to_device(iter([]), size=3)) == []


def test_ctr_model_trains_and_updates_only_touched_rows():
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, model=2))
    vocab, slots, batch = 64, 6, 16
    model = CTRModel(vocab=vocab, embed_dim=8, mesh=mesh, hidden=(16,))
    params, mlp_state = model.init(jax.random.key(0), batch, slots)
    opt = optim.adam(1e-2)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)

    rng = np.random.RandomState(0)
    # synthetic CTR: label correlates with presence of low ids
    ids = rng.randint(0, vocab, (batch, slots)).astype(np.int32)
    ids[:, -2:] = vocab  # empty sentinel slots
    labels = (ids[:, :4].min(1) < vocab // 3).astype(np.float32)
    ids_j, labels_j = jnp.asarray(ids), jnp.asarray(labels)

    deep_before = np.asarray(jax.device_get(params["deep"]))
    losses_seen = []
    p = params
    for i in range(10):
        p, opt_state, loss = step(p, opt_state, ids_j, labels_j,
                                  jnp.float32(0.1), i, jax.random.key(i))
        losses_seen.append(float(loss))
    assert losses_seen[-1] < losses_seen[0], losses_seen
    deep_after = np.asarray(jax.device_get(p["deep"]))

    touched = np.unique(ids[ids < vocab])
    untouched = np.setdiff1d(np.arange(vocab + 1), touched)
    # rows never looked up must be bit-identical (row-sparse update)
    np.testing.assert_array_equal(deep_after[untouched],
                                  deep_before[untouched])
    assert not np.allclose(deep_after[touched], deep_before[touched])


def test_ctr_step_compiles_once():
    """The second step must HIT the tracing cache: init places the MLP
    on the mesh so step outputs round-trip with identical avals (a miss
    here silently doubles compile time and poisoned the round-3 chip
    benchmark)."""
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, model=2))
    model = CTRModel(vocab=128, embed_dim=8, mesh=mesh, hidden=(16,))
    params, mlp_state = model.init(jax.random.key(0), 16, 4)
    opt = optim.adam(1e-2)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (16, 4)),
                      jnp.int32)
    labels = jnp.asarray(np.random.RandomState(1).randint(0, 2, 16),
                         jnp.float32)
    for i in range(3):
        params, opt_state, loss = step(
            params, opt_state, ids, labels, jnp.float32(0.1),
            jnp.asarray(i, jnp.int32), jax.random.key(i))
    assert step._cache_size() == 1, step._cache_size()
