"""Tiered hot-row embedding cache + streaming online learning (ISSUE 19).

Three depths:

- **The cache alone** — a fake backing with injectable watermarks and
  failover counters proves the freshness machinery row by row: the
  staleness bound (`shard_wm - row_wm <= max_staleness`) decides every
  serve, misses coalesce into ONE pull per lookup, the rewind and
  failover resets drop exactly the affected shard, the vectorized fast
  path answers bit-identically to the classifying slow path, and the
  steady state is zero-recompile / zero-implicit-transfer under
  RecompileGuard + transfer_guard("disallow").
- **The shared surface** — `PServerEmbedding` and
  `HostOffloadEmbedding` both satisfy `LookupSurface` structurally
  (no isinstance anywhere), and the cache runs unchanged over the
  host-offload backing in static mode.
- **Chaos over real shards** — a FaultPlan kills a primary mid-read:
  the client fails over, the cache notices the new authority via the
  failover counter and re-validates, and every row served afterwards
  is bit-equal to ground truth (no stale-beyond-bound read ever). A
  second plan kills the streaming trainer mid-stream; the reformed
  trainer (same id, fresh client) replays through a lost ACK and the
  final table equals the exact numpy ledger — pushes exactly-once
  through the reform.
"""

import json
import socket

import numpy as np
import pytest

import jax

from paddle_tpu.native.pserver import PServerGroup
from paddle_tpu.native.taskqueue import TaskQueue
from paddle_tpu.parallel.pserver_client import (PServerClient,
                                                PServerEmbedding)
from paddle_tpu.parallel.sparse import (HostOffloadEmbedding,
                                        LookupSurface)
from paddle_tpu.serve.ctr import CtrServer, init_tower
from paddle_tpu.serve.embed_cache import TieredEmbedCache
from paddle_tpu.testing.faults import FaultError, FaultPlan
from paddle_tpu.train.online import StreamingTrainer

pytestmark = pytest.mark.ctr

DIM = 4


class FakeBacking:
    """Injectable-everything backing: values are `row * scale +
    version` so a served vector proves exactly which table version it
    came from; watermarks and failover counters are plain lists the
    test mutates."""

    def __init__(self, vocab=32, n_shards=2, dim=DIM):
        self.vocab, self.dim = vocab, dim
        self._n = n_shards
        self.rows_per = vocab // n_shards
        self.wms = [0] * n_shards
        self.fo = [0] * n_shards
        self.version = 0            # payload generation, not watermark
        self.pull_calls = []

    def value(self, r):
        return np.full(self.dim, 10.0 * r + self.version, np.float32)

    def pull_rows(self, table, ids):
        ids = np.asarray(ids).reshape(-1)
        self.pull_calls.append(sorted(int(i) for i in ids))
        rows = np.stack([self.value(int(r)) for r in ids])
        return rows.astype(np.float32), list(self.wms)

    def owner_of(self, ids):
        ids = np.asarray(ids).reshape(-1)
        owner = ids // self.rows_per
        owner[(ids < 0) | (ids >= self.vocab)] = -1
        return owner.astype(np.int64)

    @property
    def n_shards(self):
        return self._n

    def poll_watermarks(self, table):
        return list(self.wms)

    def shard_failovers(self):
        return list(self.fo)


def mk_cache(**kw):
    fake = FakeBacking()
    kw.setdefault("hot_rows", 16)
    kw.setdefault("host_rows", 24)
    cache = TieredEmbedCache(fake, **kw)
    return fake, cache


# ---------------------------------------------------------------------------
# the cache alone


def test_lookup_contract_and_miss_coalescing():
    """OOB ids -> zero vectors; duplicates classify once; ALL misses
    of a lookup land in ONE pull (one ranged RPC per shard inside the
    backing — never one per row)."""
    fake, cache = mk_cache()
    out = np.asarray(cache.lookup([3, 17, 3, -1, 99, 17]))
    assert out.shape == (6, DIM)
    assert np.array_equal(out[0], fake.value(3))
    assert np.array_equal(out[1], fake.value(17))
    assert np.array_equal(out[2], fake.value(3))
    assert np.array_equal(out[3], np.zeros(DIM))
    assert np.array_equal(out[4], np.zeros(DIM))
    # one pull, unique needed rows only
    assert fake.pull_calls == [[3, 17]]
    c = cache.counters()
    assert c["pulls"] == 1 and c["rows_pulled"] == 2
    assert c["misses"] == 2
    assert cache.reconcile()["ok"]


def test_fast_path_matches_slow_path_bitwise():
    """The vectorized steady-state answer must be indistinguishable
    from the classifying slow path — same values, same zero rows."""
    fake, cache = mk_cache()
    q = np.asarray([5, 2, 9, -1, 5, 40], np.int64)
    slow = np.asarray(cache.lookup(q))   # first call fills (slow path)
    fast = np.asarray(cache.lookup(q))   # all-resident (fast path)
    assert np.array_equal(slow, fast)
    c = cache.counters()
    assert c["hits_device"] > 0
    assert cache.reconcile()["ok"]


def test_staleness_bound_decides_every_serve():
    """A row is served from cache iff its shard's known watermark is
    within `max_staleness` of the row's fill stamp — at the bound it
    still serves, one past the bound it refills."""
    fake, cache = mk_cache(max_staleness=2)
    cache.lookup([1])                      # fill at wm 0
    fake.version = 1                       # backing moves on
    fake.wms[0] = 2                        # staleness 2 == bound
    cache.refresh()
    out = np.asarray(cache.lookup([1]))[0]
    assert out[0] == 10.0                  # still the OLD value: bound holds
    assert cache.counters()["stale_refills"] == 0
    fake.wms[0] = 3                        # staleness 3 > bound
    cache.refresh()
    out = np.asarray(cache.lookup([1]))[0]
    assert out[0] == 11.0                  # refilled: never stale beyond bound
    assert cache.counters()["stale_refills"] == 1
    assert cache.reconcile()["ok"]


def test_push_feed_invalidates_without_polling():
    """`note_watermark` (the on_watermark seam) advances the ledger
    with zero RPCs: a push the cache hears about makes max_staleness=0
    rows refill on next touch."""
    fake, cache = mk_cache(max_staleness=0)
    cache.lookup([2, 3])
    fake.version = 5
    fake.wms[0] = 1
    cache.note_watermark(0, 1)             # what a push ACK would feed
    out = np.asarray(cache.lookup([2]))[0]
    assert out[0] == 25.0                  # row 2, version 5
    assert cache.counters()["stale_refills"] == 1


def test_watermark_rewind_drops_only_that_shard():
    """A rewind (failover to a prefix backup) conservatively drops the
    shard's rows; the other shard keeps serving from cache."""
    fake, cache = mk_cache()
    cache.lookup([1, 20])                  # shard 0 and shard 1 rows
    fake.wms = [4, 4]
    cache.refresh()
    cache.lookup([1, 20])
    pulls_before = cache.counters()["pulls"]
    cache.note_watermark(0, 1)             # REWIND on shard 0
    assert cache.counters()["invalidations_rewind"] == 1
    cache.lookup([1, 20])
    c = cache.counters()
    assert c["pulls"] == pulls_before + 1
    # the pull re-fetched ONLY shard 0's row
    assert fake.pull_calls[-1] == [1]


def test_failover_counter_invalidates_shard():
    """A failover the watermark doesn't reveal (counter diff) still
    invalidates: new authority means re-validate."""
    fake, cache = mk_cache()
    cache.lookup([1, 20])
    fake.fo[1] += 1
    cache.lookup([1, 20])
    c = cache.counters()
    assert c["invalidations_failover"] == 1
    assert fake.pull_calls[-1] == [20]


def test_refresh_stale_moves_refills_off_the_read_path():
    """The maintenance tick re-pulls stale rows in one batch; the
    next lookup is then a pure hit with NO stale refill in its own
    latency."""
    fake, cache = mk_cache(max_staleness=0)
    cache.lookup([1, 2, 3])
    fake.version = 7
    fake.wms[0] = 1
    cache.note_watermark(0, 1)
    n = cache.refresh_stale()
    assert n == 3
    out = np.asarray(cache.lookup([1, 2, 3]))
    assert out[0][0] == 17.0               # fresh values...
    c = cache.counters()
    assert c["stale_refills"] == 0         # ...without a read-path refill
    assert c["refresh_rows"] == 3
    assert cache.reconcile()["ok"]


def test_host_eviction_retires_device_slot():
    """The arena strictly replicates host entries: evicting a row from
    the host tier retires its device slot too, and the evicted row
    misses (not serves stale) on next touch."""
    fake, cache = mk_cache(hot_rows=4, host_rows=4)
    cache.lookup([0, 1, 2, 3])
    cache.lookup([4, 5, 6])                # evicts 0..2 from host
    c = cache.counters()
    assert c["evictions_host"] == 3
    out = np.asarray(cache.lookup([0]))
    assert np.array_equal(out[0], fake.value(0))
    assert cache.reconcile()["ok"]
    assert cache.counters()["entries_device"] <= 4


def test_overflow_lookup_serves_from_host_tier():
    """More live rows than the arena holds: the lookup still answers
    (host-tier assembly) and counts the overflow."""
    fake, cache = mk_cache(hot_rows=4, host_rows=24)
    ids = list(range(12))
    out = np.asarray(cache.lookup(ids))
    for i in ids:
        assert np.array_equal(out[i], fake.value(i))
    assert cache.counters()["overflow_lookups"] == 1


@pytest.mark.analysis
def test_steady_state_zero_recompile_zero_implicit_transfer():
    """After warmup, lookups at seen widths are ZERO fresh compiles
    and move nothing implicitly: slots cross via explicit device_put,
    hot rows never re-cross."""
    from paddle_tpu.analysis.guards import RecompileGuard

    fake, cache = mk_cache()
    q1 = np.asarray([1, 2, 3, 20, 21], np.int64)
    q2 = np.asarray([2, 3, 1, 20, -1], np.int64)    # same width bucket
    cache.lookup(q1)
    cache.lookup(q2)                                 # warmup both paths
    with RecompileGuard(name="embed cache steady state") as g:
        with jax.transfer_guard("disallow"):
            for _ in range(4):
                cache.lookup(q1).block_until_ready()
                cache.lookup(q2).block_until_ready()
    assert g.compiles == 0


# ---------------------------------------------------------------------------
# the shared lookup surface


def test_lookup_surface_is_structural():
    """Both embedding backings satisfy the one `LookupSurface`
    protocol — the drift that motivated it (missing alltoall_* on the
    host-offload side) stays fixed."""
    host = HostOffloadEmbedding(8, DIM)
    assert isinstance(host, LookupSurface)

    class _StubClient:
        num_rows, dim, n_shards = 8, DIM, 1

    ps = PServerEmbedding(_StubClient())
    assert isinstance(ps, LookupSurface)
    # and the cache-backing quintet is present on both
    for obj in (host, ps):
        for name in ("pull_rows", "owner_of", "poll_watermarks",
                     "shard_failovers"):
            assert callable(getattr(obj, name))
        assert isinstance(obj.n_shards, int)


def test_cache_over_host_offload_static_mode():
    """The cache runs unchanged over `HostOffloadEmbedding`
    (watermarks=None -> static mode: entries never stale), answering
    bit-equal to the backing's own lookup."""
    emb = HostOffloadEmbedding(16, DIM)
    table = emb.init(jax.random.key(0))
    cache = TieredEmbedCache(emb, table, hot_rows=8, host_rows=12)
    q = np.asarray([3, 0, 15, -1, 3], np.int64)
    want = np.asarray(emb.lookup(table, q))
    got1 = np.asarray(cache.lookup(q))
    got2 = np.asarray(cache.lookup(q))      # fast path
    assert np.array_equal(want, got1)
    assert np.array_equal(want, got2)
    c = cache.counters()
    assert c["hits_device"] > 0 and c["stale_refills"] == 0
    assert cache.reconcile()["ok"]


# ---------------------------------------------------------------------------
# the CTR serving path


def test_ctr_server_scores_and_rejects():
    fake, cache = mk_cache()
    tower = init_tower(jax.random.key(1), DIM)
    srv = CtrServer(cache, tower, slots=4, max_batch=2)
    scores = srv.score(np.asarray([[1, 2, 3, -1], [5, 6, -1, -1]]))
    assert scores.shape == (2,)
    assert np.all((scores > 0) & (scores < 1))
    # smaller batches pad up into the same fixed bucket
    s1 = srv.score(np.asarray([[1, 2, 3, -1]]))
    assert np.array_equal(s1[0], scores[0])
    with pytest.raises(ValueError):
        srv.score(np.zeros((3, 4), np.int64))       # batch too big
    with pytest.raises(ValueError):
        srv.score_request({"not_ids": 1})
    out = srv.score_request({"ids": [[1, 2, 3]]})
    assert out["batch"] == 1 and len(out["scores"]) == 1
    assert srv.counters()["rejected"] == 1


def test_ctr_http_edge_route():
    """POST /v1/ctr/score answers through the edge front door; GET is
    405, no backend bound is 404."""
    from paddle_tpu.serve.http_edge import HttpEdge

    class _StubRouter:
        draining = False
        results = {}

        def sweep(self):
            return False

        def queue_space(self):
            return 8

        def submit(self, *a, **k):
            raise AssertionError("CTR traffic must not touch submit")

        def counters(self):
            return {}

        def drain(self, reason=""):
            pass

    fake, cache = mk_cache()
    tower = init_tower(jax.random.key(1), DIM)
    ctr = CtrServer(cache, tower, slots=4, max_batch=2)
    edge = HttpEdge(_StubRouter(), ctr=ctr).start()
    try:
        blob = json.dumps({"ids": [[1, 2, 3], [5, 6, 7]]}).encode()
        raw = _exchange(edge.addr,
                        f"POST /v1/ctr/score HTTP/1.1\r\nHost: e\r\n"
                        f"Content-Length: {len(blob)}\r\n\r\n"
                        .encode() + blob)
        assert b" 200 " in raw.split(b"\r\n", 1)[0]
        body = json.loads(raw.split(b"\r\n\r\n", 1)[1])
        assert body["batch"] == 2 and len(body["scores"]) == 2
        raw = _exchange(edge.addr,
                        b"GET /v1/ctr/score HTTP/1.1\r\nHost: e\r\n"
                        b"\r\n")
        assert b" 405 " in raw.split(b"\r\n", 1)[0]
        assert edge.counters()["ctr_requests"] == 1
    finally:
        edge.close()
    edge2 = HttpEdge(_StubRouter()).start()     # no CTR backend bound
    try:
        blob = json.dumps({"ids": [[1]]}).encode()
        raw = _exchange(edge2.addr,
                        f"POST /v1/ctr/score HTTP/1.1\r\nHost: e\r\n"
                        f"Content-Length: {len(blob)}\r\n\r\n"
                        .encode() + blob)
        assert b" 404 " in raw.split(b"\r\n", 1)[0]
    finally:
        edge2.close()


def _exchange(addr, blob, timeout_s=5.0):
    with socket.create_connection(addr, timeout=timeout_s) as s:
        s.sendall(blob)
        out = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                return out
            out += chunk


# ---------------------------------------------------------------------------
# chaos over real shards


VOCAB = 16


def _dyadic_grad_fn(payload, rows, dim):
    """Payload-pure deltas with dyadic values: float sums are exact in
    any order, so the expected table is computable in numpy to the
    bit."""
    del rows
    r = int(payload["row"])
    ids = np.asarray([r], np.int64)
    grads = np.full((1, dim), float(payload["delta"]), np.float32)
    return ids, grads


def _expected_table(init, tasks, lr=1.0):
    out = np.array(init, np.float32, copy=True)
    for t in tasks:
        out[t["row"]] -= np.float32(lr) * np.float32(t["delta"])
    return out


@pytest.mark.faults
@pytest.mark.pserver
def test_shard_failover_never_serves_stale_beyond_bound():
    """Kill the primary mid-read: the client fails over to the backup,
    the cache sees the failover counter move and re-validates, and
    with max_staleness=0 every row served after every acknowledged
    push is bit-equal to ground truth — no stale read ever."""
    with PServerGroup(VOCAB, DIM, n_shards=2, replicated=True) as grp:
        plan = FaultPlan(pserver_kill_get_at=2)
        plan.wrap_pserver_shard(grp.primaries[0])

        push = PServerClient(grp.specs, DIM, trainer_id=0)
        push.register()
        emb = PServerEmbedding(push)
        table = emb.init(jax.random.key(2))
        init = push.get_rows(np.arange(VOCAB))

        read = PServerClient(grp.specs, DIM, trainer_id=1)
        read.register()
        read_emb = PServerEmbedding(read)
        cache = TieredEmbedCache(read_emb, table, hot_rows=8,
                                 host_rows=12, max_staleness=0)
        cache.bind_push_feed(push)    # same thread: reentrant-safe

        tasks = [{"row": i % VOCAB, "delta": 2.0 ** -(i % 5)}
                 for i in range(12)]
        applied = []
        for i, t in enumerate(tasks):
            emb.apply_row_grads(table, np.asarray([t["row"]]),
                                np.full((1, DIM), t["delta"],
                                        np.float32), 1.0)
            applied.append(t)
            # read a window covering both shards; get #2 kills the
            # shard-0 primary mid-loop and the client fails over
            got = np.asarray(cache.lookup([t["row"], 1, VOCAB - 1]))
            want = _expected_table(init, applied)
            assert np.array_equal(got[0], want[t["row"]]), (
                f"stale read at step {i}")
            assert np.array_equal(got[1], want[1])
            assert np.array_equal(got[2], want[VOCAB - 1])
        assert plan.count("psgetkill") == 1
        assert read.shard_failovers()[0] >= 1
        assert cache.counters()["invalidations_failover"] >= 1
        assert cache.reconcile()["ok"]


@pytest.mark.faults
def test_reform_mid_stream_exactly_once_watermarks():
    """Kill the streaming trainer mid-stream AND drop a push ACK: the
    reformed trainer (same id, fresh client) adopts the shard's
    applied epochs at registration, replays the leased-back task, the
    retried push DUPs out, and the final table equals the exact numpy
    ledger — every delta applied exactly once through the reform."""
    with PServerGroup(VOCAB, DIM, n_shards=1, replicated=False) as grp:
        ack_plan = FaultPlan(pserver_lost_ack_at=2)
        ack_plan.wrap_pserver_shard(grp.primaries[0])

        boot = PServerClient(grp.specs, DIM, trainer_id=0)
        boot.register()
        boot_emb = PServerEmbedding(boot)
        table = boot_emb.init(jax.random.key(5))
        init = boot.get_rows(np.arange(VOCAB))

        tasks = [{"row": i % VOCAB, "delta": 2.0 ** -(i % 6),
                  "seed": i, "vocab": VOCAB} for i in range(8)]
        q = TaskQueue(timeout_ms=200, max_retries=4)
        for t in tasks:
            q.add_task(json.dumps(t).encode())

        def mk_trainer():
            client = PServerClient(grp.specs, DIM, trainer_id=7)
            client.register()       # adopts the applied-epoch watermark
            return StreamingTrainer(q, PServerEmbedding(client), table,
                                    lr=1.0, grad_fn=_dyadic_grad_fn)

        t1 = mk_trainer()
        FaultPlan(online_kill_step_at=4).wrap_online_trainer(t1)
        with pytest.raises(FaultError):
            t1.run(len(tasks))
        done_before = t1.stats["tasks_done"]
        assert done_before < len(tasks)

        # REFORM: fresh instance, same trainer id, same queue. The
        # killed step's task leases back to todo after timeout_ms and
        # the reformed stream consumes the remainder.
        t2 = mk_trainer()
        remaining = len(tasks) - done_before
        assert t2.run(remaining) == remaining

        want = _expected_table(init, tasks)
        got = boot.get_rows(np.arange(VOCAB))
        assert np.array_equal(got, want)
        st = grp.primaries[0].stats()
        # the lost-ACK retry DUPed instead of double-applying, and the
        # push watermark equals exactly one apply per task
        assert ack_plan.count("pslostack") == 1
        assert st["duplicates"] >= 1
        assert st["version"] == len(tasks) + 1     # +1: the init load
