"""Native C++ runtime: recordio chunk files + fault-tolerant task queue.

Mirrors the reference's in-process multi-node test strategy (reference:
go/master/service_internal_test.go; pserver/test/test_ParameterServer2.cpp
drives real server objects in one process).
"""

import json
import os
import threading
import time

import pytest

from paddle_tpu.native import (
    MasterClient,
    MasterServer,
    RecordReader,
    RecordWriter,
    TaskQueue,
    TaskStatus,
    count_chunks,
    read_records,
    write_records,
)


# ---- recordio ----

def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.rio")
    recs = [f"record-{i}".encode() for i in range(257)] + [b""]
    write_records(path, recs, records_per_chunk=50)
    assert read_records(path) == recs
    assert count_chunks(path) == 6  # ceil(258/50)


def test_recordio_chunk_range(tmp_path):
    path = str(tmp_path / "data.rio")
    recs = [bytes([i]) * 10 for i in range(100)]
    write_records(path, recs, records_per_chunk=10)
    assert count_chunks(path) == 10
    # chunks [3, 5) hold records 30..49
    assert read_records(path, 3, 5) == recs[30:50]
    assert read_records(path, 9) == recs[90:]


def test_recordio_corruption_detected(tmp_path):
    path = str(tmp_path / "data.rio")
    write_records(path, [b"x" * 100], records_per_chunk=10)
    with open(path, "r+b") as f:
        f.seek(30)
        f.write(b"\xff")
    with pytest.raises(OSError):
        read_records(path)


# ---- task queue core ----

def _make_queue(n_tasks=6, **kw):
    q = TaskQueue(**kw)
    for i in range(n_tasks):
        q.add_task(f"task-{i}".encode())
    q.start()
    return q


def test_taskqueue_basic_flow():
    q = _make_queue(3)
    assert q.pass_num == 0
    seen = []
    while True:
        status, tid, payload = q.get_task()
        if status != TaskStatus.OK:
            break
        seen.append(payload)
        q.finish_task(tid)
    assert status == TaskStatus.PASS_END
    assert sorted(seen) == [b"task-0", b"task-1", b"task-2"]
    assert q.counts() == {"todo": 0, "pending": 0, "done": 3, "discarded": 0}
    # next pass recycles
    assert q.next_pass() == 1
    assert q.counts()["todo"] == 3


def test_taskqueue_not_started():
    q = TaskQueue()
    q.add_task(b"t")
    status, _, _ = q.get_task()
    assert status == TaskStatus.NOT_STARTED


def test_taskqueue_lease_timeout_requeues():
    q = _make_queue(1, timeout_ms=80, max_retries=3)
    status, tid, _ = q.get_task()
    assert status == TaskStatus.OK
    # lease expires -> task back on todo with a failure count
    time.sleep(0.15)
    status2, tid2, payload2 = q.get_task()
    assert status2 == TaskStatus.OK
    assert payload2 == b"task-0"
    q.finish_task(tid2)
    assert q.counts()["done"] == 1


def test_taskqueue_retry_then_discard():
    q = _make_queue(1, max_retries=2)
    for _ in range(3):  # 3 failures > max_retries=2
        status, tid, _ = q.get_task()
        assert status == TaskStatus.OK
        q.fail_task(tid)
    status, _, _ = q.get_task()
    assert status == TaskStatus.PASS_END
    assert q.counts()["discarded"] == 1


def test_taskqueue_pending_wait():
    q = _make_queue(1, timeout_ms=60000)
    st, tid, _ = q.get_task()
    assert st == TaskStatus.OK
    st2, _, _ = q.get_task()
    assert st2 == TaskStatus.PENDING_WAIT
    q.finish_task(tid)
    st3, _, _ = q.get_task()
    assert st3 == TaskStatus.PASS_END


def test_taskqueue_next_pass_requires_drain():
    q = _make_queue(2)
    q.get_task()
    with pytest.raises(RuntimeError):
        q.next_pass()


def test_taskqueue_snapshot_recover(tmp_path):
    snap = str(tmp_path / "master.snap")
    q = _make_queue(4)
    st, tid, _ = q.get_task()
    q.finish_task(tid)
    st, tid2, _ = q.get_task()  # leave one leased
    q.snapshot(snap)

    # a fresh master recovers: leased task returns to todo (re-lease),
    # finished work is preserved
    q2 = TaskQueue()
    q2.restore(snap)
    q2.start()
    c = q2.counts()
    assert c["done"] == 1
    assert c["todo"] == 3  # 2 never-leased + 1 recovered lease
    got = []
    while True:
        status, tid, payload = q2.get_task()
        if status != TaskStatus.OK:
            break
        got.append(payload)
        q2.finish_task(tid)
    assert len(got) == 3
    assert q2.counts()["done"] == 4


def test_taskqueue_payload_cap():
    q = TaskQueue()
    with pytest.raises(ValueError, match="cap"):
        q.add_task(b"x" * (2 << 20))


def test_stale_finish_is_noop():
    # worker outlives its lease; the requeued task's finish must not raise
    q = _make_queue(1, timeout_ms=60, max_retries=3)
    st, tid, _ = q.get_task()
    assert st == TaskStatus.OK
    time.sleep(0.12)  # lease expires...
    assert q.counts()["todo"] == 1  # ...and timeout processing requeues it
    q.finish_task(tid)  # stale finish: tolerated no-op
    st2, tid2, _ = q.get_task()
    assert st2 == TaskStatus.OK
    q.finish_task(tid2)
    with pytest.raises(KeyError):
        q.finish_task(99999)  # never-issued ids still rejected


def test_superseded_lease_cannot_act():
    # A's lease times out, B re-leases the SAME task; A's late finish and
    # fail must both be stale no-ops against B's live lease.
    q = _make_queue(1, timeout_ms=60, max_retries=3)
    _, handle_a, _ = q.get_task()
    time.sleep(0.12)
    assert q.counts()["todo"] == 1  # timeout processed, requeued
    st, handle_b, _ = q.get_task()
    assert st == TaskStatus.OK
    assert handle_a != handle_b  # distinct lease epochs
    q.finish_task(handle_a)  # stale: must NOT complete B's lease
    assert q.counts()["pending"] == 1
    q.fail_task(handle_a)    # stale: must NOT revoke B's lease
    assert q.counts()["pending"] == 1
    q.finish_task(handle_b)  # the live lease completes normally
    assert q.counts()["done"] == 1


def test_late_finish_before_timeout_processing_counts():
    # lease expired but no queue operation has run timeout processing yet:
    # the late finish is accepted (work did complete; no requeue needed)
    q = _make_queue(1, timeout_ms=60, max_retries=3)
    _, tid, _ = q.get_task()
    time.sleep(0.12)
    q.finish_task(tid)
    assert q.counts() == {"todo": 0, "pending": 0, "done": 1,
                          "discarded": 0}


def test_save_model_election():
    q = _make_queue(1)
    assert q.request_save_model(trainer_id=0, ttl_ms=60000)
    assert not q.request_save_model(trainer_id=1, ttl_ms=60000)
    assert q.request_save_model(trainer_id=0, ttl_ms=60000)  # holder renews
    q2 = _make_queue(1)
    assert q2.request_save_model(trainer_id=7, ttl_ms=60)
    time.sleep(0.12)
    assert q2.request_save_model(trainer_id=1, ttl_ms=60)  # expired grant


# ---- TCP service ----

def test_master_server_client_roundtrip(tmp_path):
    q = TaskQueue(timeout_ms=60000, max_retries=1)
    with MasterServer(q) as srv:
        cli = MasterClient(port=srv.port)
        for i in range(3):
            cli.add_task(f"net-{i}".encode())
        cli.start()
        assert cli.pass_num == 0
        got = []
        while True:
            status, tid, payload = cli.get_task()
            if status != TaskStatus.OK:
                break
            got.append(payload)
            cli.finish_task(tid)
        assert status == TaskStatus.PASS_END
        assert sorted(got) == [b"net-0", b"net-1", b"net-2"]
        assert cli.counts()["done"] == 3
        assert cli.next_pass() == 1
        assert cli.request_save_model(0)
        assert not cli.request_save_model(1)
        cli.close()


def test_master_multiple_workers_share_tasks():
    q = TaskQueue()
    with MasterServer(q) as srv:
        setup = MasterClient(port=srv.port)
        for i in range(40):
            setup.add_task(f"w-{i}".encode())
        setup.start()

        results, lock = [], threading.Lock()

        def worker():
            cli = MasterClient(port=srv.port)
            while True:
                status, tid, payload = cli.get_task()
                if status == TaskStatus.PASS_END:
                    break
                if status != TaskStatus.OK:
                    time.sleep(0.01)
                    continue
                with lock:
                    results.append(payload)
                cli.finish_task(tid)
            cli.close()

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == sorted(f"w-{i}".encode() for i in range(40))
        assert len(set(results)) == 40  # exactly-once on the happy path
        setup.close()


def test_server_stop_with_open_client_connection():
    """stop() must not deadlock while a client connection is parked."""
    q = TaskQueue()
    srv = MasterServer(q)
    cli = MasterClient(port=srv.port)
    cli.add_task(b"t")

    done = threading.Event()

    def stopper():
        srv.stop()
        done.set()

    t = threading.Thread(target=stopper)
    t.start()
    t.join(timeout=10)
    assert done.is_set(), "MasterServer.stop() deadlocked on open client"
    cli.close()


class TestMasterClientClose:
    """close() lifecycle hardening (ISSUE 2 satellite): idempotent
    close, closed-state RPC refusal, and socket release on every
    reconnect-failure path — a leaked fd per dead master would bleed a
    long-lived trainer dry."""

    def test_close_is_idempotent_and_releases_socket(self):
        q = TaskQueue()
        with MasterServer(q) as srv:
            cli = MasterClient(port=srv.port)
            sock = cli._sock
            assert sock is not None
            cli.close()
            assert cli._sock is None
            assert sock.fileno() == -1          # really released
            cli.close()                         # second close: no-op
            cli.close()
            assert cli._sock is None

    def test_closed_client_refuses_rpcs(self):
        """A closed client must NOT silently reconnect (that path is
        how sockets escaped the drop bookkeeping) — it fails loudly."""
        q = TaskQueue()
        with MasterServer(q) as srv:
            cli = MasterClient(port=srv.port)
            cli.close()
            with pytest.raises(RuntimeError, match="closed"):
                cli.counts()
            with pytest.raises(RuntimeError, match="closed"):
                cli.get_task()

    def test_context_manager_closes(self):
        q = TaskQueue()
        with MasterServer(q) as srv:
            with MasterClient(port=srv.port) as cli:
                cli.add_task(b"t")
                sock = cli._sock
            assert cli._sock is None and sock.fileno() == -1
            with pytest.raises(RuntimeError, match="closed"):
                cli.counts()

    def test_reconnect_failure_releases_socket(self):
        """Master death mid-conversation: the exhausted-retries path
        must leave NO socket behind (and close() afterwards is safe)."""
        q = TaskQueue()
        srv = MasterServer(q)
        cli = MasterClient(port=srv.port, retries=1, timeout=0.5,
                           backoff_base=0.01, backoff_max=0.02)
        cli.add_task(b"t")
        srv.stop()
        with pytest.raises(ConnectionError):
            cli.counts()
        assert cli._sock is None                # released, not leaked
        cli.close()                             # safe after failure
        cli.close()

    def test_eager_connect_failure_leaves_no_socket(self):
        """Constructor against a dead port: bounded ConnectionError,
        and the half-built client holds no socket."""
        import socket as _socket

        # grab a port with no listener
        probe = _socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        with pytest.raises(ConnectionError):
            MasterClient(port=dead_port, retries=1, timeout=0.2,
                         backoff_base=0.01, backoff_max=0.02)


def test_malformed_frame_rejected():
    import socket
    import struct as st

    q = TaskQueue()
    with MasterServer(q) as srv:
        s = socket.create_connection(("127.0.0.1", srv.port))
        # OP_FINISH with no id bytes: must get an error status, not crash
        s.sendall(st.pack("<I", 1) + bytes([2]))
        hdr = s.recv(4)
        (n,) = st.unpack("<I", hdr)
        resp = s.recv(n)
        assert resp[0] == 254
        s.close()
        # master still functional afterwards
        cli = MasterClient(port=srv.port)
        cli.add_task(b"ok")
        cli.start()
        status, tid, payload = cli.get_task()
        assert status == TaskStatus.OK and payload == b"ok"
        cli.finish_task(tid)
        cli.close()


# ---- end-to-end: recordio dataset partitioned into tasks, streamed ----

def test_record_streaming_end_to_end(tmp_path):
    path = str(tmp_path / "train.rio")
    recs = [json.dumps({"i": i}).encode() for i in range(60)]
    write_records(path, recs, records_per_chunk=10)

    q = TaskQueue()
    assert q.add_file_chunks(path, chunks_per_task=2) == 3
    q.start()
    with MasterServer(q) as srv:
        cli = MasterClient(port=srv.port)
        reader = cli.record_reader()
        got = sorted(json.loads(r)["i"] for r in reader())
        assert got == list(range(60))
        cli.close()


def test_ha_master_restart_recovers(tmp_path):
    """The master-HA contract (reference: go/master/etcd_client.go stores
    snapshots in etcd so an elected replacement resumes the queue): a
    master writing snapshots to a shared directory dies; a NEW master
    pointed at the same directory recovers the queue — done tasks stay
    done, leased tasks return to todo (with a bumped lease epoch), and
    the save-model election still works."""
    from paddle_tpu.native.taskqueue import HAMaster, TaskStatus

    snap_dir = str(tmp_path / "shared-fs")

    # master #1: three tasks, one finished, one still leased at "death"
    m1 = HAMaster(snap_dir, interval_s=0)  # snapshot manually
    for i in range(3):
        m1.queue.add_task(f"task-{i}".encode())
    m1.queue.start()
    st, tid, _ = m1.queue.get_task()
    assert st == TaskStatus.OK
    m1.queue.finish_task(tid)
    st2, tid2, _ = m1.queue.get_task()   # leased, never finished
    assert st2 == TaskStatus.OK
    m1.checkpoint()
    m1.stop(final_snapshot=False)  # simulate crash AFTER the snapshot

    # master #2 on another "host", same shared dir
    m2 = HAMaster(snap_dir, interval_s=0)
    assert m2.recovered_from is not None
    c = m2.queue.counts()
    assert c["done"] == 1
    # the leased-but-unfinished task is back in todo
    assert c["todo"] == 2 and c["pending"] == 0
    # pre-crash lease handle is stale: the recovered task's epoch was
    # bumped, so finishing through the old handle is a tolerated NO-OP
    # (taskqueue.cc tq_finish_task: superseded lease → rc 1)
    m2.queue.finish_task(tid2)
    assert m2.queue.counts()["done"] == 1
    assert m2.queue.counts()["todo"] == 2
    # both remaining tasks still servable to completion
    m2.queue.start()
    for _ in range(2):
        st, tid, _ = m2.queue.get_task()
        assert st == TaskStatus.OK
        m2.queue.finish_task(tid)
    assert m2.queue.counts()["done"] == 3
    assert m2.queue.request_save_model(trainer_id=0)
    m2.stop()


def test_ha_master_snapshot_rotation(tmp_path):
    from paddle_tpu.native.taskqueue import HAMaster

    snap_dir = str(tmp_path / "snaps")
    m = HAMaster(snap_dir, interval_s=0, keep=2)
    m.queue.add_task(b"t")
    m.queue.start()
    paths = [m.checkpoint() for _ in range(4)]
    kept = sorted(os.listdir(snap_dir))
    assert len(kept) == 2
    assert os.path.basename(paths[-1]) in kept
    # a fresh master picks the NEWEST snapshot and continues numbering
    m.stop(final_snapshot=False)
    m2 = HAMaster(snap_dir, interval_s=0, keep=2)
    assert m2.recovered_from.endswith(os.path.basename(paths[-1]))
    m2.stop(final_snapshot=False)


def test_tcp_elastic_task_reassignment(tmp_path):
    """The Go-runtime elasticity contract over the REAL TCP service
    (reference: go/master/service.go:341 checkTimeoutFunc — a dead
    trainer's leased task returns to todo and another trainer completes
    the pass): worker A takes a task and dies (connection dropped, no
    finish); after the lease expires, worker B receives the same task
    and finishes the pass."""
    import time as _time

    from paddle_tpu.native.taskqueue import (MasterClient, MasterServer,
                                             TaskQueue, TaskStatus)

    q = TaskQueue(timeout_ms=300, max_retries=3)
    payloads = {b"alpha", b"beta"}
    for p in sorted(payloads):
        q.add_task(p)
    q.start()
    with MasterServer(q) as srv:
        a = MasterClient(port=srv.port)
        st, tid_a, payload_a = a.get_task()
        assert st == TaskStatus.OK
        a.close()  # worker A dies holding the lease

        # worker B alone must complete BOTH tasks — including A's, which
        # can only come back via lease-timeout requeue (no timing
        # assumptions on when exactly the lease expires)
        b = MasterClient(port=srv.port)
        finished = []
        deadline = _time.time() + 10.0
        while len(finished) < 2 and _time.time() < deadline:
            st, tid, payload = b.get_task()
            if st == TaskStatus.OK:
                finished.append(payload)
                b.finish_task(tid)
            else:
                _time.sleep(0.05)
        assert sorted(finished) == sorted(payloads), finished
        assert q.counts()["done"] == 2
        # pass drains even though worker A never reported back
        assert q.next_pass() == 1
        b.close()


class TestNativeLoader:
    """C++ threaded prefetch loader (native/src/loader.cc — the async
    DoubleBuffer DataProvider analog)."""

    def _write_files(self, tmp_path, n_files=3, per_file=40):
        from paddle_tpu import native

        paths, want = [], []
        for i in range(n_files):
            p = tmp_path / f"part-{i}.rio"
            recs = [f"f{i}r{j}".encode() for j in range(per_file)]
            native.write_records(str(p), recs, records_per_chunk=7)
            paths.append(str(p))
            want.extend(recs)
        return paths, want

    def test_single_thread_preserves_order(self, tmp_path):
        from paddle_tpu import native

        paths, want = self._write_files(tmp_path)
        got = list(native.native_reader(paths, n_threads=1)())
        assert got == want

    def test_multi_thread_full_coverage(self, tmp_path):
        from paddle_tpu import native

        paths, want = self._write_files(tmp_path)
        got = list(native.native_reader(paths, n_threads=3, capacity=8)())
        assert sorted(got) == sorted(want)
        assert len(got) == len(want)

    def test_reader_is_reusable(self, tmp_path):
        from paddle_tpu import native

        paths, want = self._write_files(tmp_path, n_files=1, per_file=5)
        reader = native.native_reader(paths, n_threads=1)
        assert list(reader()) == want
        assert list(reader()) == want  # combinator contract: re-iterable

    def test_early_close_does_not_hang(self, tmp_path):
        from paddle_tpu import native

        paths, _ = self._write_files(tmp_path, n_files=2, per_file=500)
        it = native.native_reader(paths, n_threads=2, capacity=4)()
        assert next(it) is not None
        it.close()  # generator close -> ldr_close joins blocked producers

    def test_missing_file_raises(self, tmp_path):
        from paddle_tpu import native

        reader = native.native_reader([str(tmp_path / "nope.rio")])
        with pytest.raises(OSError):
            list(reader())

    def test_empty_path_list_yields_nothing(self):
        from paddle_tpu import native

        assert list(native.native_reader([])()) == []
