"""Book-chapter model tests: word2vec, recommender system, SRL db-LSTM
(reference: python/paddle/v2/fluid/tests/book/test_word2vec.py,
test_recommender_system.py, test_label_semantic_roles.py — each trains
its network until the cost drops; these do the same on the synthetic
dataset-zoo readers)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.data import batch as B
from paddle_tpu.data import dataset_zoo as zoo
from paddle_tpu.models import recommender, srl, word2vec


def _train(params, batches, loss_fn, *, lr=5e-3, epochs=6):
    opt = optim.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch, i):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, i))(params)
        params, opt_state = opt.update(grads, opt_state, params, i)
        return params, opt_state, loss

    first = last = None
    i = 0
    for _ in range(epochs):
        for batch in batches:
            params, opt_state, loss = step(params, opt_state, batch,
                                           jnp.asarray(i))
            if first is None:
                first = float(loss)
            last = float(loss)
            i += 1
    return params, first, last


class TestWord2Vec:
    def _batches(self, vocab, n_ctx, batch=32):
        rows = list(zoo.imikolov(zoo.imikolov_build_dict(vocab),
                                 n=n_ctx + 1, mode="train",
                                 sentences=48)())
        out = []
        for s in range(0, len(rows) - batch + 1, batch):
            arr = np.asarray(rows[s:s + batch], np.int32)
            out.append({"ctx": jnp.asarray(arr[:, :n_ctx]),
                        "next": jnp.asarray(arr[:, n_ctx])})
        return out

    def test_softmax_converges(self):
        vocab, n_ctx = 200, 4
        params = word2vec.init_params(jax.random.key(0), vocab,
                                      embed_dim=16, hidden=32,
                                      context=n_ctx)
        batches = self._batches(vocab, n_ctx)
        params, first, last = _train(
            params, batches,
            lambda p, b, i: word2vec.loss(p, b["ctx"], b["next"]),
            lr=3e-2, epochs=10)
        # markov structure in the zoo reader makes the next word
        # predictable: cost must drop well below the uniform log(V) start
        assert last < first * 0.7, (first, last)
        ids = word2vec.nearest(params, jnp.asarray([3, 7]), k=3)
        assert ids.shape == (2, 3)
        assert int(ids[0, 0]) == 3 and int(ids[1, 0]) == 7  # self at rank 0

    def test_nce_converges(self):
        vocab, n_ctx = 200, 4
        params = word2vec.init_params(jax.random.key(1), vocab,
                                      embed_dim=16, hidden=32,
                                      context=n_ctx)
        batches = self._batches(vocab, n_ctx)

        def nce(p, b, i):
            # fresh negatives every step — fold the TRACED step index into
            # the key (a Python-side counter would bake one constant key
            # at trace time)
            key = jax.random.fold_in(jax.random.key(2), i)
            return word2vec.loss_nce(p, b["ctx"], b["next"], key,
                                     num_noise=8)

        params, first, last = _train(params, batches, nce, lr=3e-2,
                                     epochs=10)
        assert last < first * 0.7, (first, last)


class TestRecommender:
    CFG = recommender.RecommenderConfig(
        n_users=zoo.movielens_max_user_id() + 1,
        n_movies=zoo.movielens_max_movie_id() + 1,
        n_categories=zoo.movielens_movie_categories(),
        title_vocab=64, id_dim=8, side_dim=4, feat_dim=16,
        title_filter=8)

    def _batches(self, batch=32):
        rows = list(zoo.movielens(n=512)())
        rng = np.random.RandomState(0)
        out = []
        for s in range(0, len(rows) - batch + 1, batch):
            chunk = rows[s:s + batch]
            u, g, a, j, m, c, score = map(np.asarray, zip(*chunk))
            # synthetic title: 4 tokens keyed off the movie id
            titles = (m[:, None] * 3 + np.arange(4)[None, :]) % 64
            out.append({
                "user_id": jnp.asarray(u, jnp.int32),
                "gender_id": jnp.asarray(g, jnp.int32),
                "age_id": jnp.asarray(a, jnp.int32),
                "job_id": jnp.asarray(j, jnp.int32),
                "movie_id": jnp.asarray(m, jnp.int32),
                "cat_ids": jnp.asarray(c[:, None], jnp.int32),
                "cat_lengths": jnp.ones((batch,), jnp.int32),
                "title_ids": jnp.asarray(titles, jnp.int32),
                "title_lengths": jnp.full((batch,), 4, jnp.int32),
                "rating": jnp.asarray(score, jnp.float32),
            })
        return out

    def test_converges(self):
        params = recommender.init_params(jax.random.key(0), self.CFG)
        batches = self._batches()
        params, first, last = _train(
            params, batches,
            lambda p, b, i: recommender.loss(p, b, b["rating"]), epochs=8)
        assert last < first * 0.7, (first, last)
        pred = recommender.predict_rating(params, batches[0])
        assert pred.shape == (32,)
        assert float(jnp.max(jnp.abs(pred))) <= 5.0 + 1e-5


class TestSRL:
    def _batches(self, max_len=20, batch=16):
        rows = list(zoo.conll05(n=128)())
        out, buf = [], []
        for words, verb, mark, labels in rows:
            buf.append((words[:max_len], verb, mark[:max_len],
                        labels[:max_len]))
            if len(buf) == batch:
                w, lens = B.pad_sequences([b[0] for b in buf], max_len)
                mk, _ = B.pad_sequences([b[2] for b in buf], max_len)
                lb, _ = B.pad_sequences([b[3] for b in buf], max_len)
                # the 6 word-window columns: shifted copies of the word
                # row (the reference's ctx_n2..ctx_p2 preprocessing)
                win = np.stack([np.roll(w, s, axis=1)
                                for s in (0, 2, 1, 0, -1, -2)], axis=-1)
                verbs = np.asarray([b[1] for b in buf], np.int32)
                pred_col = np.broadcast_to(verbs[:, None],
                                           (batch, max_len)).copy()
                out.append({
                    "win": jnp.asarray(win, jnp.int32),
                    "pred": jnp.asarray(pred_col),
                    "mark": jnp.asarray(mk, jnp.int32),
                    "labels": jnp.asarray(lb, jnp.int32),
                    "lens": jnp.asarray(lens, jnp.int32),
                })
        return out

    def test_converges_and_decodes(self):
        params = srl.init_params(jax.random.key(0), word_vocab=500,
                                 pred_vocab=50, num_labels=9,
                                 word_dim=8, mark_dim=4, hidden=16,
                                 depth=4)
        batches = self._batches()
        params, first, last = _train(
            params, batches,
            lambda p, b, i: srl.loss(p, b["win"], b["pred"], b["mark"],
                                     b["labels"], b["lens"]),
            lr=2e-2, epochs=20)
        assert last < first * 0.6, (first, last)
        b0 = batches[0]
        tags = srl.decode(params, b0["win"], b0["pred"], b0["mark"],
                          b0["lens"])
        assert tags.shape == b0["labels"].shape
        assert int(jnp.min(tags)) >= 0 and int(jnp.max(tags)) < 9
        # after training, viterbi tags should beat chance agreement with
        # the synthetic labeling rule on valid positions
        mask = np.arange(tags.shape[1])[None, :] < np.asarray(b0["lens"])[:, None]
        agree = float((np.asarray(tags) == np.asarray(b0["labels"]))[mask].mean())
        assert agree > 0.5, agree

    def test_depth8_default_shapes(self):
        params = srl.init_params(jax.random.key(1), word_vocab=50,
                                 pred_vocab=10, num_labels=5,
                                 word_dim=4, mark_dim=2, hidden=8)
        assert "mix7" in params and "lstm7" in params  # depth 8 default
        w = jnp.zeros((2, 6, 6), jnp.int32)
        e = srl.emissions(params, w, jnp.zeros((2, 6), jnp.int32),
                          jnp.zeros((2, 6), jnp.int32),
                          jnp.asarray([6, 4]))
        assert e.shape == (2, 6, 5)
