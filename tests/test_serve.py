"""Compiled inference artifacts + C ABI (reference: paddle/capi,
merge_model single-file deployment)."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import models, nn
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.serve import (export_compiled_model, load_compiled_model)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _export_mlp(path, batch=4, din=16, dout=3):
    model = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(dout)])
    params, mstate = model.init(jax.random.key(0), ShapeSpec((batch, din)))

    def forward(x):
        out, _ = model.apply(params, mstate, x, training=False)
        return out

    x = np.random.RandomState(0).rand(batch, din).astype(np.float32)
    export_compiled_model(forward, [x], path, name="mlp")
    return forward, x


def test_artifact_roundtrip(tmp_path):
    path = str(tmp_path / "mlp.ptc")
    forward, x = _export_mlp(path)
    m = load_compiled_model(path)
    assert m.meta["name"] == "mlp"
    assert m.input_signature[0]["shape"] == [4, 16]
    got = m.predict(x)
    want = forward(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


class TestDecoderArtifact:
    """The decode LOOP (prefill + scan) as a serving artifact — the
    reference's SequenceGenerator serving surface (api/PaddleAPI.h:1025)
    compiled to one weights-folded program."""

    def _cfg(self):
        from paddle_tpu.models import transformer as T
        return T.TransformerConfig(vocab=32, dim=16, n_layers=2,
                                   n_heads=2, mlp_ratio=2,
                                   attn_impl="dense")

    def test_greedy_roundtrip_matches_generate(self, tmp_path):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import export_decoder
        cfg = self._cfg()
        params = T.init_params(jax.random.key(0), cfg)
        path = str(tmp_path / "dec.ptc")
        export_decoder(params, cfg, path, batch=2, prompt_len=5, steps=4)
        m = load_compiled_model(path)
        assert m.meta["kind"] == "decoder"
        prompt = np.random.RandomState(0).randint(
            1, 32, (2, 5)).astype(np.int32)
        got = np.asarray(m.predict(prompt))
        want = np.asarray(T.generate(params, cfg, jnp.asarray(prompt),
                                     steps=4))
        np.testing.assert_array_equal(got, want)

    def test_varlen_sampled_roundtrip(self, tmp_path):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import export_decoder
        cfg = self._cfg()
        params = T.init_params(jax.random.key(1), cfg)
        path = str(tmp_path / "dec.ptc")
        export_decoder(params, cfg, path, batch=2, prompt_len=6, steps=3,
                       variable_lengths=True, temperature=0.8, top_k=8)
        m = load_compiled_model(path)
        assert m.meta["sampled"] and m.meta["variable_lengths"]
        prompt = np.zeros((2, 6), np.int32)
        prompt[0] = np.random.RandomState(1).randint(1, 32, 6)
        prompt[1, :4] = np.random.RandomState(2).randint(1, 32, 4)
        lens = np.asarray([6, 4], np.int32)
        seed = np.asarray(
            jax.random.key_data(jax.random.key(7)), np.uint32)
        got = np.asarray(m.predict(prompt, lens, seed))
        want = np.asarray(T.sample(
            params, cfg, jnp.asarray(prompt), steps=3,
            rng=jax.random.key(7), temperature=0.8, top_k=8,
            prompt_lens=jnp.asarray(lens)))
        np.testing.assert_array_equal(got, want)

    def test_decoder_artifact_needs_no_model_code(self, tmp_path):
        """The decode loop must run from the artifact alone in a fresh
        process that never imports the transformer."""
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import export_decoder
        cfg = self._cfg()
        params = T.init_params(jax.random.key(2), cfg)
        path = str(tmp_path / "dec.ptc")
        export_decoder(params, cfg, path, batch=1, prompt_len=4, steps=3)
        code = f"""
import sys
sys.path.insert(0, {REPO!r})
import scripts.cpu_guard  # the ONE cpu-pin implementation
import numpy as np
from paddle_tpu.serve.artifact import load_compiled_model
m = load_compiled_model({path!r})
out = m.predict(np.ones((1, 4), np.int32))
assert np.asarray(out).shape == (1, 7), out.shape
print("ok")
"""
        r = subprocess.run([sys.executable, "-c", code],
                           env={**os.environ, "JAX_PLATFORMS": "cpu"},
                           capture_output=True, text=True, timeout=240)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "ok" in r.stdout


class TestInt8Quant:
    """Weight-only int8 serving: per-channel quantization accuracy,
    in-jit dequant decode parity, and the shrunk decoder artifact."""

    def test_roundtrip_error_small(self):
        from paddle_tpu.serve import quant
        w = np.random.RandomState(0).randn(64, 32).astype(np.float32)
        # per-channel scales must survive wildly different column norms
        w[:, 0] *= 100.0
        qt = quant.quantize_tensor(jnp.asarray(w))
        assert qt.q.dtype == jnp.int8 and qt.scale.shape == (32,)
        d = np.asarray(quant.dequantize_tensor(qt))
        rel = np.linalg.norm(d - w) / np.linalg.norm(w)
        assert rel < 0.01, rel
        # int8 range actually used (not crushed to a few levels)
        assert int(jnp.max(jnp.abs(qt.q))) == 127

    def test_vectors_ints_and_unmatched_pass_through(self):
        from paddle_tpu.serve import quant
        tree = {"proj": {"kernel": jnp.ones((4, 4)),
                         "bias": jnp.ones((4,))},
                "embed": {"table": jnp.ones((8, 4))},
                "ids": jnp.arange(6, dtype=jnp.int32)}
        qt = quant.quantize_params(tree)  # DEFAULT_MATCH
        assert isinstance(qt["proj"]["kernel"], quant.QuantizedTensor)
        assert qt["proj"]["bias"].shape == (4,)       # vector: untouched
        assert not isinstance(qt["embed"]["table"],
                              quant.QuantizedTensor)  # excluded by match
        assert jnp.issubdtype(qt["ids"].dtype, jnp.integer)
        back = quant.dequantize_params(qt)
        np.testing.assert_allclose(np.asarray(back["proj"]["kernel"]),
                                   np.ones((4, 4)), atol=0.02)

    def test_per_expert_scales_on_stacked_kernels(self):
        from paddle_tpu.serve import quant
        # one expert 100x larger must not crush the others' resolution
        w = np.random.RandomState(0).randn(4, 16, 8).astype(np.float32)
        w[3] *= 100.0
        qt = quant.quantize_tensor(jnp.asarray(w))
        assert qt.scale.shape == (4, 8)  # per expert, per out channel
        d = np.asarray(quant.dequantize_tensor(qt))
        for e in range(4):
            rel = np.linalg.norm(d[e] - w[e]) / np.linalg.norm(w[e])
            assert rel < 0.01, (e, rel)

    def test_quantized_decode_close_to_full_precision(self):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import quant
        cfg = T.TransformerConfig(vocab=32, dim=32, n_layers=2,
                                  n_heads=4, mlp_ratio=2,
                                  attn_impl="dense")
        params = T.init_params(jax.random.key(0), cfg)
        qp = quant.quantize_params(params)  # DEFAULT_MATCH
        assert quant.quantization_error(params, qp) < 0.02
        toks = jnp.asarray(
            np.random.RandomState(0).randint(1, 32, (2, 8)), jnp.int32)
        full = np.asarray(T.apply(params, cfg, toks))
        q = np.asarray(T.apply(quant.dequantize_params(qp), cfg, toks))
        # logits track closely; argmax agrees on a large majority
        agree = (full.argmax(-1) == q.argmax(-1)).mean()
        assert agree >= 0.8, agree

    def test_int8_decoder_artifact_shrinks_and_runs(self, tmp_path):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import export_decoder
        cfg = T.TransformerConfig(vocab=64, dim=64, n_layers=2,
                                  n_heads=4, mlp_ratio=4,
                                  attn_impl="dense")
        params = T.init_params(jax.random.key(1), cfg)
        p32 = str(tmp_path / "dec32.ptc")
        p8 = str(tmp_path / "dec8.ptc")
        export_decoder(params, cfg, p32, batch=1, prompt_len=4, steps=3)
        export_decoder(params, cfg, p8, batch=1, prompt_len=4, steps=3,
                       int8_weights=True)
        # matmul weights dominate this model: int8 must cut the
        # artifact to well under half the f32 size
        assert os.path.getsize(p8) < 0.5 * os.path.getsize(p32), (
            os.path.getsize(p8), os.path.getsize(p32))
        m = load_compiled_model(p8)
        assert m.meta["int8_weights"] is True
        out = np.asarray(m.predict(np.ones((1, 4), np.int32)))
        assert out.shape == (1, 7)
        assert (out >= 0).all() and (out < 64).all()


def test_artifact_input_validation(tmp_path):
    path = str(tmp_path / "mlp.ptc")
    _export_mlp(path)
    m = load_compiled_model(path)
    with pytest.raises(ValueError, match="takes 1 inputs"):
        m.predict(np.zeros((4, 16), np.float32), np.zeros(3))
    with pytest.raises(ValueError, match="input shape"):
        m.predict(np.zeros((2, 16), np.float32))


def test_artifact_needs_no_model_code(tmp_path):
    """Loading runs in a fresh process that never builds the model."""
    path = str(tmp_path / "mlp.ptc")
    _, x = _export_mlp(path)
    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from paddle_tpu.serve import load_compiled_model
m = load_compiled_model({path!r})
x = np.random.RandomState(0).rand(4, 16).astype(np.float32)
out = np.asarray(m.predict(x))
assert out.shape == (4, 3), out.shape
assert np.isfinite(out).all()
print("STANDALONE_OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=300)
    assert "STANDALONE_OK" in r.stdout, r.stderr[-2000:]


def test_capi_end_to_end(tmp_path):
    """Real C program drives the embedded-interpreter inference ABI."""
    from paddle_tpu.native.build import ensure_capi_built

    capi = ensure_capi_built()
    artifact = str(tmp_path / "mlp.ptc")
    forward, x = _export_mlp(artifact)
    want = np.asarray(forward(np.full((4, 16), 0.5, np.float32)))

    driver_src = os.path.join(REPO, "tests", "capi_driver.c")
    driver = str(tmp_path / "capi_driver")
    subprocess.run(["gcc", "-O1", "-o", driver, driver_src, "-ldl", "-lm"],
                   check=True, capture_output=True, text=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PADDLE_TPU_PLATFORM="cpu",
               PYTHONPATH=REPO)
    r = subprocess.run(
        [driver, capi, REPO, artifact, str(4 * 16), str(4 * 3)],
        capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    assert "CAPI_OK" in r.stdout
    out0 = float([l for l in r.stdout.splitlines()
                  if l.startswith("OUT0")][0].split()[1])
    assert out0 == pytest.approx(float(want[0, 0]), rel=1e-4)


def test_artifact_carries_raw_mlir(tmp_path):
    """The artifact now embeds program.mlir — the raw StableHLO text the
    Python-free PJRT-C server (native/src/pjrt_serve.cc) compiles via
    PJRT_Client_Compile(format="mlir")."""
    import tarfile

    from paddle_tpu.serve.artifact import extract_mlir

    path = str(tmp_path / "mlp.ptc")
    _export_mlp(path)
    with tarfile.open(path) as tar:
        names = tar.getnames()
    assert "program.mlir" in names
    mlir_path = str(tmp_path / "program.mlir")
    meta = extract_mlir(path, mlir_path)
    text = open(mlir_path, "rb").read()
    assert b"stablehlo" in text and b"func.func public @main" in text
    assert meta["name"] == "mlp"


@pytest.mark.slow


def test_pjrt_serve_library_builds():
    """The PJRT-C serving library must compile and expose its ABI.
    (Running it needs a PJRT plugin device — covered by the gated test
    below on TPU hosts.)"""
    import ctypes

    pytest.importorskip(
        "tensorflow", reason="pjrt_c_api.h ships in the tensorflow wheel")

    from paddle_tpu.native.build import ensure_pjrt_built

    lib = ctypes.CDLL(ensure_pjrt_built())
    for sym in ("pts_load", "pts_forward", "pts_free", "pts_last_error"):
        assert hasattr(lib, sym)


@pytest.mark.skipif(
    os.environ.get("PADDLE_TPU_RUN_PJRT_TEST") != "1",
    reason="needs a live PJRT plugin device (the single-claim TPU); "
           "set PADDLE_TPU_RUN_PJRT_TEST=1 on a TPU host")
def test_pjrt_serve_end_to_end(tmp_path):
    """Full Python-free TPU serving: export artifact, extract raw
    StableHLO, compile+run it through libtpu's PJRT C API from C."""
    import ctypes

    from paddle_tpu.native.build import ensure_pjrt_built
    from paddle_tpu.serve.artifact import extract_mlir

    path = str(tmp_path / "mlp.ptc")
    forward, x = _export_mlp(path)
    want = np.asarray(forward(x))
    mlir_path = str(tmp_path / "program.mlir")
    extract_mlir(path, mlir_path)

    import libtpu

    plugin = os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
    lib = ctypes.CDLL(ensure_pjrt_built())
    lib.pts_load.restype = ctypes.c_void_p
    lib.pts_load.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.pts_last_error.restype = ctypes.c_char_p
    h = lib.pts_load(plugin.encode(), mlir_path.encode())
    assert h, lib.pts_last_error().decode()
    dims = (ctypes.c_int64 * 2)(*x.shape)
    out = np.zeros(want.shape, np.float32)
    rc = lib.pts_forward(
        ctypes.c_void_p(h), x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dims, 2, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size)
    assert rc == 0, lib.pts_last_error().decode()
    np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-3)
    lib.pts_free(ctypes.c_void_p(h))
