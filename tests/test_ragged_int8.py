"""Int8 dequant-fused ragged attention: parity + serving contract.

The quantized `(s8, scale)` pair arenas are half the HBM of a float
pool — the 2x-concurrency lever — and with PR12 they take the SAME
one-launch fused walk as float arenas: per-page dequant runs on the
VMEM scratch block right after its DMA lands, before the shared
attention body. The contract mirrors tests/test_ragged_attention.py
exactly: the kernel must match the jnp dequant-gather oracle
BIT-FOR-BIT under jit in interpret mode (`_walk_kernel_int8`'s
per-block `(s8 -> f32) * scale -> q.dtype` is the same element
sequence as `kv_dequantize`, so equality is exact, not approximate),
and an int8-pool ENGINE forced through the kernel must serve the
identical tokens + logprobs as the jnp path through oversubscription
and speculative verify rounds.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import transformer as T
from paddle_tpu.ops import paged_attention as PA
from paddle_tpu.ops import ragged_paged_attention as RPA
from paddle_tpu.serve.engine import DecodeEngine

pytestmark = pytest.mark.pallas

PAGE, HKV, DH = 4, 2, 8


def _arena8(np_rng, num_pages):
    """Quantized `(s8, scale)` K and V arenas with non-trivial scales
    (standard-normal data -> per-(position, kv-head) absmax varies)."""
    shape = (num_pages, PAGE, HKV, DH)
    ka = jnp.asarray(np_rng.standard_normal(shape), jnp.float32)
    va = jnp.asarray(np_rng.standard_normal(shape), jnp.float32)
    return PA.kv_quantize(ka), PA.kv_quantize(va)


def _jit(fn, **static):
    return jax.jit(functools.partial(fn, **static))


def assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active, *,
                                 page_size, max_len):
    kw = dict(page_size=page_size, max_len=max_len)
    ref = _jit(RPA.ragged_reference, **kw)(q, ka8, va8, pt, pos0,
                                           active)
    ker = _jit(RPA.ragged_pallas, **kw)(q, ka8, va8, pt, pos0, active)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(ker))
    return ref


class TestInt8RaggedParity:
    """Bit-identity of the dequant-fused walk across the same shape
    zoo the float suite pins."""

    def test_single_token_decode(self, np_rng):
        ka8, va8 = _arena8(np_rng, 9)
        pt = jnp.asarray(np_rng.randint(0, 9, (5, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((5, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 3, 7, 13, 5], jnp.int32)
        active = jnp.ones((5,), bool)
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=14)

    def test_page_boundary_crossing_window(self, np_rng):
        # TQ=3 prefill-chunk windows straddling page boundaries — the
        # dequant runs per scratch BLOCK, so a window reading both
        # sides of a block edge reads two independently-scaled dequants
        ka8, va8 = _arena8(np_rng, 8)
        pt = jnp.asarray(np_rng.randint(0, 8, (4, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 3, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([PAGE - 1, PAGE - 2, 2 * PAGE - 1, 0],
                           jnp.int32)
        active = jnp.ones((4,), bool)
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=16)

    def test_mixed_chunk_decode_verify_batch(self, np_rng):
        # one launch, ragged mix: prefill chunk mid-prompt, fresh
        # prompt at 0, deep decode row, inactive row — decode, chunk
        # and speculative verify windows are all this one grid
        ka8, va8 = _arena8(np_rng, 12)
        pt = jnp.asarray(np_rng.randint(0, 12, (4, 5)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 4, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([6, 0, 15, 19], jnp.int32)
        active = jnp.asarray([True, True, True, False])
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=19)

    def test_sentinel_and_inactive_rows(self, np_rng):
        # sentinel table entries (= num_pages) clip to the last real
        # page in BOTH the data and the scale-plane DMA — a mismatch
        # would dequantize real bytes with a garbage scale
        ka8, va8 = _arena8(np_rng, 6)
        pt = jnp.asarray(np_rng.randint(0, 6, (3, 4)), jnp.int32)
        pt = pt.at[0, 2:].set(6).at[2, :].set(6)
        q = jnp.asarray(np_rng.standard_normal((3, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([5, 9, 21], jnp.int32)
        active = jnp.asarray([True, True, False])
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=12)

    def test_bf16_compute_dtype(self, np_rng):
        # dequant lands on q.dtype scratch: with a bf16 q the kernel's
        # f32-multiply-then-round must equal kv_dequantize(..., bf16)
        ka8, va8 = _arena8(np_rng, 6)
        pt = jnp.asarray(np_rng.randint(0, 6, (3, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((3, 2, 4, DH)),
                        jnp.bfloat16)
        pos0 = jnp.asarray([0, 4, 8], jnp.int32)
        active = jnp.ones((3,), bool)
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=11)

    def test_max_len_not_page_multiple(self, np_rng):
        ka8, va8 = _arena8(np_rng, 7)
        pt = jnp.asarray(np_rng.randint(0, 7, (3, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((3, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 5, 9], jnp.int32)
        active = jnp.ones((3,), bool)
        assert_kernel_matches_oracle(q, ka8, va8, pt, pos0, active,
                                     page_size=PAGE, max_len=10)

    @pytest.mark.slow
    def test_int8_shape_sweep(self, np_rng):
        # randomized geometry sweep (each trial is a fresh compile —
        # the count is a tier-1 budget lever, same as the float sweep)
        for trial in range(5):
            num_pages = int(np_rng.randint(4, 14))
            mp = int(np_rng.randint(2, 6))
            r = int(np_rng.randint(1, 7))
            tq = int(np_rng.randint(1, 6))
            max_len = int(np_rng.randint(tq, mp * PAGE + 1))
            ka8, va8 = _arena8(np_rng, num_pages)
            pt = jnp.asarray(
                np_rng.randint(0, num_pages + 1, (r, mp)), jnp.int32)
            q = jnp.asarray(
                np_rng.standard_normal((r, tq, 2 * HKV, DH)),
                jnp.float32)
            pos0 = jnp.asarray(
                np_rng.randint(0, max(1, max_len - tq + 1), (r,)),
                jnp.int32)
            active = jnp.asarray(np_rng.randint(0, 2, (r,)) > 0)
            assert_kernel_matches_oracle(
                q, ka8, va8, pt, pos0, active, page_size=PAGE,
                max_len=max_len)


class TestInt8Dispatch:
    def test_fits_vmem_accounts_scale_and_scratch(self):
        # per key-block the int8 walk stages data (1B/elem) + scale
        # plane (4B/row) + the f32 dequant scratch (4B/elem) — MORE
        # than the same logical window in f32 (4B/elem), so a geometry
        # can fit as float and NOT fit as int8. Shape-only probes:
        # fits_vmem reads .shape/.dtype, never the bytes.
        pt = jnp.zeros((1, 8), jnp.int32)
        kw = dict(page_size=128, max_len=1024)
        # sized so the f32 walk is ~10.5MB of the 12MB budget: int8's
        # ~1.26x factor (1B data + scale + 4B scratch vs plain 4B)
        # pushes the SAME window over the line
        shape = (16, 128, 10, 128)
        kf = jax.ShapeDtypeStruct(shape, jnp.float32)
        k8 = (jax.ShapeDtypeStruct(shape, jnp.int8),
              jax.ShapeDtypeStruct(shape[:-1], jnp.float32))
        assert RPA.fits_vmem(kf, pt, **kw)
        assert not RPA.fits_vmem(k8, pt, **kw)
        # and a small int8 walk fits — the dispatch gate is open
        small = ((jax.ShapeDtypeStruct((6, PAGE, HKV, DH), jnp.int8),
                  jax.ShapeDtypeStruct((6, PAGE, HKV), jnp.float32)))
        assert RPA.fits_vmem(small, jnp.zeros((2, 3), jnp.int32),
                             page_size=PAGE, max_len=12)

    def test_verify_tq1_is_decode_int8(self, np_rng):
        """The spec path's K=0 degenerate is a plain decode step on
        int8 arenas too — through the forced kernel on both sides."""
        ka8, va8 = _arena8(np_rng, 9)
        pt = jnp.asarray(np_rng.randint(0, 9, (4, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 1, 4, DH)),
                        jnp.float32)
        k = jnp.asarray(np_rng.standard_normal((4, 1, HKV, DH)),
                        jnp.float32)
        v = jnp.asarray(np_rng.standard_normal((4, 1, HKV, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 5, 9, 30], jnp.int32)
        active = jnp.asarray([True, True, True, False])
        kw = dict(page_size=PAGE, max_len=14, impl="pallas")
        out_d, ka_d, va_d = _jit(PA.paged_decode_attention, **kw)(
            q, k, v, ka8, va8, pt, pos0, active)
        out_v, ka_v, va_v = _jit(PA.paged_verify_attention, **kw)(
            q, k, v, ka8, va8, pt, pos0, active)
        np.testing.assert_array_equal(np.asarray(out_d),
                                      np.asarray(out_v))
        for a, b in zip(ka_d + va_d, ka_v + va_v):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


CFG8 = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                           attn_impl="dense", kv_cache_dtype="int8")


@pytest.fixture(scope="module")
def params8():
    return T.init_params(jax.random.key(0), CFG8)


def _mk_eng(params, impl, **kw):
    return DecodeEngine(params, CFG8, slots=2, max_len=48,
                        page_size=8, ragged_impl=impl, **kw)


def _prompts(seed=0):
    """Oversubscribed traffic (6 requests through 2 slots) with the
    repetitive shapes the n-gram proposer bites on."""
    r = np.random.RandomState(seed)
    base = r.randint(0, 61, (6,)).astype(np.int32)
    return [np.concatenate([base, base, base[:3]]).astype(np.int32),
            r.randint(0, 61, (7,)).astype(np.int32),
            np.concatenate([base, base]).astype(np.int32),
            r.randint(0, 61, (5,)).astype(np.int32),
            np.concatenate([base[:4], base]).astype(np.int32),
            r.randint(0, 61, (4,)).astype(np.int32)]


class TestInt8EngineParity:
    """ISSUE acceptance: greedy serving parity (tokens + logprobs) for
    an int8-pool engine with the kernel forced, through
    oversubscription and speculative rounds — the engine-level proof
    that dropping the int8-excludes-kernel special case is safe."""

    @pytest.mark.slow
    def test_oversubscribed_greedy_parity(self, params8):
        ps = _prompts()
        want, want_lp = _mk_eng(params8, "jnp").serve(
            [p.copy() for p in ps], max_new=8, return_logprobs=True)
        eng = _mk_eng(params8, "pallas")
        got, got_lp = eng.serve([p.copy() for p in ps], max_new=8,
                                return_logprobs=True)
        assert got == want
        for a, b in zip(got_lp, want_lp):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert eng.artifact_manifest()["ragged_impl"] == "pallas"

    @pytest.mark.slow
    def test_speculative_rounds_parity(self, params8):
        ps = _prompts(seed=2)[:4]
        want = _mk_eng(params8, "jnp").serve(
            [p.copy() for p in ps], max_new=10, speculative=True)
        eng = _mk_eng(params8, "pallas")
        got = eng.serve([p.copy() for p in ps], max_new=10,
                        speculative=True)
        assert got == want
        st = eng.last_stats
        # the verify windows must actually exercise TQ>1 kernel
        # launches (real acceptance), not degenerate to decode
        assert st.draft_proposed > 0
        assert 0 < st.draft_accepted <= st.draft_proposed
