"""CRF and CTC tests: brute-force enumeration checks on tiny cases (the
strongest possible correctness oracle), gradient checks, and decode
consistency (reference: gserver/tests/test_CRFLayerGrad.cpp,
test_LinearChainCRF.cpp, test_WarpCTCLayer.cpp)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import crf as C
from paddle_tpu.ops import ctc as K
from gradcheck import directional_grad_check


def brute_force_log_norm(params, emissions, length):
    """Enumerate all tag paths for one sequence."""
    n = emissions.shape[-1]
    start, end, trans = map(np.asarray, params)
    e = np.asarray(emissions)
    scores = []
    for path in itertools.product(range(n), repeat=length):
        s = start[path[0]] + e[0, path[0]] + end[path[-1]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + e[t, path[t]]
        scores.append(s)
    m = np.max(scores)
    return m + np.log(np.sum(np.exp(np.asarray(scores) - m)))


class TestCRF:
    def test_log_norm_matches_brute_force(self, rng, np_rng):
        n, t = 3, 4
        params = C.init_crf_params(rng, n)
        emissions = np_rng.randn(1, t, n).astype(np.float32)
        got = float(C.crf_log_norm(params, jnp.asarray(emissions), jnp.asarray([t]))[0])
        want = brute_force_log_norm(params, emissions[0], t)
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_log_norm_ragged(self, rng, np_rng):
        n = 3
        params = C.init_crf_params(rng, n)
        emissions = np_rng.randn(2, 5, n).astype(np.float32)
        lengths = jnp.asarray([2, 5])
        got = C.crf_log_norm(params, jnp.asarray(emissions), lengths)
        want0 = brute_force_log_norm(params, emissions[0], 2)
        np.testing.assert_allclose(float(got[0]), want0, rtol=1e-4)

    def test_log_likelihood_normalized(self, rng, np_rng):
        """Sum over all paths of exp(loglik) must be 1."""
        n, t = 2, 3
        params = C.init_crf_params(rng, n)
        emissions = jnp.asarray(np_rng.randn(1, t, n), jnp.float32)
        total = 0.0
        for path in itertools.product(range(n), repeat=t):
            tags = jnp.asarray([list(path)])
            ll = C.crf_log_likelihood(params, emissions, tags, jnp.asarray([t]))
            total += float(jnp.exp(ll[0]))
        np.testing.assert_allclose(total, 1.0, rtol=1e-4)

    def test_decode_matches_brute_force(self, rng, np_rng):
        n, t = 3, 4
        params = C.init_crf_params(rng, n)
        emissions = np_rng.randn(1, t, n).astype(np.float32)
        tags, score = C.crf_decode(params, jnp.asarray(emissions), jnp.asarray([t]))
        # brute force best path
        start, end, trans = map(np.asarray, params)
        e = emissions[0]
        best, best_s = None, -1e30
        for path in itertools.product(range(n), repeat=t):
            s = start[path[0]] + e[0, path[0]] + end[path[-1]]
            for i in range(1, t):
                s += trans[path[i - 1], path[i]] + e[i, path[i]]
            if s > best_s:
                best, best_s = path, s
        assert tuple(np.asarray(tags)[0]) == best
        np.testing.assert_allclose(float(score[0]), best_s, rtol=1e-4)

    def test_grad(self, rng, np_rng):
        n, t = 3, 4
        params = C.init_crf_params(rng, n)
        emissions = jnp.asarray(np_rng.randn(2, t, n), jnp.float32)
        tags = jnp.asarray(np_rng.randint(0, n, (2, t)))
        lengths = jnp.asarray([t, t - 1])

        def loss(p):
            cp = C.CRFParams(**p)
            return -jnp.mean(C.crf_log_likelihood(cp, emissions, tags, lengths))

        directional_grad_check(
            loss, {"start": params.start, "end": params.end, "trans": params.trans}
        )


def brute_force_ctc(log_p, labels, blank=0):
    """Sum probability over all alignments for one sequence."""
    t, c = log_p.shape
    total = -np.inf
    for align in itertools.product(range(c), repeat=t):
        # collapse
        collapsed = []
        prev = None
        for a in align:
            if a != blank and a != prev:
                collapsed.append(a)
            prev = a
        if collapsed == list(labels):
            s = sum(log_p[i, a] for i, a in enumerate(align))
            total = np.logaddexp(total, s)
    return -total


class TestCTC:
    def test_matches_brute_force(self, np_rng):
        t, c = 4, 3
        logits = np_rng.randn(1, t, c).astype(np.float32)
        log_p = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
        labels = np.asarray([[1, 2]])
        got = float(
            K.ctc_loss(
                jnp.asarray(log_p), jnp.asarray([t]), jnp.asarray(labels),
                jnp.asarray([2]),
            )[0]
        )
        want = brute_force_ctc(log_p[0], [1, 2])
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_repeated_label(self, np_rng):
        t, c = 5, 3
        logits = np_rng.randn(1, t, c).astype(np.float32)
        log_p = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
        got = float(
            K.ctc_loss(
                jnp.asarray(log_p), jnp.asarray([t]), jnp.asarray([[1, 1]]),
                jnp.asarray([2]),
            )[0]
        )
        want = brute_force_ctc(log_p[0], [1, 1])
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_ragged_input_lengths(self, np_rng):
        t, c = 6, 3
        logits = np_rng.randn(2, t, c).astype(np.float32)
        log_p = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
        got = K.ctc_loss(
            jnp.asarray(log_p), jnp.asarray([3, 6]), jnp.asarray([[1], [2]]),
            jnp.asarray([1, 1]),
        )
        want0 = brute_force_ctc(log_p[0, :3], [1])
        np.testing.assert_allclose(float(got[0]), want0, rtol=1e-4)

    def test_grad_finite(self, np_rng):
        t, c = 5, 4
        logits = jnp.asarray(np_rng.randn(2, t, c), jnp.float32)

        def loss(p):
            log_p = jax.nn.log_softmax(p["x"], axis=-1)
            return jnp.sum(
                K.ctc_loss(
                    log_p, jnp.asarray([t, t - 1]), jnp.asarray([[1, 2], [3, 0]]),
                    jnp.asarray([2, 1]),
                )
            )

        directional_grad_check(loss, {"x": logits}, rtol=5e-3)

    def test_greedy_decode(self):
        # frames argmax: [1, 1, 0, 2, 2] -> collapse -> [1, 2]
        lp = np.full((1, 5, 3), -5.0, np.float32)
        for i, k in enumerate([1, 1, 0, 2, 2]):
            lp[0, i, k] = 0.0
        decoded, lens = K.ctc_greedy_decode(jnp.asarray(lp), jnp.asarray([5]))
        assert int(lens[0]) == 2
        np.testing.assert_array_equal(np.asarray(decoded)[0, :2], [1, 2])
        assert np.all(np.asarray(decoded)[0, 2:] == -1)
