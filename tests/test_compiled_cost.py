"""Chip-independent compiled-cost evidence (r4 verdict item #2).

Two rounds of a wedged TPU relay proved the repo needs perf evidence
that does not require the chip: these tests assert *compiled-program*
properties — residual-set bytes, while-loop state dtypes, scan-body
FLOP scaling — on the CPU backend, so every optimization in the
unmeasured-IOU table has reviewable evidence even when the relay is
dark. The on-chip campaign (benchmarks/run_r4_measurements.sh) turns
these same claims into wall-clock numbers when the chip answers;
benchmarks/results_v5e1.md's "compiled-cost evidence" section records
the quantities measured here at the real bench shapes.

Three claims:

  (a) ResNet remat shrinks the fwd->bwd residual set (the HBM-resident
      activations PROFILE_NOTES' 57.6 GiB/step roofline is made of) —
      measured abstractly via eval_shape of the vjp closure, which is
      exact at any batch size without materializing anything.
  (b) The int8 decode loop STREAMS s8 weights: the compiled while
      state carries s8 tensors (dequant traced inside the body, pinned
      by a loop-varying optimization_barrier). The negative control —
      dequant outside generate() — shows XLA hoisting the convert,
      which is exactly the failure docs/PARITY.md asked about.
  (c) Sliding-window attention cost scales with the window, not T^2:
      the backward's scan-body FLOPs are CONSTANT as T doubles (trip
      count is linear in T => linear total), where the full-attention
      backward's body FLOPs are linear in T (=> quadratic total).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import losses


def _residual_bytes(model, mstate, params, rng, x_shape):
    """Bytes of the fwd->bwd residual pytree — jax.vjp's returned
    closure IS a pytree of the saved tensors, and eval_shape walks it
    abstractly, so this is exact at any batch size at zero cost."""
    x = jax.ShapeDtypeStruct(x_shape, jnp.float32)
    y = jax.ShapeDtypeStruct((x_shape[0],), jnp.int32)

    def loss_fn(p, x, y):
        logits, _ = model.apply(p, mstate, x, training=True, rng=rng)
        return jnp.mean(losses.softmax_cross_entropy(logits, y))

    vjp_shape = jax.eval_shape(
        lambda p, x, y: jax.vjp(loss_fn, p, x, y)[1], params, x, y)
    return sum(l.size * jnp.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(vjp_shape))


class TestRematResiduals:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_remat_shrinks_residual_set(self):
        """Measured AT the headline bench config (bs 256, 224px —
        eval_shape makes the big shape free): 42.16 GiB of residuals
        baseline -> 18.69 (conv_out, -56%) -> 8.86 (full, -79%). Small
        batches would dilute the ratio with the batch-independent
        parameter residuals, so the assertion runs at the real shape."""
        from paddle_tpu import models
        from paddle_tpu.nn.module import ShapeSpec

        rng = jax.random.key(0)
        sizes = {}
        for remat in (None, "conv_out", "full"):
            model = models.resnet.resnet(50, num_classes=1000,
                                         remat=remat)
            params, mstate = model.init(rng, ShapeSpec((2, 224, 224, 3)))
            sizes[remat] = _residual_bytes(model, mstate, params, rng,
                                           (256, 224, 224, 3))
        assert sizes[None] > 40 * 2**30, sizes   # the roofline's scale
        assert sizes["conv_out"] < 0.5 * sizes[None], sizes
        assert sizes["full"] < 0.25 * sizes[None], sizes

    def test_remat_survives_lowering(self):
        """The recompute must reach XLA: jax.checkpoint lowers its saved
        residuals through optimization_barrier ops, so their presence in
        the StableHLO is the signature that the remat was not traced
        away before the compiler ever saw it."""
        from paddle_tpu import models
        from paddle_tpu.nn.module import ShapeSpec

        rng = jax.random.key(0)

        def lowered_text(remat):
            model = models.resnet.resnet(18, num_classes=10, remat=remat)
            params, mstate = model.init(rng, ShapeSpec((2, 64, 64, 3)))

            def loss_fn(p, x, y):
                logits, _ = model.apply(p, mstate, x, training=True,
                                        rng=rng)
                return jnp.mean(losses.softmax_cross_entropy(logits, y))

            x = jnp.zeros((2, 64, 64, 3), jnp.float32)
            y = jnp.zeros((2,), jnp.int32)
            return jax.jit(jax.grad(loss_fn)).lower(params, x, y).as_text()

        assert "optimization_barrier" not in lowered_text(None)
        assert lowered_text("full").count("optimization_barrier") >= 8


def _while_lines(compiled_text):
    return [l for l in compiled_text.splitlines() if " while(" in l]


class TestInt8DecodeLoop:
    @pytest.fixture(scope="class")
    def setup(self):
        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve import quant

        cfg = T.TransformerConfig(vocab=128, dim=64, n_layers=2,
                                  n_heads=2, attn_impl="dense")
        params = T.init_params(jax.random.key(0), cfg)
        qp = quant.quantize_params(params)
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, 128, (1, 8)), jnp.int32)
        return T, quant, cfg, params, qp, prompt

    def test_decode_loop_carries_s8(self, setup):
        """The PARITY.md hoisting question, answered in the affirmative
        direction: pass qparams to generate() and the compiled decode
        while-loop's carried state includes the s8 weights — each step
        streams 1/4 the weight bytes of a hoisted-f32 loop."""
        T, quant, cfg, params, qp, prompt = setup
        txt = jax.jit(
            lambda qp, p: T.generate(qp, cfg, p, steps=4)
        ).lower(qp, prompt).compile().as_text()
        wl = _while_lines(txt)
        assert wl, "decode did not compile to a while loop"
        assert any("s8[" in l for l in wl), (
            "int8 decode loop state carries no s8 tensors — the dequant "
            "was hoisted and the loop streams full-precision weights")

    def test_hoisted_control_has_no_s8_loop(self, setup):
        """Negative control: dequantizing OUTSIDE generate() leaves the
        f32 weights as loop invariants (this was the only int8 path
        before r5) — documents why the in-loop placement matters."""
        T, quant, cfg, params, qp, prompt = setup
        txt = jax.jit(
            lambda qp, p: T.generate(quant.dequantize_params(qp), cfg, p,
                                     steps=4)
        ).lower(qp, prompt).compile().as_text()
        wl = _while_lines(txt)
        assert wl and not any("s8[" in l for l in wl)

    def test_streaming_matches_hoisted_tokens(self, setup):
        """Placement must not change math: in-loop dequant decodes the
        exact same tokens as the hoisted path."""
        T, quant, cfg, params, qp, prompt = setup
        a = T.generate(qp, cfg, prompt, steps=6)
        b = T.generate(quant.dequantize_params(qp), cfg, prompt, steps=6)
        assert jnp.array_equal(a, b)


class TestRollingSWACache:
    def test_decode_loop_state_is_window_sized(self):
        """Sliding-window decode must CARRY a window-slot ring cache,
        not a full-length masked buffer — the full buffer would stream
        O(total) cache bytes every step (the einsum reads the whole
        buffer; masking happens after). total=80 and window=8 are
        chosen to be unambiguous in the HLO shape strings."""
        from paddle_tpu.models import transformer as T

        cfg = T.TransformerConfig(vocab=48, dim=16, n_layers=2,
                                  n_heads=2, attn_impl="dense",
                                  attn_window=8)
        params = T.init_params(jax.random.key(0), cfg)
        prompt = jnp.zeros((1, 16), jnp.int32)  # + 64 steps = total 80
        txt = jax.jit(
            lambda p, toks: T.generate(p, cfg, toks, steps=64)
        ).lower(params, prompt).compile().as_text()
        wl = _while_lines(txt)
        assert wl, "decode did not compile to a while loop"
        assert any("[1,8," in l for l in wl), (
            "no window-sized (8-slot) cache in the decode loop state")
        assert not any("[1,80," in l for l in wl), (
            "decode loop still carries a full-length (80-slot) buffer")

    @pytest.mark.slow

    def test_rolling_matches_full_buffer_band_mask(self):
        """The ring layout must not change math: same tokens as the
        band-masked full buffer, which still serves beam_decode (its
        greedy-equality is tested in test_transformer, but assert the
        cross-impl equality here where the ring is the subject)."""
        from paddle_tpu.models import transformer as T

        cfg = T.TransformerConfig(vocab=32, dim=16, n_layers=2,
                                  n_heads=2, mlp_ratio=2,
                                  attn_impl="dense", attn_window=4)
        params = T.init_params(jax.random.key(1), cfg)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(1, 32, (2, 6)), jnp.int32)
        rolled = T.generate(params, cfg, prompt, steps=7)   # ring path
        seqs, _ = T.beam_decode(params, cfg, prompt, steps=7,
                                beam_size=1)                # full buffer
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                      np.asarray(rolled))


class TestSWAFlopScaling:
    @staticmethod
    def _bwd_body_flops(T, window):
        """XLA cost analysis counts a scan's body ONCE (trip count is
        not multiplied in), so body-FLOPs-vs-T is the scaling law of
        the per-block work: constant body => linear total, linear body
        => quadratic total."""
        from paddle_tpu.ops.flash_attention import flash_attention

        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(1, T, 2, 32), jnp.float32)
                   for _ in range(3))
        f = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, window=window,
                block_q=128, block_k=128).sum(),
            argnums=(0, 1, 2)))
        ca = f.lower(q, k, v).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):     # older jax: one entry
            ca = ca[0]                        # per computation
        return float(ca["flops"])

    def test_swa_backward_linear_in_t(self):
        """Measured: full backward body 3.45e8 -> 6.87e8 FLOPs as T
        doubles 4096 -> 8192 (ratio 1.99: quadratic total); windowed
        (w=256) body 3.53e7 -> 3.61e7 (ratio 1.02: linear total, and
        ~19x less per-block work at T=8192)."""
        full = [self._bwd_body_flops(t, None) for t in (4096, 8192)]
        sw = [self._bwd_body_flops(t, 256) for t in (4096, 8192)]
        assert full[1] / full[0] > 1.7, full     # body linear in T
        assert sw[1] / sw[0] < 1.2, sw           # body constant in T
        assert sw[1] < full[1] / 4, (sw, full)   # and much cheaper


class TestFusedCEResiduals:
    """Claim (d), r5: fused chunked cross-entropy removes the [N, vocab]
    logits tensor from the fwd->bwd residual set of the flagship LM.

    Measured AT the transformer bench config (B4 T8192 D512 L8 V32000,
    flash attention + per-block remat — benchmarks/suite.py
    bench_transformer_lm): 4.81 GiB of residuals plain -> 0.91 GiB
    fused (-81%); the f32 logits (4*8191*32000*4 B = 4.19 GiB) were 87%
    of the set. eval_shape makes the big shape free on CPU."""

    def test_fused_ce_drops_logits_residual(self):
        import dataclasses

        from paddle_tpu.models import transformer as T

        cfg = T.TransformerConfig(vocab=32000, dim=512, n_layers=8,
                                  n_heads=8, attn_impl="flash",
                                  remat=True)
        params = T.init_params(jax.random.key(0), cfg)
        toks = jax.ShapeDtypeStruct((4, 8192), jnp.int32)

        def residual_bytes(c):
            vjp_shape = jax.eval_shape(
                lambda p, t: jax.vjp(lambda p: T.loss(p, c, t), p)[1],
                params, toks)
            return sum(l.size * jnp.dtype(l.dtype).itemsize
                       for l in jax.tree.leaves(vjp_shape))

        base = residual_bytes(cfg)
        fused = residual_bytes(
            dataclasses.replace(cfg, fused_ce_chunk=2048))
        logits_bytes = 4 * 8191 * 32000 * 4
        assert base > logits_bytes, (base, logits_bytes)
        # the drop IS the logits tensor: what the fused path stops
        # saving is (to within 10%) exactly the [N, V] f32 logits
        assert base - fused > 0.9 * logits_bytes, (base, fused)
        assert fused < 0.35 * base, (fused, base)


class TestGQACacheState:
    def test_decode_loop_cache_shrinks_with_kv_heads(self):
        """Claim (e): GQA's win is decode bandwidth — the KV cache the
        while loop CARRIES (and re-reads every step, the decode
        bottleneck) must shrink by n_heads/n_kv_heads, and the compact
        cache must never be expanded back to n_heads inside the loop.
        H=4 heads, head_dim 8, total length 24: the MHA loop state
        carries [1,24,4,8] K/V buffers; with n_kv_heads=1 it must carry
        [1,24,1,8] and no [1,24,4,8] tensor may appear in the loop."""
        import dataclasses

        from paddle_tpu.models import transformer as T

        base = T.TransformerConfig(vocab=48, dim=32, n_layers=1,
                                   n_heads=4, attn_impl="dense")
        prompt = jnp.zeros((1, 8), jnp.int32)  # + 16 steps = total 24

        def while_text(cfg):
            params = T.init_params(jax.random.key(0), cfg)
            txt = jax.jit(
                lambda p, toks: T.generate(p, cfg, toks, steps=16)
            ).lower(params, prompt).compile().as_text()
            wl = _while_lines(txt)
            assert wl, "decode did not compile to a while loop"
            return "\n".join(wl)

        mha = while_text(base)
        gqa = while_text(dataclasses.replace(base, n_kv_heads=1))
        assert "[1,24,4,8]" in mha, mha[:400]
        assert "[1,24,1,8]" in gqa, gqa[:400]
        assert "[1,24,4,8]" not in gqa, (
            "GQA decode loop materializes a full-head cache — the "
            "4x bandwidth win is lost")


class TestInt8KVCacheState:
    def test_decode_loop_carries_s8_kv(self):
        """Claim (f), r5: with kv_cache_dtype="int8" the decode while
        loop's carried state holds the KV cache as s8 (+ small scale
        tensors), and no full-size fp KV buffer remains in the loop —
        the per-step cache read (the bandwidth term that GROWS with
        context) drops to ~half the bf16 bytes at head_dim-64 serving shapes (+1 scale per vector), 4x vs f32. Shapes chosen unambiguous: total=24 slots,
        2 kv-heads, head_dim 16."""
        import dataclasses

        from paddle_tpu.models import transformer as T

        cfg = T.TransformerConfig(vocab=48, dim=32, n_layers=1,
                                  n_heads=2, attn_impl="dense")
        prompt = jnp.zeros((1, 8), jnp.int32)  # + 16 steps = total 24

        def while_text(c):
            params = T.init_params(jax.random.key(0), c)
            txt = jax.jit(
                lambda p, toks: T.generate(p, c, toks, steps=16)
            ).lower(params, prompt).compile().as_text()
            wl = _while_lines(txt)
            assert wl, "decode did not compile to a while loop"
            return "\n".join(wl)

        fp = while_text(cfg)
        q8 = while_text(dataclasses.replace(cfg, kv_cache_dtype="int8"))
        assert "s8[1,24,2,16]" in q8, q8[:500]
        assert "s8[" not in fp
        # the fp-size cache must not ALSO ride the loop (that would be
        # dequant-hoisting — the cache analog of the weights failure)
        for fp_kind in ("f32[1,24,2,16]", "bf16[1,24,2,16]",
                        "f64[1,24,2,16]"):
            assert fp_kind not in q8, fp_kind


class TestEngineCompiledStep:
    def test_int8_pool_step_reads_s8(self):
        """Claim (g): the serving engine's jitted decode step takes the
        int8 pool as s8 arguments and returns s8 — no fp-size cache
        tensor appears anywhere in the compiled step, so per-step pool
        traffic is s8 for the engine exactly as the while-loop state is
        for generate(). The pool is the block-paged ARENA now
        ([num_pages, page_size, Hkv, Dh]): 3 slots x 24 max_len at
        page_size 8 -> 9 pages x 8 x 2 kv-heads x 16."""
        import dataclasses

        from paddle_tpu.models import transformer as T
        from paddle_tpu.serve.engine import DecodeEngine

        cfg = T.TransformerConfig(vocab=48, dim=32, n_layers=1,
                                  n_heads=2, attn_impl="dense",
                                  kv_cache_dtype="int8")
        params = T.init_params(jax.random.key(0), cfg)
        eng = DecodeEngine(params, cfg, slots=3, max_len=24,
                           page_size=8)
        assert eng.num_pages == 9 and eng.page_size == 8
        state = eng.init_state()
        txt = eng._step_jit.lower(state).compile().as_text()
        # the ARENA STATE crosses the step boundary as s8: parameters
        # and the root result carry s8 pool tensors, and no fp-size
        # arena tensor appears in the entry signature (the per-step
        # dequant is a transient inside the gathered attention reads)
        sig = [l for l in txt.splitlines()
               if "ENTRY" in l or "ROOT" in l or " parameter(" in l]
        sig = "\n".join(sig)
        assert "s8[9,8,2,16]" in sig, sig[:500]
        for fp_kind in ("f32[9,8,2,16]", "bf16[9,8,2,16]",
                        "f64[9,8,2,16]"):
            assert fp_kind not in sig, (fp_kind, sig[:500])
