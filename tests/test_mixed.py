"""MixedLayer composition tests — projections + operators summed
(reference: gserver/layers/MixedLayer.cpp; grad coverage mirrors
gserver/tests/test_LayerGrad.cpp's mixed/projection cases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gradcheck import directional_grad_check
from paddle_tpu import nn
from paddle_tpu.nn import mixed as M
from paddle_tpu.nn.module import ShapeSpec


def _apply_sum(layer, params, *inputs):
    out, _ = layer.apply(params, {}, *inputs, training=True, rng=None)
    return jnp.sum(out ** 2)


def test_mixed_fc_identity_dotmul_sum():
    """fc + identity + dot_mul projections over two inputs sum into one
    output; matches manual computation."""
    layer = M.Mixed([
        M.FullMatrixProjection(8, input=0, name="fc"),
        M.IdentityProjection(input=1, name="id"),
        M.DotMulProjection(input=1, name="dm"),
    ], use_bias=True)
    x0 = jnp.asarray(np.random.RandomState(0).randn(4, 6), jnp.float32)
    x1 = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((4, 6)),
                           ShapeSpec((4, 8)))
    out, _ = layer.apply(params, {}, x0, x1)
    expect = (x0 @ params["fc"]["kernel"] + x1 + params["dm"]["w"] * x1
              + params["bias"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_mixed_projection_grads():
    """Numeric-vs-analytic grads through a mixed stack of parameterized
    projections (the test_LayerGrad.cpp discipline)."""
    layer = M.Mixed([
        M.FullMatrixProjection(5, input=0),
        M.TransposedFullMatrixProjection(5, input=0),
        M.ScalingProjection(input=1),
        M.DotMulProjection(input=1),
        M.IdentityOffsetProjection(5, offset=2, input=2),
    ])
    specs = (ShapeSpec((3, 4)), ShapeSpec((3, 5)), ShapeSpec((3, 9)))
    params, _ = layer.init(jax.random.key(0), *specs)
    xs = tuple(jnp.asarray(np.random.RandomState(i).randn(*s.shape),
                           jnp.float32) for i, s in enumerate(specs))
    directional_grad_check(lambda p: _apply_sum(layer, p, *xs), params)


def test_identity_offset_selects_window():
    layer = M.Mixed([M.IdentityOffsetProjection(3, offset=2)])
    x = jnp.arange(24, dtype=jnp.float32).reshape(2, 12)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 12)))
    out, _ = layer.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x[:, 2:5]))


def test_slice_projection_concats_ranges():
    layer = M.Mixed([M.SliceProjection([(0, 2), (5, 8)])])
    x = jnp.arange(20, dtype=jnp.float32).reshape(2, 10)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 10)))
    out, _ = layer.apply(params, {}, x)
    expect = np.concatenate([np.asarray(x[:, 0:2]), np.asarray(x[:, 5:8])], 1)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_table_projection_lookup_grad():
    layer = M.Mixed([M.TableProjection(vocab=11, size=4)])
    ids = jnp.asarray([[1, 5], [9, 0]], jnp.int32)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 2), jnp.int32))
    out, _ = layer.apply(params, {}, ids)
    assert out.shape == (2, 2, 4)
    np.testing.assert_allclose(np.asarray(out[0, 1]),
                               np.asarray(params["b0_TableProjection"]["table"][5]))
    directional_grad_check(lambda p: _apply_sum(layer, p, ids), params)


def test_context_projection_branch_matches_op():
    from paddle_tpu.ops import sequence as seq_ops

    layer = M.Mixed([M.ContextProjectionBranch(3, context_start=-1)])
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 3), jnp.float32)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 5, 3)))
    out, _ = layer.apply(params, {}, x)
    expect = seq_ops.context_projection(x, None, context_len=3,
                                        context_start=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect))


def test_context_projection_trainable_padding_grad():
    layer = M.Mixed([M.ContextProjectionBranch(
        3, context_start=-1, trainable_padding=True)])
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 3), jnp.float32)
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 4, 3)))
    assert "padding" in params["b0_ContextProjectionBranch"]
    directional_grad_check(lambda p: _apply_sum(layer, p, x), params)


def test_conv_projection_flattens_and_sums_with_fc():
    """conv projection output (flattened) sums with an fc projection over
    a second flat input — the reference's mixed image+flat pattern."""
    img = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 3), jnp.float32)
    flat = jnp.asarray(np.random.RandomState(1).randn(2, 10), jnp.float32)
    layer = M.Mixed([
        M.ConvProjection(4, 3, stride=2, input=0),
        M.FullMatrixProjection(4 * 4 * 4, input=1),
    ])
    params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 8, 8, 3)),
                           ShapeSpec((2, 10)))
    out, _ = layer.apply(params, {}, img, flat)
    assert out.shape == (2, 64)
    directional_grad_check(lambda p: _apply_sum(layer, p, img, flat), params)


def test_pool_projection_max_and_avg():
    img = jnp.asarray(np.random.RandomState(0).randn(2, 4, 4, 3), jnp.float32)
    for kind in ("max", "avg"):
        layer = M.Mixed([M.PoolProjection(kind, 2)])
        params, _ = layer.init(jax.random.key(0), ShapeSpec((2, 4, 4, 3)))
        out, _ = layer.apply(params, {}, img)
        assert out.shape == (2, 2 * 2 * 3)


def test_dotmul_operator_two_inputs():
    a = jnp.asarray(np.random.RandomState(0).randn(3, 7), jnp.float32)
    b = jnp.asarray(np.random.RandomState(1).randn(3, 7), jnp.float32)
    layer = M.Mixed([M.DotMulOperator(scale=2.0, inputs=(0, 1))])
    params, _ = layer.init(jax.random.key(0), ShapeSpec((3, 7)),
                           ShapeSpec((3, 7)))
    out, _ = layer.apply(params, {}, a, b)
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.asarray(a * b),
                               rtol=1e-6)
    assert not params  # operators own no parameters (Operator.h:36)


def test_conv_operator_per_sample_filters():
    """The filter comes from the second INPUT, one filter set per batch
    row (ConvOperator.cpp offsets weights by batchId)."""
    n, h, w, c, oc, k = 2, 5, 5, 3, 4, 3
    img = jnp.asarray(np.random.RandomState(0).randn(n, h, w, c), jnp.float32)
    flt = jnp.asarray(np.random.RandomState(1).randn(n, k * k * c * oc),
                      jnp.float32)
    op = M.ConvOperator(oc, k, padding="VALID", inputs=(0, 1))
    layer = M.Mixed([op])
    params, _ = layer.init(jax.random.key(0), ShapeSpec((n, h, w, c)),
                           ShapeSpec((n, k * k * c * oc)))
    out, _ = layer.apply(params, {}, img, flt)
    assert out.shape == (n, 3 * 3 * oc)
    # per-sample check: row 0's output only depends on row 0's filter
    flt2 = flt.at[1].set(0.0)
    out2, _ = layer.apply(params, {}, img, flt2)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(out2[0]),
                               rtol=1e-5)
    assert float(jnp.abs(out2[1]).max()) == 0.0


def test_conv_trans_operator_shape():
    n, h, w, c, oc, k = 2, 3, 3, 2, 3, 2
    img = jnp.asarray(np.random.RandomState(0).randn(n, h, w, c), jnp.float32)
    flt = jnp.asarray(np.random.RandomState(1).randn(n, k * k * c * oc),
                      jnp.float32)
    layer = M.Mixed([M.ConvTransOperator(oc, k, stride=2, inputs=(0, 1))])
    params, _ = layer.init(jax.random.key(0), ShapeSpec((n, h, w, c)),
                           ShapeSpec((n, k * k * c * oc)))
    out, _ = layer.apply(params, {}, img, flt)
    assert out.shape == (n, 6 * 6 * oc)


def test_mixed_shape_mismatch_raises():
    with pytest.raises(Exception):
        layer = M.Mixed([
            M.FullMatrixProjection(5, input=0),
            M.FullMatrixProjection(6, input=0),
        ])
        layer.init(jax.random.key(0), ShapeSpec((2, 3)))


def test_mixed_in_sequential_pipeline():
    """Mixed as an ordinary Layer inside Sequential (single input)."""
    net = nn.Sequential([
        M.Mixed([M.FullMatrixProjection(16),
                 M.IdentityOffsetProjection(16, offset=0)],
                use_bias=True, activation="relu", name="mix"),
        nn.Dense(4, name="out"),
    ])
    params, state = net.init(jax.random.key(0), ShapeSpec((2, 20)))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 20), jnp.float32)
    out, _ = net.apply(params, state, x)
    assert out.shape == (2, 4)
