"""HTTP front door: the streaming network edge over the serving fleet.

The network-edge fault model (docs/RELIABILITY.md), proven at three
depths:

- **The cancel seam** — `ServingServer.cancel` / `partial_tokens` and
  the router's failover-safe forwarding: a cancel is a deadline pulled
  to now, so the PROVEN expire/retire path frees the slot, its pages,
  and any parked handoff pins; partials read the live emitted prefix.
- **The wire** — real sockets against `HttpEdge`: chunked streaming
  parity with the solo decode, malformed/oversized frames answered
  in-band without touching the router, slow-loris reads closed on the
  timeout alone, X-Deadline-Ms expiry mid-stream, disconnect-cancel
  leak accounting, overload answered 429 + Retry-After with the
  admission queue bounded, graceful drain (503 newcomers, in-flight
  finishes, the report lands).
- **The real thing** (slow/heavyweight) — live HTTP streams over real
  replica processes while `FaultPlan` SIGKILLs one mid-burst: every
  client stream still ends in exactly one completed outcome with
  bit-exact greedy tokens — the failover is invisible on the wire.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.http_edge import HttpEdge
from paddle_tpu.serve.router import ServingRouter
from paddle_tpu.serve.server import ServingServer
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.testing.fleet import TINY, save_tiny_artifact
from paddle_tpu.testing.traffic import (TrafficShape, closed_loop,
                                        open_loop, slo_report,
                                        stream_generate)

pytestmark = [pytest.mark.edge, pytest.mark.faults]

CFG = T.TransformerConfig(**TINY)

CHILD_ENV = {"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def mk_stack(params, *, max_queue=16, **edge_kw):
    """Fresh engine -> server -> 1-replica router -> started edge.
    Fresh per test: the leak-accounting assertions need books no
    earlier test wrote in."""
    eng = DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4)
    srv = ServingServer(eng, max_queue=max_queue, buckets=(16,))
    router = ServingRouter([srv])
    edge = HttpEdge(router, **edge_kw).start()
    return edge, router, srv


def wait_idle(edge, router, timeout_s=20.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if (edge.counters()["active_streams"] == 0
                and not router.sweep()):
            return True
        time.sleep(0.02)
    return False


def raw_exchange(addr, blob, timeout_s=5.0):
    """Send raw bytes, read to EOF — the malformed-input client."""
    with socket.create_connection(addr, timeout=timeout_s) as s:
        s.sendall(blob)
        out = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                return out
            out += chunk


# ---------------------------------------------------------------------------
# the cancel/partial seams (no HTTP involved)


def test_server_cancel_frees_mid_generation(params):
    """Cancel pulls the deadline to now: the in-flight request ends
    `expired` with its partial prefix, the slot and its pages retire
    through the proven machinery, and the books reconcile."""
    eng = DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4)
    srv = ServingServer(eng, max_queue=8, buckets=(16,))
    prompt = np.asarray([1, 2, 3, 4], np.int32)
    rid = srv.submit(prompt, max_new=12)
    seen = []

    def chop(_srv, _step):
        seen.append(len(srv.partial_tokens(rid)))
        if len(seen) == 3:
            assert srv.cancel(rid, reason="test cancel")

    srv.on_step.append(chop)
    res = srv.run()[rid]
    assert res.outcome == "expired"
    # the partial prefix survives into the terminal result, and it is
    # a prefix of the solo greedy decode
    full = ref_tokens(params, [1, 2, 3, 4], 12)
    assert list(res.tokens) == full[:len(res.tokens)]
    assert len(res.tokens) < 12
    # post-terminal partials read the ledger; a second cancel is a
    # no-op returning False
    assert srv.partial_tokens(rid) == list(res.tokens)
    assert not srv.cancel(rid)
    srv.reconcile()
    pool = srv.engine.pool
    assert pool.pages_in_use - pool.evictable() == 0


def test_router_cancel_queued_and_unknown(params):
    """A queued (never-scheduled) request cancels before any decode
    step; unknown ids are a False no-op, not an error."""
    eng = DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4)
    srv = ServingServer(eng, max_queue=8, buckets=(16,))
    router = ServingRouter([srv])
    rid = router.submit(np.asarray([5, 6, 7], np.int32), max_new=4)
    assert router.cancel(rid, reason="before any step")
    res = router.run()[rid]
    assert res.outcome == "expired"
    assert res.tokens == []
    assert not router.cancel(10_000)
    assert router.partial_tokens(10_000) == []
    router.reconcile()


# ---------------------------------------------------------------------------
# the wire: streaming protocol


def test_stream_parity_and_nonstream(params):
    """Chunked streaming hands over exactly the solo greedy decode,
    in order; `stream: false` returns the same tokens in one JSON
    body; TTFT/ITG land in the bound histograms."""
    from paddle_tpu.obs import MetricsRegistry

    registry = MetricsRegistry()
    edge, router, srv = mk_stack(params, registry=registry)
    try:
        prompt = [1, 2, 3, 4, 5]
        want = ref_tokens(params, prompt, 6)
        r = stream_generate(edge.addr, prompt, 6)
        assert r.status == 200 and r.outcome == "completed"
        assert r.tokens == want
        assert r.ttft_s is not None and r.ttft_s > 0
        r2 = stream_generate(edge.addr, prompt, 6, sampling=None)
        assert r2.tokens == want
        # non-stream mode: same payload, single body
        blob = json.dumps({"prompt": prompt, "max_new": 6,
                           "stream": False}).encode()
        raw = raw_exchange(
            edge.addr,
            f"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
            f"Content-Length: {len(blob)}\r\n\r\n".encode() + blob)
        body = json.loads(raw.split(b"\r\n\r\n", 1)[1])
        assert body["outcome"] == "completed"
        assert body["tokens"] == want
        assert body["n_tokens"] == 6
        snap = {s["name"] for s in registry.snapshot()["series"]}
        assert "edge_ttft_seconds_bucket" in snap
        assert "edge_requests" in snap
        c = edge.counters()
        assert c["requests"] == 3 == c["completed"]
    finally:
        edge.close()


@pytest.mark.locks      # rides with the LK003 hot-path fix
def test_stream_bit_exact_across_many_chunks(params,
                                             lock_order_guard):
    """The LK003 hot-path contract: `_snapshot` reads the partial
    tokens UNDER the router lock and the chunked socket write happens
    OUTSIDE it. Throttled steps force the stream through many
    snapshot/write cycles (one or two tokens per chunk), and the
    concatenation of every chunk must still be bit-exact against the
    solo greedy decode — proving the restructure drops the lock
    without ever tearing or reordering the stream. Runs under
    LockOrderGuard so a regression that re-nests the write under the
    lock shows up as an order violation, not just a slow stream."""
    edge, router, srv = mk_stack(params)
    throttle_steps(srv, delay_s=0.03)
    try:
        prompt = [2, 4, 6]
        want = ref_tokens(params, prompt, 8)
        r = stream_generate(edge.addr, prompt, 8)
        assert r.status == 200 and r.outcome == "completed"
        assert r.tokens == want         # bit-exact, in order
        # the throttle spread the stream over several chunks: some
        # inter-token gap is nonzero, so parity was across REAL
        # snapshot/write cycles, not one lucky final chunk
        assert any(g > 0 for g in r.gaps_s)
        assert wait_idle(edge, router)
    finally:
        edge.close()


def test_healthz_and_metrics(params):
    from paddle_tpu.obs import MetricsRegistry

    edge, router, srv = mk_stack(params, registry=MetricsRegistry())
    try:
        raw = raw_exchange(edge.addr,
                           b"GET /healthz HTTP/1.1\r\nHost: e\r\n\r\n")
        assert b" 200 " in raw.split(b"\r\n", 1)[0]
        payload = json.loads(raw.split(b"\r\n\r\n", 1)[1])
        assert payload == {"draining": False,
                           "queue_space": 16, "active_streams": 0}
        # histogram series appear once observations exist: stream one
        # request, then scrape
        stream_generate(edge.addr, [1, 2, 3], 2)
        raw = raw_exchange(edge.addr,
                           b"GET /metrics HTTP/1.1\r\nHost: e\r\n\r\n")
        assert b"edge_connections" in raw
        assert b"edge_ttft_seconds_bucket" in raw
    finally:
        edge.close()


def test_malformed_never_touch_the_fleet(params):
    """Every malformed/oversized/unknown frame is answered in-band
    with its proper status — and the router's admission ledger never
    hears about any of them."""
    edge, router, srv = mk_stack(params, max_header_bytes=512,
                                 max_body_bytes=256)
    try:
        cases = [
            # (raw request, expected status)
            (b"NONSENSE\r\n\r\n", b" 400 "),
            (b"GET /nope HTTP/1.1\r\nHost: e\r\n\r\n", b" 404 "),
            (b"GET /v1/generate HTTP/1.1\r\nHost: e\r\n\r\n", b" 405 "),
            (b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n\r\n", b" 411 "),
            (b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
             b"Content-Length: zero\r\n\r\n", b" 400 "),
            # declared body over the cap: refused BEFORE a byte is read
            (b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
             b"Content-Length: 99999\r\n\r\n", b" 413 "),
            # header block over the cap: refused as it accumulates
            (b"GET /healthz HTTP/1.1\r\n"
             + b"X-Filler: " + b"a" * 4096 + b"\r\n\r\n", b" 431 "),
            # body that is not JSON
            (b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
             b"Content-Length: 9\r\n\r\nnot json!", b" 400 "),
            # JSON but no usable prompt
            (b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
             b"Content-Length: 13\r\n\r\n{\"prompt\": 3}", b" 400 "),
        ]
        for raw_req, status in cases:
            raw = raw_exchange(edge.addr, raw_req)
            assert status in raw.split(b"\r\n", 1)[0], (raw_req, raw)
        assert router.counters()["requests"] == 0
        assert edge.counters()["requests"] == 0
        assert edge.counters()["malformed_400"] > 0
    finally:
        edge.close()


def test_slow_loris_closed_on_timeout_alone(params):
    """A client feeding header bytes slower than the read deadline is
    closed WITHOUT a reply and without touching the router."""
    edge, router, srv = mk_stack(params, header_timeout_s=0.2,
                                 body_timeout_s=0.2)
    try:
        with socket.create_connection(edge.addr, timeout=5.0) as s:
            s.sendall(b"POST /v1/generate HTTP/1.1\r\n")  # ...stall...
            s.settimeout(5.0)
            assert s.recv(4096) == b""      # closed, no reply owed
        # same defense on the BODY read: headers complete, body stalls
        with socket.create_connection(edge.addr, timeout=5.0) as s:
            s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
                      b"Content-Length: 64\r\n\r\n{\"pro")
            s.settimeout(5.0)
            assert s.recv(4096) == b""
        assert edge.counters()["hangups"] == 2
        assert router.counters()["requests"] == 0
    finally:
        edge.close()


def test_deadline_header_expires_request(params):
    """X-Deadline-Ms rides the submit into the fleet's own deadline
    machinery: a budget far smaller than the decode ends `expired`
    with whatever prefix was produced."""
    edge, router, srv = mk_stack(params)
    try:
        r = stream_generate(edge.addr, [1, 2, 3], 12, deadline_ms=0.01)
        assert r.status == 200
        assert r.outcome == "expired"
        assert len(r.tokens) < 12
        # malformed deadline header: 400 in-band
        blob = json.dumps({"prompt": [1], "max_new": 2}).encode()
        raw = raw_exchange(
            edge.addr,
            f"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
            f"X-Deadline-Ms: soon\r\n"
            f"Content-Length: {len(blob)}\r\n\r\n".encode() + blob)
        assert b" 400 " in raw.split(b"\r\n", 1)[0]
    finally:
        edge.close()


# ---------------------------------------------------------------------------
# disconnect cancellation


def throttle_steps(srv, delay_s=0.03):
    """Slow every decode sweep. The disconnect tests race a client's
    FIN against generation finishing; on an idle box the FIN always
    wins, but on a loaded 1-vCPU runner the tiny model can emit every
    token before the EOF probe gets scheduled — then router.cancel
    correctly finds a terminal request and counts nothing. Pinning a
    floor on step wall-time makes the race deterministic."""
    orig = srv.step
    def slow_step():
        time.sleep(delay_s)
        return orig()
    srv.step = slow_step


@pytest.mark.locks      # chaos lane re-run under LockOrderGuard
def test_disconnect_mid_stream_frees_slot_and_pages(
        params, lock_order_guard):
    """The tentpole invariant: a client vanishing mid-stream costs
    the fleet NOTHING durable — the in-flight request is force-
    expired through the deadline/retire path, its slot and pages
    free (pages still resident are cache-only and evictable), the
    books reconcile, and the next client is served normally."""
    edge, router, srv = mk_stack(params)
    throttle_steps(srv)
    try:
        r = stream_generate(edge.addr, [1, 2, 3, 4], 12,
                            abort_after_tokens=2)
        assert r.aborted and len(r.tokens) >= 2
        assert wait_idle(edge, router)
        c = edge.counters()
        assert c["disconnect_cancels"] == 1
        assert c["active_streams"] == 0
        # the ledger shows the force-expire, with the partial prefix
        (rid, res), = router.results.items()
        assert res.outcome == "expired"
        assert len(res.tokens) < 12
        router.reconcile()
        srv.reconcile()
        pool = srv.engine.pool
        assert pool.pages_in_use - pool.evictable() == 0
        assert all(req is None for req in srv._slot_req)
        # the fleet is still fully serviceable
        want = ref_tokens(params, [9, 8, 7], 4)
        r2 = stream_generate(edge.addr, [9, 8, 7], 4)
        assert r2.outcome == "completed" and r2.tokens == want
    finally:
        edge.close()


def test_disconnect_while_queued_cancels_before_decode(params):
    """A client that vanishes while its request is still QUEUED
    (both slots busy) is cancelled before it ever takes a slot."""
    edge, router, srv = mk_stack(params)
    throttle_steps(srv)
    try:
        holders = [
            threading.Thread(
                target=stream_generate,
                args=(edge.addr, [1, 2, 3 + i], 10), daemon=True)
            for i in range(2)
        ]
        for t in holders:
            t.start()
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and edge.counters()["requests"] < 2):
            time.sleep(0.01)
        # both slots busy: this one queues (the edge sends NOTHING
        # until tokens flow), then its client leaves without ever
        # reading a byte
        blob = json.dumps({"prompt": [4, 5, 6],
                           "max_new": 10}).encode()
        s = socket.create_connection(edge.addr, timeout=5.0)
        s.sendall(f"POST /v1/generate HTTP/1.1\r\nHost: e\r\n"
                  f"Content-Length: {len(blob)}\r\n\r\n".encode()
                  + blob)
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and edge.counters()["requests"] < 3):
            time.sleep(0.01)
        assert edge.counters()["requests"] == 3
        s.close()                   # the queued client vanishes
        for t in holders:
            t.join(timeout=20.0)
        assert wait_idle(edge, router)
        assert edge.counters()["disconnect_cancels"] == 1
        router.reconcile()
        srv.reconcile()
    finally:
        edge.close()


# ---------------------------------------------------------------------------
# overload backpressure


def test_overload_sheds_429_and_bounds_the_queue(params):
    """An open-loop burst far beyond capacity sheds 429 + Retry-After
    AT THE EDGE; the admission queue never grows past its bound, and
    every admitted request still completes."""
    edge, router, srv = mk_stack(params, max_queue=3)
    depth = [0]
    real_sweep = router.sweep

    def recording_sweep():
        depth[0] = max(depth[0], len(srv.queue))
        return real_sweep()

    edge._sweep_fn = recording_sweep
    try:
        # warm the decode path so the burst meets a live fleet
        stream_generate(edge.addr, [1, 2], 2)
        shape = TrafficShape(out_base=6, out_cap=10)
        burst = open_loop(edge.addr, shape, phases=((200.0, 30),),
                          seed=7)
        rep = slo_report(burst, 1.0)
        assert rep["shed_429"] > 0
        assert rep["completed"] > 0
        assert rep["completed"] + rep["shed_429"] == len(burst)
        sheds = [r for r in burst if r.status == 429]
        assert all(r.retry_after is not None for r in sheds)
        assert depth[0] <= 3
        assert wait_idle(edge, router)
        router.reconcile()
        assert edge.counters()["shed_429"] == rep["shed_429"]
    finally:
        edge.close()


def test_closed_loop_holds_slo_under_fair_load(params):
    """The harness's own sanity bar: closed-loop users (self-
    limiting) against a healthy fleet complete everything, and the
    report's percentiles are well-formed."""
    edge, router, srv = mk_stack(params)
    try:
        shape = TrafficShape(out_base=2, out_cap=6)
        t0 = time.monotonic()
        results = closed_loop(edge.addr, shape, users=3,
                              requests_per_user=2, seed=3)
        rep = slo_report(results, time.monotonic() - t0)
        assert rep["completed"] == 6 == rep["requests"]
        assert rep["sustained_qps"] > 0
        assert rep["p99_ttft_s"] >= rep["p50_ttft_s"] > 0
        assert rep["tokens_streamed"] > 0
    finally:
        edge.close()


# ---------------------------------------------------------------------------
# graceful drain


def test_drain_503_in_flight_finishes_report_lands(params, tmp_path):
    """The SIGTERM sequence without the signal: drain() stops
    admission (newcomers answer 503 + Retry-After), the in-flight
    stream runs to its natural end, wait_drained() goes idle and the
    drain report lands atomically."""
    report = tmp_path / "drain.json"
    edge, router, srv = mk_stack(params,
                                 drain_report_path=str(report))
    try:
        got = {}

        def one(key, **kw):
            got[key] = stream_generate(edge.addr, [1, 2, 3], 8, **kw)

        t = threading.Thread(target=one, args=("inflight",),
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and edge.counters()["requests"] < 1):
            time.sleep(0.01)
        edge.drain(reason="test drain")
        late = stream_generate(edge.addr, [4, 5], 2)
        assert late.status == 503
        assert late.retry_after is not None
        t.join(timeout=20.0)
        assert got["inflight"].outcome == "completed"
        assert got["inflight"].tokens == ref_tokens(params,
                                                    [1, 2, 3], 8)
        assert edge.wait_drained(timeout_s=20.0)
        payload = json.loads(report.read_text())
        assert payload["kind"] == "edge_drain_report"
        assert payload["reason"] == "test drain"
        assert payload["edge"]["shed_503"] == 1
        assert payload["fleet"]["completed"] >= 1
    finally:
        edge.close()


# ---------------------------------------------------------------------------
# the real thing: SIGKILL under live HTTP load


@pytest.mark.fleet
@pytest.mark.slow
@pytest.mark.heavyweight
def test_sigkill_replica_under_live_http_load(params, tmp_path):
    """THE edge chaos bar, on real OS processes: live HTTP streams
    over a 3-replica process fleet while FaultPlan SIGKILLs one
    mid-burst. Every client stream must end in exactly one completed
    outcome with bit-exact greedy tokens — the `sent` high-water mark
    makes redistribution invisible on the wire (a survivor regrows
    the identical prefix; only tokens beyond it are written)."""
    from paddle_tpu.serve.fleet import FleetSupervisor, ReplicaSpec

    art = str(tmp_path / "engine.tar")
    save_tiny_artifact(art, buckets=(16,))
    spec = ReplicaSpec(
        builder="paddle_tpu.testing.fleet:build_tiny_server",
        kwargs=dict(artifact=art, buckets=(16,), max_retries=1),
        env=dict(CHILD_ENV))
    sup = FleetSupervisor(spec, min_replicas=3, max_replicas=3)
    sup.start()
    # LATE-bound sweep: wrap_fleet replaces `sup.sweep`, and the wrap
    # is only installed below once streams are live (the drive thread
    # sweeps from the moment the edge starts — a fixed sweep count
    # would burn down before any client connected)
    edge = HttpEdge(sup.router, sweep_fn=lambda: sup.sweep(),
                    submit_fn=sup.submit,
                    drain_fn=lambda why: sup.drain(reason=why)
                    ).start()
    try:
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, CFG.vocab, (4 + i % 5,)
                               ).astype(np.int32) for i in range(8)]
        results = [None] * len(prompts)

        def client(i):
            results[i] = stream_generate(edge.addr, prompts[i], 8,
                                         timeout_s=120.0)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        # arm the kill only when every replica holds live work, so
        # the victim provably dies with streams in flight
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if (edge.counters()["active_streams"] >= 4
                    and all(r.pending
                            for r in sup.router.replicas)):
                break
            time.sleep(0.005)
        assert all(r.pending for r in sup.router.replicas), \
            "fleet never reached the armed state"
        FaultPlan(fleet_sigkill_at=0,
                  fleet_sigkill_replica=1).wrap_fleet(sup)
        for t in threads:
            t.join(timeout=120.0)
        assert all(r is not None for r in results)
        # exactly one completed outcome per stream, tokens bit-exact
        # with the solo decode: the kill never reached a client
        for p, r in zip(prompts, results):
            assert r.status == 200 and r.outcome == "completed"
            assert r.tokens == ref_tokens(params, p, 8)
        sup.reconcile()
        c = sup.router.counters()
        assert c["replicas_lost"] == 1
        assert c["redistributed"] >= 1
        assert c["completed"] == len(prompts)
        assert c["failed"] == 0 and c["shed"] == 0
        # the supervisor repaired the fleet back to its floor
        assert sup.counters()["procs_alive"] == 3
    finally:
        edge.close()
        sup.shutdown(drain=False)
