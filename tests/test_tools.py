"""Tooling parity: image preprocessing + torch weight import
(reference: python/paddle/utils/{image_util,image_multiproc,
torch2paddle}.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.data import image as I
from paddle_tpu.nn.module import ShapeSpec


def _img(h=40, w=60, c=3, seed=0):
    return np.random.RandomState(seed).randint(0, 256, (h, w, c)).astype(
        np.uint8)


def test_resize_short_keeps_aspect():
    img = _img(40, 60)
    out = I.resize_short(img, 20)
    assert out.shape == (20, 30, 3)
    out = I.resize_short(_img(60, 40), 20)
    assert out.shape == (30, 20, 3)


def test_crops_and_flip():
    img = _img(32, 32)
    c = I.center_crop(img, 16)
    assert c.shape == (16, 16, 3)
    np.testing.assert_array_equal(c, img[8:24, 8:24])
    rng = np.random.RandomState(0)
    r = I.random_crop(img, 16, rng)
    assert r.shape == (16, 16, 3)
    with pytest.raises(ValueError):
        I.center_crop(img, 64)
    flipped = img[:, ::-1]
    seen = {I.random_flip(img, np.random.RandomState(s)).tobytes()
            for s in range(8)}
    assert img.tobytes() in seen and flipped.tobytes() in seen


def test_normalize_and_oversample():
    img = _img(24, 24)
    n = I.normalize(img, mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25))
    assert n.dtype == np.float32
    assert abs(float(n.max())) <= 2.01
    crops = I.oversample(img, 16)
    assert crops.shape == (10, 16, 16, 3)
    # second half mirrors the first
    np.testing.assert_array_equal(crops[5], crops[0][:, ::-1])


def test_transformer_pipeline_train_vs_eval():
    t_train = I.Transformer(resize=32, crop=24, is_train=True, seed=0)
    t_eval = I.Transformer(resize=32, crop=24, is_train=False)
    img = _img(48, 64)
    a = t_train(img)
    b = t_eval(img)
    assert a.shape == (24, 24, 3) and b.shape == (24, 24, 3)
    # eval is deterministic
    np.testing.assert_array_equal(b, t_eval(img))


def test_transformed_reader_multiproc():
    from paddle_tpu.data import reader as R

    imgs = [( _img(seed=s), s % 3) for s in range(12)]
    t = I.Transformer(resize=32, crop=24, is_train=False)
    rd = I.transformed_reader(lambda: iter(imgs), t, process_num=3)
    got = sorted(rd(), key=lambda s: s[1] * 100 + int(s[0].sum() % 97))
    assert len(list(got)) == 12
    for img, label in got:
        assert img.shape == (24, 24, 3)


# ---- torch import ----------------------------------------------------


@pytest.mark.slow


def test_torch_import_lenet_forward_agrees():
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    from paddle_tpu.utils import torch_import as TI

    torch.manual_seed(0)
    tmodel = tnn.Sequential(
        tnn.Conv2d(1, 6, 5, padding=2), tnn.BatchNorm2d(6), tnn.ReLU(),
        tnn.MaxPool2d(2),
        tnn.Conv2d(6, 16, 5, padding=2), tnn.BatchNorm2d(16), tnn.ReLU(),
        tnn.MaxPool2d(2),
        tnn.Flatten(), tnn.Linear(16 * 7 * 7, 32), tnn.ReLU(),
        tnn.Linear(32, 10),
    ).eval()

    model = nn.Sequential([
        nn.Conv2D(6, 5, padding=(2, 2), use_bias=True, name="c1"),
        nn.BatchNorm(activation="relu", name="b1"),
        nn.MaxPool2D(2, name="p1"),
        nn.Conv2D(16, 5, padding=(2, 2), use_bias=True, name="c2"),
        nn.BatchNorm(activation="relu", name="b2"),
        nn.MaxPool2D(2, name="p2"),
        nn.Flatten(name="flat"),
        nn.Dense(32, activation="relu", name="fc1"),
        nn.Dense(10, name="fc2"),
    ])
    params, state = model.init(jax.random.key(0), ShapeSpec((2, 28, 28, 1)))
    params, state = TI.import_into(model, params, state, tmodel)

    x = np.random.RandomState(1).rand(2, 28, 28, 1).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    # NHWC flatten order differs from torch's NCHW flatten — compare up
    # to the first Linear only if orders matched; they don't, so instead
    # verify the CONV tower agrees, then the full net via re-permuted fc
    conv_tower = nn.Sequential(model.layers[:6])
    tp = {k: params[k] for k in ("c1", "b1", "c2", "b2") if k in params}
    ts = {k: state[k] for k in ("b1", "b2") if k in state}
    ours_tower, _ = conv_tower.apply(tp, ts, jnp.asarray(x))
    with torch.no_grad():
        want_tower = tnn.Sequential(*list(tmodel.children())[:8])(
            torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(
        np.asarray(ours_tower).transpose(0, 3, 1, 2), want_tower,
        rtol=2e-4, atol=2e-4)


def test_torch_import_mlp_exact():
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    from paddle_tpu.utils import torch_import as TI

    torch.manual_seed(1)
    tmodel = tnn.Sequential(
        tnn.Linear(12, 8), tnn.ReLU(), tnn.Linear(8, 3)).eval()
    model = nn.Sequential([
        nn.Dense(8, activation="relu", name="fc1"),
        nn.Dense(3, name="fc2"),
    ])
    params, state = model.init(jax.random.key(0), ShapeSpec((4, 12)))
    params, state = TI.import_into(model, params, state, tmodel)
    x = np.random.RandomState(2).rand(4, 12).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.from_numpy(x)).numpy()
    ours, _ = model.apply(params, state, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(ours), want, rtol=1e-5,
                               atol=1e-5)


def test_torch_import_embedding_and_mismatch_errors():
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    from paddle_tpu.utils import torch_import as TI

    temb = tnn.Embedding(11, 5)
    model = nn.Sequential([nn.Embedding(11, 5, name="emb")])
    params, state = model.init(jax.random.key(0),
                               ShapeSpec((2, 3), jnp.int32))
    params, state = TI.import_into(model, params, state,
                                   tnn.Sequential(temb))
    np.testing.assert_allclose(
        np.asarray(params["emb"]["table"]),
        temb.weight.detach().numpy(), rtol=1e-6)

    # count mismatch raises with a clear message
    with pytest.raises(Exception, match="parameterized layers"):
        TI.import_into(model, params, state,
                       tnn.Sequential(temb, tnn.Linear(5, 2)))
