"""Optimizer tests: each optimizer decreases a quadratic, matches known
single-step math (the FirstOrderOptimizer update rules, reference:
paddle/parameter/FirstOrderOptimizer.h)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optim
from paddle_tpu.optim import schedules


def quad_loss(params):
    return 0.5 * jnp.sum(jnp.square(params["w"] - 3.0))


ALL_OPTS = [
    ("sgd", dict(learning_rate=0.1)),
    ("momentum", dict(learning_rate=0.1, mu=0.9)),
    ("adagrad", dict(learning_rate=0.5)),
    ("decayed_adagrad", dict(learning_rate=0.3)),
    ("adadelta", dict(rho=0.9)),
    ("rmsprop", dict(learning_rate=0.05)),
    ("adam", dict(learning_rate=0.2)),
    ("adamax", dict(learning_rate=0.2)),
    ("ftrl", dict(learning_rate=0.5)),
    ("lbfgs", dict(learning_rate=0.5, history=5)),
    ("proximal_gd", dict(learning_rate=0.1)),
]


@pytest.mark.parametrize("name,kwargs", ALL_OPTS)
def test_decreases_quadratic(name, kwargs):
    opt = optim.get(name, **kwargs)
    params = {"w": jnp.asarray([0.0, 1.0, 5.0])}
    opt_state = opt.init(params)
    step = jnp.zeros((), jnp.int32)
    loss0 = float(quad_loss(params))

    @jax.jit
    def run(params, opt_state):
        def body(carry, i):
            params, opt_state = carry
            grads = jax.grad(quad_loss)(params)
            params, opt_state = opt.update(grads, opt_state, params, step + i)
            return (params, opt_state), None

        (params, opt_state), _ = jax.lax.scan(
            body, (params, opt_state), jnp.arange(300)
        )
        return params, opt_state

    params, opt_state = run(params, opt_state)
    assert float(quad_loss(params)) < loss0 * 0.5, name


def test_sgd_exact_step():
    opt = optim.sgd(0.1)
    params = {"w": jnp.asarray([1.0])}
    grads = {"w": jnp.asarray([2.0])}
    new_params, _ = opt.update(grads, opt.init(params), params, jnp.zeros((), jnp.int32))
    np.testing.assert_allclose(new_params["w"], [0.8], rtol=1e-6)


def test_momentum_accumulates():
    opt = optim.momentum(0.1, mu=0.5)
    params = {"w": jnp.asarray([0.0])}
    st = opt.init(params)
    g = {"w": jnp.asarray([1.0])}
    s = jnp.zeros((), jnp.int32)
    params, st = opt.update(g, st, params, s)       # v=1, w=-0.1
    np.testing.assert_allclose(params["w"], [-0.1], rtol=1e-6)
    params, st = opt.update(g, st, params, s)       # v=1.5, w=-0.25
    np.testing.assert_allclose(params["w"], [-0.25], rtol=1e-6)


def test_adam_bias_correction_first_step():
    opt = optim.adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=0.0)
    params = {"w": jnp.asarray([1.0])}
    g = {"w": jnp.asarray([0.5])}
    new_params, _ = opt.update(g, opt.init(params), params, jnp.zeros((), jnp.int32))
    # first adam step with bias correction moves by ~lr in grad direction
    np.testing.assert_allclose(new_params["w"], [1.0 - 0.001], rtol=1e-4)


def test_lbfgs_beats_sgd_on_rosenbrock():
    """The point of (L-)BFGS: curvature exploitation on an ill-
    conditioned deterministic objective. Same step budget, same lr
    family — L-BFGS must land far closer to the optimum than SGD."""
    def rosen(params):
        x = params["x"]
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                       + (1.0 - x[:-1]) ** 2)

    def run(opt, steps=200):
        params = {"x": jnp.zeros((4,))}
        st = opt.init(params)

        @jax.jit
        def body(carry, i):
            params, st = carry
            g = jax.grad(rosen)(params)
            params, st = opt.update(g, st, params, i)
            return (params, st), None

        (params, st), _ = jax.lax.scan(body, (params, st),
                                       jnp.arange(steps))
        return float(rosen(params))

    l_lbfgs = run(optim.lbfgs(learning_rate=0.1, history=10))
    l_sgd = run(optim.sgd(learning_rate=1e-3))  # larger lr diverges
    assert np.isfinite(l_lbfgs)
    assert l_lbfgs < l_sgd * 0.2, (l_lbfgs, l_sgd)


def test_lbfgs_quadratic_near_newton():
    """On a diagonal quadratic with lr=1, L-BFGS approaches the Newton
    step once history accumulates: a handful of iterations should reach
    machine-level loss where plain GD at a stable lr cannot."""
    scales = jnp.asarray([1.0, 10.0, 100.0])

    def loss(params):
        return 0.5 * jnp.sum(scales * jnp.square(params["w"] - 1.0))

    opt = optim.lbfgs(learning_rate=1.0, history=10)
    params = {"w": jnp.zeros((3,))}
    st = opt.init(params)
    for i in range(30):
        g = jax.grad(loss)(params)
        params, st = opt.update(g, st, params, jnp.asarray(i))
    assert float(loss(params)) < 1e-6, float(loss(params))


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_owlqn_produces_exact_zeros():
    """OWL-QN on a lasso-style objective: the orthant projection must
    drive truly-irrelevant coordinates to EXACT zero (the reference's
    op_fix_omega_signs semantics), while fitting the relevant ones."""
    r = np.random.RandomState(0)
    A = jnp.asarray(r.randn(64, 8), jnp.float32)
    w_true = jnp.asarray([2.0, -1.5, 0, 0, 0, 0, 0, 0], jnp.float32)
    b = A @ w_true

    def data_loss(params):
        return 0.5 * jnp.mean((A @ params["w"] - b) ** 2)

    l1 = 0.05
    opt = optim.owlqn(learning_rate=0.5, l1=l1, history=10)
    params = {"w": jnp.zeros((8,))}
    st = opt.init(params)
    for i in range(200):
        g = jax.grad(data_loss)(params)
        params, st = opt.update(g, st, params, jnp.asarray(i))
    w = np.asarray(params["w"])
    # relevant coordinates recovered (shrunk slightly by l1)
    assert abs(w[0] - 2.0) < 0.2 and abs(w[1] + 1.5) < 0.2, w
    # irrelevant coordinates are EXACTLY zero, not merely small
    assert (w[2:] == 0.0).sum() >= 4, w
    # and the regularized objective actually decreased vs the origin
    def full(params):
        return float(data_loss(params) + l1 * jnp.sum(jnp.abs(params["w"])))
    assert full(params) < full({"w": jnp.zeros((8,))})


def test_owlqn_validates_l1():
    with pytest.raises(ValueError, match="l1"):
        optim.owlqn(l1=0.0)


def test_clip_global_norm():
    grads = {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])}
    clipped, norm = optim.clip_by_global_norm(grads, 1.0)
    np.testing.assert_allclose(float(norm), 5.0, rtol=1e-6)
    total = np.sqrt(sum(float(jnp.sum(jnp.square(v))) for v in clipped.values()))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_weight_decay_chain():
    opt = optim.chain(optim.sgd(0.1), weight_decay=0.5)
    params = {"w": jnp.asarray([2.0])}
    grads = {"w": jnp.asarray([0.0])}
    new_params, _ = opt.update(grads, opt.init(params), params, jnp.zeros((), jnp.int32))
    np.testing.assert_allclose(new_params["w"], [2.0 - 0.1 * 0.5 * 2.0], rtol=1e-6)


class TestSchedules:
    def test_constant(self):
        s = schedules.constant(0.5)
        assert float(s(jnp.asarray(100))) == 0.5

    def test_discrete_exp(self):
        s = schedules.discrete_exp(1.0, 0.5, 10)
        np.testing.assert_allclose(float(s(jnp.asarray(0))), 1.0)
        np.testing.assert_allclose(float(s(jnp.asarray(10))), 0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(25))), 0.25)

    def test_linear(self):
        s = schedules.linear_decay(1.0, 0.01, 0.1)
        np.testing.assert_allclose(float(s(jnp.asarray(50))), 0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(1000))), 0.1)

    def test_piecewise(self):
        s = schedules.piecewise([10, 20], [1.0, 0.1, 0.01])
        assert float(s(jnp.asarray(5))) == 1.0
        assert float(s(jnp.asarray(15))) == pytest.approx(0.1)
        assert float(s(jnp.asarray(25))) == pytest.approx(0.01)

    def test_poly(self):
        s = schedules.poly(1.0, 1.0, 1.0)
        np.testing.assert_allclose(float(s(jnp.asarray(1))), 0.5)


class TestModelAverage:
    def test_average(self):
        from paddle_tpu.optim import average

        params = {"w": jnp.asarray([0.0])}
        st = average.init(params)
        for v in [1.0, 2.0, 3.0]:
            st = average.accumulate(st, {"w": jnp.asarray([v])})
        avg = average.averaged_params(st, params)
        np.testing.assert_allclose(avg["w"], [2.0], rtol=1e-6)

    def test_empty_falls_back(self):
        from paddle_tpu.optim import average

        params = {"w": jnp.asarray([7.0])}
        st = average.init(params)
        avg = average.averaged_params(st, params)
        np.testing.assert_allclose(avg["w"], [7.0])
