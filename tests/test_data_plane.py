"""Zero-copy data plane: shared-memory KV arena + batched control RPC.

The tentpole claim (ROADMAP, docs/SERVING.md "Zero-copy data plane"):
a KV migration's page bytes move through a `serve.shm_arena.ShmArena`
both replicas map — the control frame carries only a picklable ticket
(tag + segment ids + sizes) — and the arena's on-shared-memory
ownership ledger makes the path crash-safe: a SIGKILL on either side
of a transfer leaves segments a reclaim sweep provably frees, never a
wrong answer and never a permanent /dev/shm leak. Proven here at
every layer:

- arena unit surface: scatter/gather round-trips (zero-copy within a
  segment, counted assembly across), the free-list cap, idempotent
  free, attach-by-name with a version gate, `reconcile()` catching
  both leaks and phantom expectations;
- orphan reclamation under REAL death (forked children SIGKILL
  themselves through `FaultPlan.wrap_arena` mid-scatter / mid-adopt):
  dead-owner segments reclaim, live-owner segments survive the same
  sweep, and a reclaimed ticket is refused as STALE by `gather` —
  exactly-once never depends on sweep timing;
- the multi-part wire framing the control plane rides
  (`wire.send_frames`/`recv_frames`): legacy interop, the 1 GiB cap
  enforced across the SUM of parts before allocation, and the
  truncated-frame regression (a peer dying after the header is a dead
  stream, not short data);
- disaggregated-fleet parity over the arena: greedy and speculative
  decode stay bit-exact vs solo `generate()` through an arena-backed
  migration, every ACK frees its ticket, and the pickle-fallback arm
  (`FaultPlan(arena_error_at=...)`) produces the SAME tokens with a
  `data_plane_fallbacks` counter + flight event — never a wrong
  answer;
- batched control RPC (`transport.ProcessReplica`): handoff ACKs
  defer onto the next sweep frame, `rpc_frames_coalesced` counts the
  frames that never hit the wire, and per-stream `partial_tokens`
  polls are served from the partials block every sweep reply already
  carries (the PR17 edge's poll loop stops costing one RPC per token);
- real-process SIGKILL chaos (slow lane): source killed mid-scatter,
  destination killed mid-adopt, and the supervisor itself SIGKILLed —
  each ends with exactly one outcome per request, zero leaked
  segments after the reclaim sweep the supervisor's own `sweep()`
  drives, and bit-exact completions on the survivors.
"""

import multiprocessing
import os
import signal
import socket
import struct
import time

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.obs.flight import FlightRecorder
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.fleet import (FleetSupervisor, ReplicaProcess,
                                    ReplicaSpec)
from paddle_tpu.serve.router import ServingRouter
from paddle_tpu.serve.server import (MigrationRefusedError,
                                     ServingServer)
from paddle_tpu.serve.shm_arena import (ArenaError, ArenaFull,
                                        ArenaUnavailable, ShmArena,
                                        _pid_alive, attach_cached)
from paddle_tpu.serve.transport import (ProcessReplica, ReplicaClient,
                                        ReplicaTransportServer)
from paddle_tpu.testing.faults import FaultPlan, ManualClock
from paddle_tpu.wire import (MAX_PARTS, recv_frames, send_frame,
                             send_frames)

pytestmark = [pytest.mark.data]

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")
BUCKETS = (16,)

#: env every replica child gets (the parent conftest pins cpu + 8
#: virtual devices; children re-assert cpu and need only 1)
CHILD_ENV = {"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def engines(params):
    """Three warmed engines (prefill + two decode), migration bodies
    pre-compiled by one throwaway fleet pass so the per-test call
    phase pays traffic, not compiles."""
    engs = [DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4,
                         prefill_chunk=8)
            for _ in range(3)]
    warm = np.arange(11, dtype=np.int32)
    for e in engs:
        e.serve([warm], max_new=2, buckets=BUCKETS)
    clk = ManualClock()
    router = _make_fleet(engs, clk, None)
    router.submit(np.arange(1, 12, dtype=np.int32), max_new=2)
    router.run()
    return engs


def _make_fleet(engines, clk, arena, *,
                roles=("prefill", "decode", "decode"), wrap=None,
                speculative=False, flight=None, **router_kw):
    """Disaggregated fleet with the shared arena handed to every
    server as a live OBJECT (in-process replicas share one mapping —
    attach-by-name is the cross-process path, covered below)."""
    servers = []
    for i, (eng, role) in enumerate(zip(engines, roles)):
        if wrap and wrap.get(i) is not None:
            eng = wrap[i](eng)
        servers.append(ServingServer(
            eng, role=role, max_queue=16, clock=clk, buckets=BUCKETS,
            max_retries=2, data_plane=arena, flight=flight,
            speculative=(speculative and role == "decode")))
    return ServingRouter(servers, clock=clk, probe_interval_s=1e9,
                         **router_kw)


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def prompts_for(n, seed, lo=9, hi=14):
    r = np.random.RandomState(seed)
    return [r.randint(1, 60, (int(r.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


@pytest.fixture
def mk_arena():
    made = []

    def make(**kw):
        a = ShmArena(**kw)
        made.append(a)
        return a

    yield make
    for a in made:
        a.close(destroy=True)


# ---------------------------------------------------------------------------
# arena unit surface (no jax, no engines)


class TestArena:
    def test_scatter_gather_roundtrip_zero_copy(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        parts = [b"hello, pages",
                 np.arange(64, dtype=np.int32).tobytes()]
        t = arena.scatter(parts)
        assert t["arena"] == arena.name
        assert t["nbytes"] == sum(len(p) for p in parts)
        got = arena.gather(t)
        assert [bytes(g) for g in got] == [bytes(p) for p in parts]
        # both parts lie inside one segment: pure views, nothing
        # assembled
        assert arena.bytes_gather_copied == 0
        assert arena.segments_live() == len(t["segs"]) == 1
        arena.adopt(t)
        # the ACK path: free returns the segments and replays as a
        # no-op (the router may resend a lost ACK)
        assert arena.free(t) == 1
        assert arena.free(t) == 0
        assert arena.segments_live() == 0
        c = arena.counters()
        assert c["arena_scatters"] == 1
        assert c["arena_adoptions"] == 1
        assert c["arena_frees"] == 1
        assert c["arena_bytes_scattered"] == t["nbytes"]
        arena.reconcile()

    def test_segment_spanning_part_is_assembled(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        blob = bytes(range(256)) * 10           # 2560 B -> 3 segments
        t = arena.scatter([blob])
        assert len(t["segs"]) == 3
        [got] = arena.gather(t)
        assert bytes(got) == blob
        assert arena.bytes_gather_copied == len(blob)
        arena.free(t)
        arena.reconcile()

    def test_arena_full_is_transient(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        t1 = arena.scatter([b"x" * 7000])       # 7 of 8 segments
        with pytest.raises(ArenaFull):
            arena.scatter([b"y" * 2048])
        # nothing was half-claimed by the refusal
        assert arena.segments_live() == 7
        arena.free(t1)
        t2 = arena.scatter([b"y" * 2048])
        arena.free(t2)
        arena.reconcile()

    def test_attach_by_name_and_version_gate(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=4)
        other = ShmArena(arena.name, create=False)
        t = arena.scatter([b"cross-process bytes"])
        [got] = other.gather(t)
        assert bytes(got) == b"cross-process bytes"
        other.adopt(t)                  # the destination-side stamp
        assert arena.free(t) == 1       # the SOURCE owns the release
        other.close()
        h1 = attach_cached(arena.name)
        assert attach_cached(arena.name) is h1   # one handle/process
        h1.close()
        with pytest.raises(ArenaUnavailable):
            ShmArena("pt-arena-no-such-arena", create=False)
        # a same-name arena from an incompatible build is refused,
        # never misread
        arena._led[1] = 999
        with pytest.raises(ArenaUnavailable, match="version mismatch"):
            ShmArena(arena.name, create=False)
        arena._led[1] = ShmArena.VERSION

    def test_reconcile_catches_leak_and_phantom(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=4)
        t = arena.scatter([b"z" * 10])
        with pytest.raises(AssertionError, match="arena leak"):
            arena.reconcile()           # live ticket nobody expected
        arena.reconcile([t["tag"]])
        arena.free(t)
        with pytest.raises(AssertionError, match="lost"):
            arena.reconcile([t["tag"]])   # expectation without segments
        arena.reconcile()


# ---------------------------------------------------------------------------
# orphan reclamation under real death (forked children, SIGKILL)


def _child_scatter_then_exit(arena, conn, blob):
    # forked children reuse the INHERITED handle: fork shares the
    # mapping, and attaching by name would double-register the arena
    # with the parent's resource tracker
    t = arena.scatter([blob])
    conn.send(t)
    conn.close()
    os._exit(0)                 # dies un-ACKed: its segments leak


def _child_scatter_killed(arena, blob, plan_kwargs):
    FaultPlan(**plan_kwargs).wrap_arena(arena)
    arena.scatter([blob])       # SIGKILLs itself mid-write
    os._exit(1)                 # pragma: no cover - never reached


def _child_adopt_killed(arena, ticket, plan_kwargs):
    FaultPlan(**plan_kwargs).wrap_arena(arena)
    arena.adopt(ticket)         # SIGKILLs itself mid-stamp
    os._exit(1)                 # pragma: no cover - never reached


def _fork(fn, *args):
    p = multiprocessing.get_context("fork").Process(target=fn,
                                                    args=args)
    p.start()
    return p


class TestOrphanReclaim:
    # fork-based children touch ONLY the arena (numpy over shm) and
    # os._exit before any JAX work, so jax's fork-vs-threads warning
    # does not apply here
    pytestmark = [
        pytest.mark.faults,
        pytest.mark.filterwarnings("ignore:os.fork:RuntimeWarning")]

    def test_stale_ticket_refused_after_reclaim(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        parent, child = multiprocessing.get_context("fork").Pipe()
        p = _fork(_child_scatter_then_exit, arena, child, b"k" * 100)
        ticket = parent.recv()
        p.join(10.0)
        assert p.exitcode == 0
        c = arena.counters()
        assert c["arena_segments_leaked"] == len(ticket["segs"]) == 1
        assert arena.reclaim_orphans() == 1
        # the ticket outlived its segments: gather must refuse, never
        # hand back whatever lands there next
        with pytest.raises(ArenaError, match="stale ticket"):
            arena.gather(ticket)
        assert arena.free(ticket) == 0          # idempotent with reclaim
        arena.reconcile()

    def test_source_killed_mid_scatter_leaks_all_claimed(self,
                                                         mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        # 1500 B claims 2 segments up front; the kill after the FIRST
        # write must leak BOTH (claimed is owned, written or not)
        p = _fork(_child_scatter_killed, arena, b"s" * 1500,
                  dict(arena_kill_scatter_at=0))
        p.join(10.0)
        assert p.exitcode == -signal.SIGKILL
        c = arena.counters()
        assert c["arena_segments_leaked"] == 2
        assert arena.reclaim_orphans() == 2
        assert arena.reclaim_orphans() == 0     # sweep replay: no-op
        arena.reconcile()

    def test_destination_killed_mid_adopt_costs_nothing(self,
                                                        mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        t = arena.scatter([b"q" * 1500])        # 2 segments
        # kill before the SECOND stamp: a mixed ledger (one ADOPTED
        # with a dead adopter, one still INFLIGHT) — but the live
        # SOURCE owns both, so nothing leaks and nothing reclaims
        p = _fork(_child_adopt_killed, arena, t,
                  dict(arena_kill_adopt_at=1))
        p.join(10.0)
        assert p.exitcode == -signal.SIGKILL
        c = arena.counters()
        assert c["arena_segments_leaked"] == 0
        assert arena.reclaim_orphans() == 0
        [got] = arena.gather(t)                 # bytes still whole
        assert bytes(got) == b"q" * 1500
        assert arena.free(t) == 2               # the normal ACK path
        arena.reconcile()

    def test_both_sides_killed_one_sweep_reclaims_all(self, mk_arena):
        arena = mk_arena(seg_size=1024, n_segs=8)
        parent, child = multiprocessing.get_context("fork").Pipe()
        pa = _fork(_child_scatter_killed, arena, b"a" * 1500,
                   dict(arena_kill_scatter_at=0))     # leaks 2
        pb = _fork(_child_scatter_then_exit, arena, child, b"b" * 100)
        dead_ticket = parent.recv()                   # leaks 1
        mine = arena.scatter([b"m" * 10])       # must SURVIVE the sweep
        pa.join(10.0)
        pb.join(10.0)
        assert (pa.exitcode, pb.exitcode) == (-signal.SIGKILL, 0)
        assert arena.counters()["arena_segments_leaked"] == 3
        assert arena.reclaim_orphans() == 3
        arena.reconcile([mine["tag"]])
        with pytest.raises(ArenaError, match="stale ticket"):
            arena.gather(dead_ticket)
        [got] = arena.gather(mine)
        assert bytes(got) == b"m" * 10
        arena.free(mine)
        arena.reconcile()


# ---------------------------------------------------------------------------
# multi-part wire framing (the control plane's transport idiom)


class TestMultiPartWire:
    def test_roundtrip_and_legacy_interop(self):
        a, b = socket.socketpair()
        try:
            parts = [b"head", b"", b"x" * 70000]
            send_frames(a, parts)
            assert recv_frames(b) == parts
            # a legacy single frame arrives as a one-element list:
            # old clients keep working against new servers
            send_frame(a, b"legacy")
            assert recv_frames(b) == [b"legacy"]
        finally:
            a.close()
            b.close()

    def test_truncated_multipart_frame_is_a_dead_stream(self):
        # regression: the peer dies after the header promised 12
        # payload bytes but delivered 3 — the receiver must raise,
        # not hang and not deliver short data as a frame
        a, b = socket.socketpair()
        try:
            hdr = struct.pack("<II", 0xFFFFFFFF, 2)
            hdr += struct.pack("<2Q", 5, 7)
            a.sendall(hdr + b"abc")
            a.close()
            with pytest.raises(ConnectionError, match="mid-frame"):
                recv_frames(b)
        finally:
            b.close()

    def test_summed_cap_enforced_before_allocation(self):
        a, b = socket.socketpair()
        try:
            # every part is under the cap; the SUM is over it — the
            # header alone is refused, no payload byte was ever sent
            # so nothing could have been allocated
            hdr = struct.pack("<II", 0xFFFFFFFF, 3)
            hdr += struct.pack("<3Q", 500, 500, 500)
            a.sendall(hdr)
            with pytest.raises(ConnectionError, match="exceeds"):
                recv_frames(b, max_frame=1024)
        finally:
            a.close()
            b.close()

    def test_part_count_cap(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<II", 0xFFFFFFFF, MAX_PARTS + 1))
            with pytest.raises(ConnectionError, match="part cap"):
                recv_frames(b)
        finally:
            a.close()
            b.close()

    def test_sender_refuses_oversized_sum(self):
        a, b = socket.socketpair()
        try:
            with pytest.raises(ValueError, match="multi-part frame"):
                send_frames(a, [b"x" * 600, b"y" * 600],
                            max_frame=1024)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# disaggregated fleet over the arena (in-process, bit-exact parity)


class TestArenaFleet:
    def test_greedy_parity_zero_copies_acked_free(self, params,
                                                  engines, mk_arena):
        arena = mk_arena(seg_size=4096, n_segs=32)
        clk = ManualClock()
        router = _make_fleet(engines, clk, arena)
        prompts = prompts_for(3, seed=7)
        ids = [router.submit(p, max_new=5) for p in prompts]
        res = router.run()
        for p, rr in zip(prompts, ids):
            assert res[rr].outcome == "completed"
            assert res[rr].tokens == ref_tokens(params, p, 5)
            assert res[rr].replica in (1, 2)    # landed on decode tier
        c = router.counters()
        assert c["migrations"] == 3
        assert c["fleet_data_plane_fallbacks"] == 0
        # every migration moved its bytes through the arena exactly
        # once, and every ACK freed its ticket
        assert arena.scatters == 3 and arena.adoptions == 3
        assert arena.frees == 3
        assert arena.bytes_scattered > 0
        assert arena.bytes_gathered == arena.bytes_scattered
        assert arena.segments_live() == 0
        arena.reconcile()
        router.reconcile()

    @pytest.mark.slow  # tier-1 budget guard: the data lane runs it
    def test_speculative_parity_over_arena(self, params, engines,
                                           mk_arena):
        arena = mk_arena(seg_size=4096, n_segs=32)
        clk = ManualClock()
        router = _make_fleet(engines, clk, arena, speculative=True)
        prompts = prompts_for(2, seed=11)
        ids = [router.submit(p, max_new=6) for p in prompts]
        res = router.run()
        for p, rr in zip(prompts, ids):
            assert res[rr].outcome == "completed"
            assert res[rr].tokens == ref_tokens(params, p, 6)
        c = router.counters()
        assert c["migrations"] == 2
        assert c["fleet_spec_rounds"] > 0
        assert c["fleet_data_plane_fallbacks"] == 0
        assert arena.scatters == 2 and arena.segments_live() == 0
        arena.reconcile()
        router.reconcile()

    def test_export_scatters_once_and_ack_frees(self, params, engines,
                                                mk_arena):
        arena = mk_arena(seg_size=4096, n_segs=16)
        srv = ServingServer(engines[0], role="prefill", buckets=BUCKETS,
                            clock=lambda: 0.0, data_plane=arena)
        rid = srv.submit(np.arange(1, 12, dtype=np.int32), max_new=4)
        srv.run()
        p1 = srv.export_request(rid)
        assert p1["kv"] is None                 # bytes never pickled
        t1 = p1["kv_ref"]["ticket"]
        # an RPC retry (or a retargeted destination) re-exports the
        # SAME ticket — never a second scatter to leak
        p2 = srv.export_request(rid)
        assert p2["kv_ref"]["ticket"] == t1
        assert arena.scatters == 1
        # handoff ledger == arena live tags (the reconcile join)
        assert arena.live_tags(os.getpid()) == {t1["tag"]}
        srv.handoff_complete(rid)
        assert arena.segments_live() == 0
        assert srv.counters()["data_plane_fallbacks"] == 0
        srv.reconcile()
        arena.reconcile()


class TestArenaFleetChaos:
    pytestmark = [pytest.mark.faults]

    def test_fallback_parity_bit_exact(self, params, engines,
                                       mk_arena):
        """The arena refuses the FIRST scatter: the payload rides the
        legacy pickle path with a counter + flight event and the SAME
        tokens; the next migration is back on the zero-copy path."""
        arena = mk_arena(seg_size=4096, n_segs=32)
        plan = FaultPlan(arena_error_at=0)
        plan.wrap_arena(arena)
        clk = ManualClock()
        flight = FlightRecorder(clock=clk)
        router = _make_fleet(engines, clk, arena, flight=flight)
        prompt = np.arange(2, 14, dtype=np.int32)
        rr = router.submit(prompt, max_new=6)
        res = router.run()
        assert plan.count("arenaerr") == 1
        assert res[rr].outcome == "completed"
        assert res[rr].tokens == ref_tokens(params, prompt, 6)
        c = router.counters()
        assert c["migrations"] == 1
        assert c["fleet_data_plane_fallbacks"] == 1
        assert arena.scatters == 0 and arena.segments_live() == 0
        falls = [e for e in flight.events()
                 if e["kind"] == "data_plane" and e["name"] == "fallback"]
        assert len(falls) == 1 and falls[0]["where"] == "scatter"
        # the fault was transient: the next migration scatters again
        p2 = np.arange(4, 16, dtype=np.int32)
        r2 = router.submit(p2, max_new=4)
        res = router.run()
        assert res[r2].outcome == "completed"
        assert res[r2].tokens == ref_tokens(params, p2, 4)
        assert arena.scatters == 1 and arena.segments_live() == 0
        assert router.counters()["fleet_data_plane_fallbacks"] == 1
        arena.reconcile()
        router.reconcile()

    def test_gather_failure_refuses_then_cancels_bit_exact(
            self, params, engines, mk_arena):
        """A ticket reclaimed between export and import (the orphan
        sweep racing a slow destination): the import REFUSES
        transiently — the destination never admits — and the source's
        cancel path decodes locally from its still-pinned copy."""
        arena = mk_arena(seg_size=4096, n_segs=16)
        src = ServingServer(engines[0], role="prefill", buckets=BUCKETS,
                            clock=lambda: 0.0, data_plane=arena)
        prompt = np.arange(3, 14, dtype=np.int32)
        rid = src.submit(prompt, max_new=4)
        src.run()
        payload = src.export_request(rid)
        arena.free(payload["kv_ref"]["ticket"])   # the simulated race
        dst = ServingServer(engines[1], role="decode", buckets=BUCKETS,
                            clock=lambda: 0.0, data_plane=arena)
        with pytest.raises(MigrationRefusedError, match="gather"):
            dst.import_request(payload)
        assert dst.counters()["data_plane_fallbacks"] == 1
        assert dst.stats.requests == 0            # never admitted
        dst.reconcile()
        src.cancel_handoff(rid)
        res = src.run()
        assert res[rid].outcome == "completed"
        assert res[rid].tokens == ref_tokens(params, prompt, 4)
        src.reconcile()
        arena.reconcile()

    def test_destination_death_retargets_the_same_ticket(
            self, params, engines, mk_arena):
        """The first destination dies mid-import: the retarget
        re-exports the SAME ticket (one scatter total), the survivor
        gathers the same segments, and the final ACK frees them."""
        arena = mk_arena(seg_size=4096, n_segs=32)
        clk = ManualClock()
        plan = FaultPlan(router_kill_import_at=0)
        router = _make_fleet(
            engines, clk, arena,
            wrap={1: lambda e: plan.wrap_replica_engine(e, clock=clk)})
        prompt = np.arange(2, 14, dtype=np.int32)
        rr = router.submit(prompt, max_new=6)
        res = router.run()
        assert plan.count("importkill") == 1
        assert res[rr].outcome == "completed"
        assert res[rr].tokens == ref_tokens(params, prompt, 6)
        assert res[rr].replica == 2         # the surviving destination
        c = router.counters()
        assert c["replicas_lost"] == 1
        assert c["migration_retargets"] == 1
        assert arena.scatters == 1          # the ticket was REUSED
        assert arena.segments_live() == 0
        arena.reconcile()
        router.reconcile()


# ---------------------------------------------------------------------------
# batched control RPC (ProcessReplica over an in-thread transport)


@pytest.fixture
def transport(engines):
    srv = ServingServer(engines[0], max_queue=8, max_retries=2,
                        buckets=BUCKETS)
    ts = ReplicaTransportServer(srv).start()
    client = ReplicaClient(ts.addr, connect_timeout=2.0,
                           io_timeout=30.0)
    yield ts, srv, client
    ts.shutdown()


class TestBatchedControlPlane:
    def test_acks_coalesce_onto_the_sweep_frame(self, params,
                                                engines):
        srv = ServingServer(engines[0], role="prefill", max_queue=8,
                            max_retries=2, buckets=BUCKETS)
        ts = ReplicaTransportServer(srv).start()
        try:
            client = ReplicaClient(ts.addr, connect_timeout=2.0,
                                   io_timeout=30.0)
            rep = ProcessReplica(client)
            prompts = prompts_for(2, seed=2)
            for p in prompts:
                rep.submit(p, max_new=4)
            while len(rep.ready_handoffs()) < 2:
                rep.step()
            f0 = client.frames
            r1, r2 = rep.ready_handoffs()
            rep.handoff_complete(r1)    # deferred: no frame moves
            rep.handoff_complete(r2)
            assert client.frames == f0
            # the mirror filters released handoffs without an RPC
            assert rep.ready_handoffs() == []
            rep.step()                  # ONE frame carries all 3 ops
            assert client.frames == f0 + 1
            assert rep.rpc_frames_coalesced == 2
            assert rep.rpc_deferred_errors == 0
            # a cancel is urgent (the source must resume decoding
            # NOW): it flushes immediately instead of deferring
            prompt = np.arange(2, 13, dtype=np.int32)
            r3 = rep.submit(prompt, max_new=4)
            while r3 not in rep.ready_handoffs():
                rep.step()
            f1 = client.frames
            rep.cancel_handoff(r3)
            assert client.frames == f1 + 1
            while r3 not in rep.results:
                rep.step()
            assert (rep.results[r3].tokens
                    == ref_tokens(params, prompt, 4))
            assert rep.rpc_deferred_errors == 0
            rep.reconcile()
        finally:
            ts.shutdown()

    def test_partials_ride_the_sweep_frame(self, transport, params):
        ts, srv, client = transport
        rep = ProcessReplica(client)
        prompt = np.arange(1, 12, dtype=np.int32)
        rid = rep.submit(prompt, max_new=6)
        seen = []
        for _ in range(64):
            rep.step()
            if rid in rep.results:
                break
            f = client.frames
            part = rep.partial_tokens(rid)
            # served from the partials block the step reply already
            # carried — the poll itself costs ZERO wire frames
            assert client.frames == f
            if len(part) > len(seen):
                seen = part
        final = rep.results[rid].tokens
        assert final == ref_tokens(params, prompt, 6)
        assert seen and seen == final[:len(seen)]
        assert rep.rpc_frames_coalesced >= len(seen)


# ---------------------------------------------------------------------------
# real-process SIGKILL chaos (the slow lane: scripts/fault_smoke.sh data)


CONFIG_SRC = """\
import jax

from paddle_tpu.models import transformer as T


def get_serve_config():
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense")
    return dict(params=T.init_params(jax.random.key(0), cfg), cfg=cfg,
                slots=2, max_len=32, page_size=4)
"""


def _proc_gone(pid):
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return True
    return state == "Z"


def _await(cond, timeout_s=30.0, poll_s=0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return cond()


def _chaos_fleet(tmp_path, roles):
    """A FleetSupervisor whose spawn seam boots REAL replica
    processes from a heterogeneous role/fault-plan list (the
    supervisor's own spec stays homogeneous): each child runs
    `testing.faults:build_chaos_replica`, attaches the supervisor's
    arena by name, and arms its own FaultPlan — the SIGKILL happens
    INSIDE the child, mid-transfer, where no parent-side proxy could
    reach. Extra clean decode entries feed below-floor repair."""
    from paddle_tpu.testing.fleet import save_tiny_artifact

    art = str(tmp_path / "engine.tar")
    save_tiny_artifact(art, buckets=BUCKETS)
    config = tmp_path / "serve_config.py"
    config.write_text(CONFIG_SRC)
    queue = list(roles) + [("decode", None)] * 3
    booted = []
    sup = None

    def spawn(_spec):
        role, plan = queue.pop(0)
        spec = ReplicaSpec(
            builder="paddle_tpu.testing.faults:build_chaos_replica",
            kwargs=dict(config=str(config), role=role, artifact=art,
                        buckets=list(BUCKETS), max_retries=1,
                        data_plane=sup.arena.name, fault_plan=plan),
            env=dict(CHILD_ENV))
        proc = ReplicaProcess(spec).start()
        proc.wait_ready(120.0)
        booted.append(proc)
        client = ReplicaClient(proc.addr, connect_timeout=1.0,
                               io_timeout=30.0, retries=8)
        return ProcessReplica(client, proc=proc, clock=sup.clock)

    sup = FleetSupervisor(
        ReplicaSpec(builder="paddle_tpu.testing.faults:"
                            "build_chaos_replica"),
        min_replicas=len(roles), max_replicas=len(roles), spawn=spawn,
        data_plane_segs=16, data_plane_seg_kb=2)
    assert sup.arena is not None
    sup.start()
    return sup, booted


def _reap(sup, booted):
    sup.shutdown(drain=False)
    for proc in booted:
        if proc.alive():
            proc.kill()


@pytest.mark.slow
@pytest.mark.heavyweight
def test_sigkill_source_mid_scatter_zero_leaked_segments(tmp_path,
                                                         params):
    """The prefill replica SIGKILLs itself after writing the FIRST
    arena segment of its first export — the ticket never existed
    anywhere, the claimed segments have a dead owner. The router's
    source-death path resubmits every parked request to the decode
    tier (bit-exact), and the supervisor's OWN sweep reclaims every
    orphaned segment: zero leaked, exactly one outcome each."""
    sup, booted = _chaos_fleet(
        tmp_path, [("prefill", dict(arena_kill_scatter_at=0)),
                   ("decode", None), ("decode", None)])
    try:
        prompts = prompts_for(4, seed=3)
        rids = [sup.submit(p, max_new=4) for p in prompts]
        res = sup.run()
        assert sorted(res) == sorted(rids)      # exactly one outcome
        assert all(res[r].outcome == "completed" for r in rids)
        for p, r in zip(prompts, rids):
            assert res[r].tokens == ref_tokens(params, p, 4)
        assert sup.router.counters()["replicas_lost"] >= 1
        c = sup.counters()
        assert c["arena_segments_leaked"] == 0
        assert c["arena_segments_live"] == 0
        assert c["arena_segments_reclaimed"] >= 1
        sup.reconcile()
    finally:
        _reap(sup, booted)


@pytest.mark.slow
@pytest.mark.heavyweight
def test_sigkill_destination_mid_adopt_zero_leaked_segments(tmp_path,
                                                            params):
    """The first decode replica SIGKILLs itself mid-adopt — AFTER
    gathering the bytes, before the stamp, its import reply lost.
    The dead destination's admission died with it (exactly-once needs
    no transaction), the retarget re-exports the SAME ticket to the
    survivor, and the source's ACK-driven free leaves zero segments
    live — the destination's death cost the arena nothing."""
    sup, booted = _chaos_fleet(
        tmp_path, [("prefill", None),
                   ("decode", dict(arena_kill_adopt_at=0)),
                   ("decode", None)])
    try:
        prompts = prompts_for(2, seed=5)
        rids = [sup.submit(p, max_new=4) for p in prompts]
        res = sup.run()
        assert sorted(res) == sorted(rids)
        assert all(res[r].outcome == "completed" for r in rids)
        for p, r in zip(prompts, rids):
            assert res[r].tokens == ref_tokens(params, p, 4)
        rc = sup.router.counters()
        assert rc["replicas_lost"] >= 1
        c = sup.counters()
        assert c["arena_segments_leaked"] == 0
        assert c["arena_segments_live"] == 0
        sup.reconcile()
    finally:
        _reap(sup, booted)


@pytest.mark.slow
@pytest.mark.heavyweight
def test_supervisor_sigkill_orphaned_arena_reclaimed():
    """Kill the SUPERVISOR itself — the arena's creator — with
    SIGKILL: no drain, no atexit, the unlink never runs. The replica
    children exit on the parent-death watchdog (the 3-deep chain:
    test -> supervisor -> replicas), and attaching to the orphaned
    arena BY NAME still audits and reclaims every dead-owner segment;
    this test then owns the unlink the dead supervisor couldn't."""
    from paddle_tpu.testing.fleet import orphan_data_fleet_main

    ctx = multiprocessing.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    sup_proc = ctx.Process(target=orphan_data_fleet_main,
                           args=(child_conn,))
    sup_proc.start()
    child_conn.close()
    assert parent_conn.poll(60.0), "supervisor never reported"
    info = parent_conn.recv()
    assert info["pids"] and all(not _proc_gone(p)
                                for p in info["pids"])
    os.kill(sup_proc.pid, signal.SIGKILL)       # no cleanup runs
    sup_proc.join(10.0)
    assert _await(lambda: all(_proc_gone(p) for p in info["pids"])), \
        f"orphaned replicas survive: {info['pids']}"
    parent_conn.close()
    arena = ShmArena(info["arena"], create=False)
    try:
        assert not _pid_alive(info["ticket"]["tag"] >> 24)
        c = arena.counters()
        assert c["arena_segments_live"] >= 1
        assert c["arena_segments_leaked"] == c["arena_segments_live"]
        n = arena.reclaim_orphans()
        assert n == len(info["ticket"]["segs"])
        with pytest.raises(ArenaError, match="stale ticket"):
            arena.gather(info["ticket"])
        arena.reconcile()
    finally:
        arena.close(destroy=True)
