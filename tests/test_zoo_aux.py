"""Dataset zoo schemas, GAN/VAE training, timers/profiler, checkgrad."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn, optim
from paddle_tpu.data import dataset_zoo as Z
from paddle_tpu.models import gan as gan_mod, vae as vae_mod
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.train import Trainer, events as E
from paddle_tpu.utils import Stat, global_stat, named_scope, timer


# ---- dataset zoo schemas (reference: v2/dataset/*) ----

def test_imdb_schema():
    d = Z.imdb_word_dict()
    samples = list(Z.imdb_train(d, n=20)())
    assert len(samples) == 20
    for ids, label in samples:
        assert ids.dtype == np.int64 and ids.min() >= 0
        assert ids.max() < len(d)
        assert label in (0, 1)


def test_imikolov_ngrams():
    d = Z.imikolov_build_dict(200)
    grams = list(Z.imikolov(d, n=5, sentences=10)())
    assert all(len(g) == 5 for g in grams)
    assert all(0 <= w < 200 for g in grams for w in g)
    # deterministic across calls
    assert grams == list(Z.imikolov(d, n=5, sentences=10)())


def test_movielens_schema():
    for u, g, a, j, m, c, score in Z.movielens(n=50)():
        assert 0 <= u < Z.movielens_max_user_id()
        assert 0 <= m < Z.movielens_max_movie_id()
        assert 1.0 <= score <= 5.0


def test_conll05_schema():
    word_d, verb_d, label_d = Z.conll05_get_dict()
    for words, verb, mark, labels in Z.conll05(n=20)():
        assert len(words) == len(mark) == len(labels)
        assert mark.sum() == 1
        assert 0 <= verb < len(verb_d)
        assert labels.max() < len(label_d)
        assert labels[mark.argmax()] == 1  # predicate position labeled


def test_wmt14_shifted_targets():
    for src, trg_in, trg_next in Z.wmt14(n=20)():
        assert trg_in[0] == 0          # <s>
        assert trg_next[-1] == 1       # <e>
        np.testing.assert_array_equal(trg_in[1:], trg_next[:-1])


def test_mq2007_formats():
    pw = list(Z.mq2007(format="pairwise", n_queries=4)())
    assert pw and all(a.shape == (46,) and b.shape == (46,) for a, b in pw)
    lw = list(Z.mq2007(format="listwise", n_queries=4)())
    assert len(lw) == 4
    qid, feats, rel = lw[0]
    assert feats.shape == (8, 46) and rel.shape == (8,)
    pt = list(Z.mq2007(format="pointwise", n_queries=2)())
    assert all(r in (0, 1, 2) for _, r in pt)


def test_flowers_voc_schema():
    img, lbl = next(iter(Z.flowers(n=2)()))
    assert img.shape == (64, 64, 3) and 0 <= lbl < 102
    img, boxes, labels, difficult = next(iter(Z.voc2012(n=2)()))
    assert img.shape == (96, 96, 3)
    assert boxes.shape[1] == 4 and boxes.min() >= 0 and boxes.max() <= 1
    assert len(labels) == len(boxes) == len(difficult)


def test_local_file_path(tmp_path, monkeypatch):
    """Loaders prefer DATA_HOME npz files over the synthetic fallback."""
    import paddle_tpu.data.datasets as ds
    import importlib

    monkeypatch.setattr(ds, "DATA_HOME", str(tmp_path))
    monkeypatch.setattr(Z, "DATA_HOME", str(tmp_path))
    (tmp_path / "imdb").mkdir()
    ids = np.empty(2, object)
    ids[0] = np.asarray([5, 6, 7])
    ids[1] = np.asarray([8, 9])
    np.savez(tmp_path / "imdb" / "train.npz", ids=ids,
             labels=np.asarray([1, 0]))
    got = list(Z.imdb_train(n=999)())
    assert len(got) == 2
    np.testing.assert_array_equal(got[0][0], [5, 6, 7])
    assert got[0][1] == 1 and got[1][1] == 0

    (tmp_path / "wmt14").mkdir()
    src = np.empty(1, object); src[0] = np.asarray([4, 5])
    trg = np.empty(1, object); trg[0] = np.asarray([6, 7])
    np.savez(tmp_path / "wmt14" / "train.npz", src=src, trg=trg)
    s, ti, tn = next(iter(Z.wmt14()()))
    np.testing.assert_array_equal(ti, [0, 6, 7])
    np.testing.assert_array_equal(tn, [6, 7, 1])


def test_snapshot_version_gate(tmp_path):
    from paddle_tpu.native import TaskQueue

    bad = tmp_path / "old.snap"
    bad.write_bytes(b"\x00" * 64)  # wrong magic
    q = TaskQueue()
    import pytest as _pytest

    with _pytest.raises(OSError, match="rc=-3"):
        q.restore(str(bad))


def test_vae_abstract_init():
    v = vae_mod.VAE(data_dim=16, latent_dim=4)
    _, _, out = v._init(None, ShapeSpec((8, 16)), _abstract=True)
    assert out.shape == (8, 16)


# ---- GAN (reference: v1_api_demo/gan/gan_trainer.py) ----

def test_gan_trains():
    data_dim = 16
    tr = gan_mod.GANTrainer(
        gan_mod.mlp_generator(data_dim, noise_dim=8, hidden=(32,)),
        gan_mod.mlp_discriminator(hidden=(32,)),
        data_dim=data_dim, noise_dim=8)
    state = tr.init_state(jax.random.key(0), batch_size=32)
    rng = np.random.RandomState(0)
    # real data: narrow gaussian blob around 0.7
    key = jax.random.key(1)
    d_losses, g_losses = [], []
    for i in range(20):
        real = jnp.asarray(
            0.7 + 0.05 * rng.randn(32, data_dim), jnp.float32)
        key, sub = jax.random.split(key)
        state, d_loss, g_loss = tr.train_step(state, real, sub)
        d_losses.append(float(d_loss))
        g_losses.append(float(g_loss))
    assert np.isfinite(d_losses).all() and np.isfinite(g_losses).all()
    samples = tr.sample(state, jax.random.key(2), 64)
    assert samples.shape == (64, data_dim)
    # generator output should drift toward the data blob mean
    assert abs(float(samples.mean()) - 0.7) < 0.25


# ---- VAE (reference: v1_api_demo/vae) ----

def test_vae_trains():
    model = vae_mod.VAE(data_dim=32, latent_dim=8, hidden=(64,))
    params, mstate = model.init(jax.random.key(0), ShapeSpec((16, 32)))
    opt = optim.adam(1e-2)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)
    proto = (rng.rand(4, 32) > 0.5).astype(np.float32)

    @jax.jit
    def step(params, opt_state, x, key, i):
        def loss_fn(p):
            outs, _ = model.apply(p, mstate, x, training=True, rng=key)
            return vae_mod.elbo_loss(outs, x)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params, opt_state = opt.update(grads, opt_state, params, i)
        return params, opt_state, loss

    losses_seen = []
    key = jax.random.key(1)
    for i in range(60):
        x = jnp.asarray(proto[rng.randint(0, 4, 16)])
        key, sub = jax.random.split(key)
        params, opt_state, loss = step(params, opt_state, x, sub, i)
        losses_seen.append(float(loss))
    assert losses_seen[-1] < losses_seen[0] * 0.8
    # decode from prior works
    imgs = model.decode(params, mstate, jnp.zeros((3, 8)))
    assert imgs.shape == (3, 32)
    assert 0.0 <= float(imgs.min()) and float(imgs.max()) <= 1.0


# ---- stats / profiler / checkgrad ----

def test_stat_timers():
    s = Stat()
    with s.timer("fwd"):
        pass
    with s.timer("fwd"):
        pass
    with s.timer("bwd"):
        pass
    summ = s.summary()
    assert summ["fwd"]["count"] == 2 and summ["bwd"]["count"] == 1
    assert "fwd" in s.report()
    s.reset("fwd")
    assert "fwd" not in s.summary()
    with timer("global"):
        pass
    assert global_stat.summary()["global"]["count"] >= 1


def test_named_scope_compiles():
    @jax.jit
    def f(x):
        with named_scope("layer1"):
            return x * 2

    assert float(f(jnp.asarray(3.0, jnp.float32))) == 6.0


def test_trainer_checkgrad():
    model = nn.Sequential([nn.Dense(8, activation="tanh"), nn.Dense(3)])
    tr = Trainer(model,
                 loss_fn=lambda lo, la: jnp.mean(
                     losses.softmax_cross_entropy(lo, la)),
                 optimizer=optim.sgd(0.1), seed=0)
    state = tr.init_state(ShapeSpec((8, 4)))
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.rand(8, 4), jnp.float32),
             jnp.asarray(rng.randint(0, 3, 8)))
    err = tr.check_gradients(state, batch, eps=1e-4)
    assert err < 1e-4, err


def test_trainer_checkgrad_multi_output():
    """check_gradients must hand the raw (tuple) model output to loss_fn
    with the same convention as make_train_step (round-1 advisor finding:
    MultiTask models raised TypeError in checkgrad)."""
    from paddle_tpu.nn.composite import MultiTask

    model = MultiTask([("head_a", nn.Dense(3)), ("head_b", nn.Dense(2))],
                      name="mt")

    def loss_fn(outs, la, lb):
        oa, ob = outs
        return (jnp.mean(losses.softmax_cross_entropy(oa, la))
                + jnp.mean(losses.softmax_cross_entropy(ob, lb)))

    tr = Trainer(model, loss_fn=loss_fn, optimizer=optim.sgd(0.1), seed=0,
                 num_inputs=2)
    state = tr.init_state(ShapeSpec((8, 4)), ShapeSpec((8, 5)))
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.rand(8, 4), jnp.float32),
             jnp.asarray(rng.rand(8, 5), jnp.float32),
             jnp.asarray(rng.randint(0, 3, 8)),
             jnp.asarray(rng.randint(0, 2, 8)))
    err = tr.check_gradients(state, batch, eps=1e-4)
    assert err < 1e-4, err


def test_printer_evaluators_and_param_stats():
    import io

    from paddle_tpu.metrics import (SeqTextPrinter, ValuePrinter,
                                    format_parameter_stats, parameter_stats)

    buf = io.StringIO()
    vp = ValuePrinter(stream=buf)
    vp.update(np.arange(6.0).reshape(2, 3), scores=np.ones((2,)))
    assert "shape=(2, 3)" in buf.getvalue()
    assert "scores" in buf.getvalue()

    buf = io.StringIO()
    sp = SeqTextPrinter({0: "<eos>", 1: "hello", 2: "world"}, eos_id=0,
                        stream=buf)
    sp.update(np.asarray([[1, 2, 0, 2], [2, 1, 1, 1]]))
    out = buf.getvalue()
    assert "hello world <eos>" in out
    assert "world hello hello hello" in out

    params = {"fc": {"kernel": np.ones((3, 4)), "bias": np.zeros(4)}}
    grads = {"fc": {"kernel": np.full((3, 4), 0.5), "bias": np.ones(4)}}
    stats = parameter_stats(params, grads)
    assert stats["fc/kernel"]["abs_mean"] == 1.0
    assert stats["fc/kernel"]["grad_abs_mean"] == 0.5
    text = format_parameter_stats(stats)
    assert "fc/kernel" in text and "fc/bias" in text


def test_cost_curve_collects_and_saves(tmp_path):
    from paddle_tpu.utils import CostCurve

    curve = CostCurve(period=2)
    for i in range(6):
        curve(E.EndIteration(0, i, cost=jnp.asarray(float(10 - i)),
                             metrics={"acc": jnp.asarray(0.1 * i)}))
    assert len(curve.series["cost"]) == 3  # every 2nd batch
    csv_path = tmp_path / "c.csv"
    curve.save_csv(str(csv_path))
    assert "cost" in csv_path.read_text()
    png_path = tmp_path / "c.png"
    curve.save_png(str(png_path), title="t")
    assert png_path.exists() and png_path.stat().st_size > 0


def test_model_diagram_dot():
    from paddle_tpu.utils import model_to_dot

    model = nn.Sequential([
        nn.Dense(8, name="fc1", activation="relu"),
        nn.Residual(nn.Sequential([nn.Dense(8, name="inner")]),
                    name="res"),
        nn.Dense(2, name="out"),
    ])
    dot = model_to_dot(model, name="m")
    assert dot.startswith('digraph "m"')
    assert "fc1" in dot and "inner" in dot and "->" in dot


def test_trainer_parameter_stats_period(capsys):
    model = nn.Sequential([nn.Dense(4, name="fc")])
    tr = Trainer(model,
                 loss_fn=lambda lo, la: jnp.mean(
                     losses.softmax_cross_entropy(lo, la)),
                 optimizer=optim.sgd(0.1), seed=0)
    state = tr.init_state(ShapeSpec((4, 3)))
    rng = np.random.RandomState(0)
    batch = (jnp.asarray(rng.rand(4, 3), jnp.float32),
             jnp.asarray(rng.randint(0, 4, 4)))

    def batches():
        for _ in range(4):
            yield batch

    tr.train(state, batches, parameter_stats_period=2)
    out = capsys.readouterr().out
    assert "parameter stats" in out and "fc/kernel" in out


# ---- round-3 layer one-liners: detection heads, hsigmoid, sequence
# reshapes (VERDICT r2 missing #3: "one-liners for the remaining op
# families") ----


def test_priorbox_layer_matches_op():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import detection as D

    layer = nn.PriorBox((64, 64), min_sizes=(0.2,), max_sizes=(0.4,))
    params, state = layer.init(jax.random.key(0), ShapeSpec((2, 8, 8, 16)))
    out, _ = layer.apply(params, state, jnp.zeros((2, 8, 8, 16)))
    want = D.prior_boxes((8, 8), (64, 64), (0.2,), (0.4,))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


@pytest.mark.slow


def test_multibox_loss_layer_batches():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import detection as D

    r = np.random.RandomState(0)
    c, m, b = 4, 3, 2
    priors = jnp.asarray(D.prior_boxes((2, 2), (32, 32), (0.3,),
                                       aspect_ratios=(2.0,)))
    n = priors.shape[0]
    loc = jnp.asarray(r.randn(b, n, 4), jnp.float32) * 0.1
    conf = jnp.asarray(r.randn(b, n, c), jnp.float32)
    gt = jnp.asarray(r.rand(b, m, 4), jnp.float32)
    gt = jnp.sort(gt.reshape(b, m, 2, 2), axis=2).reshape(b, m, 4)
    labels = jnp.asarray(r.randint(1, c, (b, m)))
    valid = jnp.asarray([[True, True, False], [True, False, False]])
    layer = nn.MultiBoxLoss()
    params, state = layer.init(jax.random.key(0), ShapeSpec((b, n, 4)))
    loss, _ = layer.apply(params, state, loc, conf, priors, gt, labels,
                          valid)
    assert loss.shape == (b,)
    assert np.isfinite(np.asarray(loss)).all()


def test_detection_output_layer_shapes():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import detection as D

    r = np.random.RandomState(1)
    c, b, k = 5, 2, 7
    priors = jnp.asarray(D.prior_boxes((2, 2), (32, 32), (0.3,),
                                       aspect_ratios=(2.0,)))
    n = priors.shape[0]
    loc = jnp.asarray(r.randn(b, n, 4), jnp.float32) * 0.05
    conf = jnp.asarray(r.randn(b, n, c), jnp.float32)
    layer = nn.DetectionOutput(num_classes=c, top_k=k)
    params, state = layer.init(jax.random.key(0), ShapeSpec((b, n, 4)))
    (classes, scores, boxes), _ = layer.apply(params, state, loc, conf,
                                              priors)
    assert classes.shape == (b, k) and scores.shape == (b, k)
    assert boxes.shape == (b, k, 4)


def test_hsigmoid_layer_trains_and_scores():
    import jax

    from gradcheck import directional_grad_check
    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec

    r = np.random.RandomState(2)
    b, d, v = 6, 8, 10
    hidden = jnp.asarray(r.randn(b, d), jnp.float32)
    labels = jnp.asarray(r.randint(0, v, b))
    layer = nn.HSigmoid(v)
    params, state = layer.init(jax.random.key(0), ShapeSpec((b, d)))
    loss, _ = layer.apply(params, state, hidden, labels)
    assert loss.shape == (b,) and (np.asarray(loss) > 0).all()
    directional_grad_check(
        lambda p: jnp.sum(layer.apply(p, {}, hidden, labels)[0]), params)
    # higher prob (lower loss) for the trained label direction
    lp = layer.predict_logprob(params, hidden, labels)
    assert np.allclose(np.asarray(lp), -np.asarray(loss))


def test_sequence_reshape_layer():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec

    x = jnp.arange(2 * 4 * 6, dtype=jnp.float32).reshape(2, 4, 6)
    lengths = jnp.asarray([4, 2])
    layer = nn.SequenceReshape(3)
    params, state = layer.init(jax.random.key(0), ShapeSpec((2, 4, 6)))
    (out, new_len), _ = layer.apply(params, state, x, lengths)
    assert out.shape == (2, 8, 3)
    np.testing.assert_array_equal(np.asarray(new_len), [8, 4])
    np.testing.assert_allclose(np.asarray(out[0, 0]), [0, 1, 2])
    np.testing.assert_allclose(np.asarray(out[0, 1]), [3, 4, 5])


def test_sequence_concat_layer():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec

    a = jnp.asarray(np.arange(2 * 3 * 2).reshape(2, 3, 2), jnp.float32)
    b = 100 + jnp.asarray(np.arange(2 * 2 * 2).reshape(2, 2, 2), jnp.float32)
    la = jnp.asarray([2, 3])
    lb = jnp.asarray([2, 1])
    layer = nn.SequenceConcat()
    params, state = layer.init(
        jax.random.key(0), ShapeSpec((2, 3, 2)), ShapeSpec((2,), jnp.int32),
        ShapeSpec((2, 2, 2)), ShapeSpec((2,), jnp.int32))
    (out, lens), _ = layer.apply(params, state, a, la, b, lb)
    assert out.shape == (2, 5, 2)
    np.testing.assert_array_equal(np.asarray(lens), [4, 4])
    # sequence 0: a[0,:2] then b[0,:2]
    np.testing.assert_allclose(np.asarray(out[0, :2]), np.asarray(a[0, :2]))
    np.testing.assert_allclose(np.asarray(out[0, 2:4]), np.asarray(b[0, :2]))
    assert float(jnp.abs(out[0, 4:]).max()) == 0.0
    # sequence 1: a[1,:3] then b[1,:1]
    np.testing.assert_allclose(np.asarray(out[1, :3]), np.asarray(a[1, :3]))
    np.testing.assert_allclose(np.asarray(out[1, 3]), np.asarray(b[1, 0]))


def test_sequence_slice_layer_first_and_last():
    import jax

    from paddle_tpu import nn
    from paddle_tpu.nn.module import ShapeSpec

    x = jnp.asarray(np.arange(2 * 5 * 1).reshape(2, 5, 1), jnp.float32)
    lengths = jnp.asarray([5, 3])
    first = nn.SequenceSlice(2)
    params, state = first.init(jax.random.key(0), ShapeSpec((2, 5, 1)))
    (out, lens), _ = first.apply(params, state, x, lengths)
    np.testing.assert_allclose(np.asarray(out[:, :, 0]), [[0, 1], [5, 6]])
    np.testing.assert_array_equal(np.asarray(lens), [2, 2])

    last = nn.SequenceSlice(2, from_end=True)
    (out, lens), _ = last.apply(params, state, x, lengths)
    np.testing.assert_allclose(np.asarray(out[:, :, 0]), [[3, 4], [6, 7]])


class TestTraffic:
    """Multi-task traffic forecaster (reference:
    v1_api_demo/traffic_prediction/trainer_config.py)."""

    def test_shapes_and_predict(self):
        from paddle_tpu.models import traffic

        params = traffic.init_params(jax.random.key(0))
        x = jnp.asarray(np.random.RandomState(0).rand(8, 24), jnp.float32)
        logits = traffic.apply(params, x)
        assert logits.shape == (8, 24, 4)
        pred = traffic.predict(params, x)
        assert pred.shape == (8, 24) and int(pred.max()) < 4

    def test_multitask_learns(self):
        from paddle_tpu import optim
        from paddle_tpu.models import traffic

        params = traffic.init_params(jax.random.key(1))
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.rand(64, 24), jnp.float32)
        # learnable rule: class for horizon t depends on mean speed
        y = jnp.asarray(
            (np.clip(np.asarray(x).mean(1, keepdims=True) * 4, 0, 3.99)
             ).astype(np.int32).repeat(24, 1))
        opt = optim.rmsprop(5e-3)
        ost = opt.init(params)

        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(
                lambda p: traffic.loss(p, x, y))(p)
            p2, s2 = opt.update(g, s, p, jnp.zeros((), jnp.int32))
            return p2, s2, l

        first = None
        for _ in range(60):
            params, ost, l = step(params, ost)
            first = first if first is not None else float(l)
        assert float(l) < first * 0.6, (first, float(l))
