"""Elastic gang training: reshard-on-restore checkpoints + gang
supervision chaos.

The contract under test: a gang of N data-parallel processes keeps its
ZeRO-sharded optimizer state durable with a topology manifest
(train.ElasticCheckpointManager), so the SAME training run resumes
bit-exactly on M != N replicas; and a gang member dying (real SIGKILL
mid-burst) or wedging (SIGSTOP) causes the supervisor to tear down the
barrier, reform at the surviving count, and continue the IDENTICAL
loss trajectory from the last durable step — exactly-once step
accounting, never a silent misreshard.
"""

import json
import os
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.core.mesh import MeshConfig, batch_sharding, build_mesh
from paddle_tpu.optim import optimizers as O
from paddle_tpu.parallel import make_zero_train_step, zero_true_sizes
from paddle_tpu.parallel.launch import GangSupervisor
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.testing.gang import build_tiny_job
from paddle_tpu.train import (
    ElasticCheckpointManager,
    ManifestMismatchError,
)
from paddle_tpu.train.resilience import (
    ResilientTrainer,
    restore_with_fallback,
)
from paddle_tpu.train.state import TrainState
from paddle_tpu.train.trainer import Trainer

pytestmark = [pytest.mark.elastic, pytest.mark.faults]


def _model(hidden=7):
    return nn.Sequential([
        nn.Dense(hidden, name="fc", activation="relu"),
        nn.Dense(3, name="out"),
    ])


def _loss(out, y):
    return jnp.mean((out - y) ** 2)


def _mesh(n):
    return build_mesh(MeshConfig(data=n), devices=jax.devices()[:n])


def _init(model, opt, mesh):
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    return params, TrainState.create_zero(params, mstate, opt, mesh)


def _advance(model, opt, mesh, state, steps=2):
    step = make_zero_train_step(model, _loss, opt, mesh, donate=False)
    x = jax.device_put(
        np.random.RandomState(0).randn(16, 8).astype(np.float32),
        batch_sharding(mesh))
    y = jax.device_put(
        np.random.RandomState(1).randn(16, 3).astype(np.float32),
        batch_sharding(mesh))
    for _ in range(steps):
        state, loss, _ = step(state, jax.random.key(7), x, y)
    return state, step, (x, y)


def _assert_opt_bits_equal(params, ref_opt, got_opt):
    """Compare the UNPADDED prefix of every flat opt leaf: padding
    differs by topology, the real moments must not."""
    sizes = jax.tree.leaves(zero_true_sizes(params, ref_opt))
    for t, a, b in zip(sizes, jax.tree.leaves(ref_opt),
                       jax.tree.leaves(got_opt)):
        av = np.asarray(a).reshape(-1)[:t]
        bv = np.asarray(b).reshape(-1)[:t]
        assert np.array_equal(av, bv)


# -- reshard-on-restore round trips ---------------------------------------


@pytest.mark.parametrize("m", [2, 1], ids=["8to2", "8to1"])
def test_reshard_restore_bit_exact(tmp_path, m):
    """An 8-replica checkpoint resumes BIT-exactly on m replicas:
    params, step counter, and every optimizer moment's unpadded
    prefix identical; `reshard_restores` counts the conversion."""
    model, opt = _model(), O.adam(1e-2)
    mesh8 = _mesh(8)
    params, st8 = _init(model, opt, mesh8)
    st8, _, _ = _advance(model, opt, mesh8, st8)
    ElasticCheckpointManager(str(tmp_path), mesh=mesh8).save(st8)

    mesh_m = _mesh(m)
    _, tmpl = _init(model, opt, mesh_m)
    mgr = ElasticCheckpointManager(str(tmp_path), mesh=mesh_m)
    got = mgr.restore(tmpl)
    assert mgr.reshard_restores == 1
    for a, b in zip(jax.tree.leaves(st8.params),
                    jax.tree.leaves(got.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert int(got.step) == int(st8.step)
    _assert_opt_bits_equal(params, st8.opt_state, got.opt_state)


def test_reshard_roundtrip_1_to_8_and_continue(tmp_path):
    """The scale-UP direction (1 -> 8): moments survive bit-exactly,
    and one more step on each topology lands on the same loss
    (allclose: reduction order differs across replica counts)."""
    model, opt = _model(), O.momentum(0.05, 0.9)
    mesh1 = _mesh(1)
    params, st1 = _init(model, opt, mesh1)
    st1, step1, (x1, y1) = _advance(model, opt, mesh1, st1)
    ElasticCheckpointManager(str(tmp_path), mesh=mesh1).save(st1)

    mesh8 = _mesh(8)
    _, tmpl = _init(model, opt, mesh8)
    mgr = ElasticCheckpointManager(str(tmp_path), mesh=mesh8)
    st8 = mgr.restore(tmpl)
    assert mgr.reshard_restores == 1
    _assert_opt_bits_equal(params, st1.opt_state, st8.opt_state)

    _, l1, _ = step1(st1, jax.random.key(7), x1, y1)
    step8 = make_zero_train_step(model, _loss, opt, mesh8,
                                 donate=False)
    x8 = jax.device_put(np.asarray(x1), batch_sharding(mesh8))
    y8 = jax.device_put(np.asarray(y1), batch_sharding(mesh8))
    _, l8, _ = step8(st8, jax.random.key(7), x8, y8)
    np.testing.assert_allclose(float(l1), float(l8), rtol=1e-5)


def test_reshard_uneven_shapes(tmp_path):
    """Leaf sizes with no relation to the replica count (Dense(5):
    kernel 40, bias 5) pad on save and unpad on restore without
    corrupting a single element."""
    model = nn.Sequential([nn.Dense(5, name="fc", activation="relu"),
                           nn.Dense(3, name="out")])
    opt = O.adam(1e-2)
    mesh8 = _mesh(8)
    params, st8 = _init(model, opt, mesh8)
    st8, _, _ = _advance(model, opt, mesh8, st8)
    ElasticCheckpointManager(str(tmp_path), mesh=mesh8).save(st8)
    mesh2 = _mesh(2)
    _, tmpl = _init(model, opt, mesh2)
    got = ElasticCheckpointManager(str(tmp_path),
                                   mesh=mesh2).restore(tmpl)
    _assert_opt_bits_equal(params, st8.opt_state, got.opt_state)


# -- failure modes: named errors, torn manifests, fallback -----------------


def test_manifest_mismatch_is_named_and_not_walked_past(tmp_path):
    """A template describing a DIFFERENT model must fail with the
    named ManifestMismatchError — and restore_with_fallback must
    re-raise it instead of silently walking back (every older step
    would mismatch identically: this is never corruption)."""
    model, opt = _model(), O.adam(1e-2)
    mesh8 = _mesh(8)
    _, st8 = _init(model, opt, mesh8)
    ElasticCheckpointManager(str(tmp_path), mesh=mesh8).save(st8)

    other = _model(hidden=9)
    mesh2 = _mesh(2)
    _, bad_tmpl = _init(other, opt, mesh2)
    mgr = ElasticCheckpointManager(str(tmp_path), mesh=mesh2)
    with pytest.raises(ManifestMismatchError):
        mgr.restore(bad_tmpl)
    with pytest.raises(ManifestMismatchError):
        restore_with_fallback(mgr, bad_tmpl)


def test_missing_or_corrupt_manifest_falls_back(tmp_path):
    """A checkpoint whose manifest is missing (SIGKILL between orbax
    commit and manifest write) or garbage is TORN: its own restore
    fails, and restore_with_fallback lands on the previous durable
    step instead."""
    model, opt = _model(), O.adam(1e-2)
    mesh8 = _mesh(8)
    params, st = _init(model, opt, mesh8)
    mgr8 = ElasticCheckpointManager(str(tmp_path), mesh=mesh8)
    st, _, _ = _advance(model, opt, mesh8, st)          # step 2
    mgr8.save(st)
    good_step = int(st.step)
    good = st
    st, _, _ = _advance(model, opt, mesh8, st)          # step 4
    mgr8.save(st)
    torn_step = int(st.step)

    # torn shape 1: manifest never landed
    os.unlink(mgr8._manifest_path(torn_step))
    mesh2 = _mesh(2)
    _, tmpl = _init(model, opt, mesh2)
    mgr2 = ElasticCheckpointManager(str(tmp_path), mesh=mesh2)
    with pytest.raises(ValueError):
        mgr2.restore(tmpl, step=torn_step)
    restored, got_step = restore_with_fallback(mgr2, tmpl)
    assert got_step == good_step
    _assert_opt_bits_equal(params, good.opt_state, restored.opt_state)

    # torn shape 2: manifest is garbage bytes
    pathlib.Path(mgr2._manifest_path(torn_step)).write_text("{not json")
    with pytest.raises(ValueError):
        mgr2.restore(tmpl, step=torn_step)
    _, got_step = restore_with_fallback(mgr2, tmpl)
    assert got_step == good_step


# -- ResilientTrainer across a topology change -----------------------------


def test_resilient_trainer_resumes_across_topology(tmp_path):
    """The mid-training handoff a reformed gang performs, in-process:
    an 8-replica ResilientTrainer checkpoints and 'dies'; a 2-replica
    one restores THROUGH the reshard path and finishes the run, with
    the conversion and the new gang_epoch visible in counters()."""
    def make_rt(mesh, gang_epoch, ckpt):
        model, opt = _model(), O.momentum(0.05, 0.9)
        trainer = Trainer(model, _loss, opt, seed=0)
        trainer._rng, init_rng = jax.random.split(trainer._rng)
        params, mstate = model.init(init_rng,
                                    jnp.zeros((8, 8), jnp.float32))
        state = TrainState.create_zero(params, mstate, opt, mesh)
        rt = ResilientTrainer(
            trainer, ckpt,
            checkpoint_manager=ElasticCheckpointManager(ckpt,
                                                        mesh=mesh),
            checkpoint_every_n_batches=2,
            install_signal_handlers=False,
            step_builder=lambda o: make_zero_train_step(
                model, _loss, o, mesh, donate=False),
            gang_epoch=gang_epoch)
        return rt, state

    def factory_for(mesh, total):
        def factory():
            rng = np.random.RandomState(5)
            for _ in range(total):
                x = rng.randn(8, 8).astype(np.float32)
                y = rng.randn(8, 3).astype(np.float32)
                yield (jax.device_put(x, batch_sharding(mesh)),
                       jax.device_put(y, batch_sharding(mesh)))
        return factory

    ckpt = str(tmp_path)
    mesh8 = _mesh(8)
    rt8, st8 = make_rt(mesh8, 0, ckpt)
    final8 = rt8.run(st8, factory_for(mesh8, 4), num_passes=1)
    assert int(final8.step) == 4

    mesh2 = _mesh(2)
    rt2, st2 = make_rt(mesh2, 1, ckpt)
    final2 = rt2.run(st2, factory_for(mesh2, 8), num_passes=1)
    assert rt2.restored_step == 4
    assert int(final2.step) == 8
    c = rt2.counters()
    assert c["reshard_restores"] == 1
    assert c["gang_epoch"] == 1


# -- gang supervision ------------------------------------------------------


def test_gang_counters_are_registry_shaped():
    """Supervisor counters bind to the obs registry and export the
    documented train_gang_* series without spawning anything."""
    from paddle_tpu.obs import MetricsRegistry

    sup = GangSupervisor(
        "paddle_tpu.testing.gang:build_tiny_job", {},
        workdir="/tmp/unused-gang", checkpoint_dir="/tmp/unused-ckpt",
        num_processes=2, total_steps=1)
    reg = MetricsRegistry()
    sup.bind_metrics(reg)
    names = {row["name"] for row in reg.snapshot()["series"]}
    assert "train_gang_reforms" in names
    assert "train_gang_members_lost" in names
    assert "train_gang_fenced_wedged" in names
    for v in sup.counters().values():
        assert isinstance(v, (int, float))


def _reference_losses(total_steps):
    """What an uninterrupted run of the gang job produces, computed
    in-process with the EXACT worker semantics (same init split, same
    fold_in-per-step rng, same ZeRO step) on a 1-replica mesh."""
    job = build_tiny_job()
    trainer = Trainer(job["model"], job["loss_fn"], job["optimizer"],
                      seed=0)
    trainer._rng, init_rng = jax.random.split(trainer._rng)
    params, mstate = job["model"].init(init_rng, *job["input_specs"])
    mesh = _mesh(1)
    state = TrainState.create_zero(params, mstate, job["optimizer"],
                                   mesh)
    step = make_zero_train_step(job["model"], job["loss_fn"],
                                job["optimizer"], mesh, donate=False)
    base = trainer._rng
    losses = []
    for i, (x, y) in enumerate(job["batches"](total_steps)):
        rng = jax.random.fold_in(base, jax.device_put(np.uint32(i)))
        state, loss, _ = step(
            state, rng,
            (jax.device_put(x, batch_sharding(mesh)),),
            (jax.device_put(y, batch_sharding(mesh)),))
        losses.append(float(loss))
    return losses


@pytest.mark.slow
@pytest.mark.heavyweight
def test_gang_sigkill_midstep_reforms_and_converges(tmp_path):
    """THE chaos proof (the suite's one sanctioned heavyweight): a
    real 2-process jax.distributed gang takes a real SIGKILL on rank 1
    mid-burst. The supervisor must observe the corpse, tear down the
    blocked barrier (survivor SIGKILLed out of its dead collective),
    reform at 1 process with gang_epoch bumped, reshard-restore the
    2-way optimizer shards, and reach the SAME loss trajectory from
    the restore step onward — every step index executed, none applied
    twice (exactly-once accounting via the step==batches-consumed
    resume cursor)."""
    total = 8
    sup = GangSupervisor(
        "paddle_tpu.testing.gang:build_tiny_job", {},
        workdir=str(tmp_path / "work"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        num_processes=2, total_steps=total, checkpoint_every=2,
        seed=0, grace_s=3.0)
    plan = FaultPlan(gang_kill_step_at=2, gang_kill_rank=1)
    plan.wrap_gang(sup)
    out = sup.run(deadline_s=300)

    assert plan.count("gangkill") == 1
    c = sup.counters()
    assert c["reforms"] == 1
    assert c["members_lost"] == 1
    assert c["gang_epoch"] == 1

    res = out["results"][0]
    assert res["final_step"] == total
    assert res["counters"]["gang_epoch"] == 1.0
    # the reformed 1-way gang really did convert the 2-way shards
    assert res["counters"]["reshard_restores"] >= 1.0
    r = res["restored_step"]
    assert r is not None and 0 < r < total
    # exactly-once: the reformed member replays from the restore step
    # through the end, step == batches-consumed the whole way
    assert res["steps"] == list(range(r, total))
    # ...and lands on the identical trajectory
    ref = _reference_losses(total)
    np.testing.assert_allclose(res["losses"], ref[r:], rtol=1e-5)
    # epoch-0 artifacts survive for post-mortem: the dead epoch wrote
    # heartbeats, the fault fired exactly once
    assert (tmp_path / "work" / "hb_0_1.json").exists()


@pytest.mark.slow
def test_gang_wedged_member_is_fenced(tmp_path):
    """Wedged-NOT-dead: rank 1 gets SIGSTOP, so it stops heartbeating
    while staying alive. The supervisor must pick it (stopped-state
    evidence), fence it with a real SIGKILL, and reform — the
    surviving count finishes the job."""
    total = 8
    sup = GangSupervisor(
        "paddle_tpu.testing.gang:build_tiny_job", {},
        workdir=str(tmp_path / "work"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        num_processes=2, total_steps=total, checkpoint_every=2,
        seed=0, heartbeat_timeout_s=6.0, grace_s=3.0)
    plan = FaultPlan(gang_wedge_step_at=2, gang_wedge_rank=1)
    plan.wrap_gang(sup)
    out = sup.run(deadline_s=300)

    assert plan.count("gangwedge") == 1
    c = sup.counters()
    assert c["fenced_wedged"] == 1
    assert c["reforms"] == 1 and c["members_lost"] == 1
    res = out["results"][0]
    assert res["final_step"] == total
    ref = _reference_losses(total)
    r = res["restored_step"]
    np.testing.assert_allclose(res["losses"], ref[r:], rtol=1e-5)


def test_gang_spec_roundtrip(tmp_path):
    """GangSpec survives its JSON hop across the spawn boundary."""
    from paddle_tpu.parallel.launch import GangSpec

    spec = GangSpec(
        builder="paddle_tpu.testing.gang:build_tiny_job",
        builder_kwargs={"batch": 8}, checkpoint_dir="/c",
        workdir="/w", total_steps=5, checkpoint_every=2, seed=3,
        coordinator="127.0.0.1:1", num_processes=2, gang_epoch=4,
        watchdog_timeout_s=30.0)
    back = GangSpec.from_json(spec.to_json())
    assert back == spec
    assert json.loads(spec.to_json())["gang_epoch"] == 4
