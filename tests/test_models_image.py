"""Image model zoo sanity: shapes, forward finiteness, grads flow.

Mirrors the reference's benchmark-config smoke coverage (reference:
benchmark/paddle/image/*.py run through the trainer in --job=time mode)
with small inputs so it stays fast on the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import models
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses


def _forward_check(model, shape, num_classes, rng, training=False):
    params, state = model.init(rng, ShapeSpec(shape))
    x = jnp.asarray(np.random.RandomState(0).rand(*shape), jnp.float32)
    out, _ = model.apply(params, state, x, training=training,
                         rng=rng if training else None)
    assert out.shape == (shape[0], num_classes)
    assert bool(jnp.all(jnp.isfinite(out)))
    return params, state, x, out


def test_resnet50_shapes(rng):
    model = models.resnet.resnet(50, num_classes=10)
    spec = model.out_spec(ShapeSpec((2, 64, 64, 3)))
    assert spec.shape == (2, 10)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_resnet18_forward_and_grad(rng):
    model = models.resnet.resnet(18, num_classes=5, width=8)
    params, state, x, _ = _forward_check(model, (2, 32, 32, 3), 5, rng)
    y = jnp.array([0, 3])

    def loss_fn(p):
        logits, _ = model.apply(p, state, x, training=False)
        return jnp.mean(losses.softmax_cross_entropy(logits, y))

    g = jax.grad(loss_fn)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)
    # stem + every residual stage must receive gradient (catches a
    # detached shortcut or branch in the Residual combinator)
    assert all(float(jnp.abs(l).sum()) > 0 for l in leaves)


def test_resnet_s2d_stem_equivalent(rng):
    """resnet(s2d_stem=True) is the SAME function with the SAME params
    as the plain model — only the stem conv's dataflow differs
    (ops.conv.conv2d_space_to_depth)."""
    plain = models.resnet.resnet(18, num_classes=5, width=8)
    s2d = models.resnet.resnet(18, num_classes=5, width=8, s2d_stem=True)
    params, state = plain.init(rng, ShapeSpec((2, 32, 32, 3)))
    params2, _ = s2d.init(rng, ShapeSpec((2, 32, 32, 3)))
    chex = jax.tree_util.tree_structure
    assert chex(params) == chex(params2)  # param-compatible (checkpoints)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3), jnp.float32)
    # eval mode is the pure-function comparison: BN normalizes by FIXED
    # running stats, so the only difference is the stem conv's dataflow
    # (measured max-abs 4.8e-6; asserted at 1e-4)
    y0, _ = plain.apply(params, state, x, training=False)
    y1, _ = s2d.apply(params, state, x, training=False)
    np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-4)
    # training mode: every BN divides by the BATCH variance of its own
    # input, so the stem's ulp-scale difference is re-amplified by each
    # of the 18 BNs in turn — measured up to ~5e-2 on random weights at
    # this size, which is batch-statistics feedback, not a dataflow
    # bug. The training-mode contract worth pinning is the BN STATE
    # update (computed from pre-normalization activations): tracks at
    # 1e-2 through the whole depth.
    _, st0 = plain.apply(params, state, x, training=True)
    _, st1 = s2d.apply(params, state, x, training=True)
    m0 = jax.tree_util.tree_leaves(st0)
    m1 = jax.tree_util.tree_leaves(st1)
    for a, b in zip(m0, m1):
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=2e-2)


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["conv_out", "full"])
def test_resnet_remat_equivalent(rng, policy):
    """resnet(remat=...) is the SAME function with the SAME params —
    identical loss AND grads; only what the backward saves vs recomputes
    differs (nn.Remat wrapping each residual block)."""
    plain = models.resnet.resnet(18, num_classes=5, width=8)
    remat = models.resnet.resnet(18, num_classes=5, width=8, remat=policy)
    params, state = plain.init(rng, ShapeSpec((2, 32, 32, 3)))
    params2, _ = remat.init(rng, ShapeSpec((2, 32, 32, 3)))
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(params2))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    y = jnp.array([0, 3])

    def loss_fn(model):
        def f(p):
            logits, _ = model.apply(p, state, x, training=True)
            return jnp.mean(losses.softmax_cross_entropy(logits, y))
        return f

    l0, g0 = jax.value_and_grad(loss_fn(plain))(params)
    l1, g1 = jax.value_and_grad(loss_fn(remat))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    # grads are equal as MATH but not as XLA programs: remat's backward
    # re-runs the forward as a separately-fused computation, and f32
    # reassociation across the refused conv+BN chains shifts O(100)-
    # magnitude BN-scale grads by up to ~1.1e-3 abs / ~9.2e-3 rel
    # (measured on both policies at this size). rtol 1e-2 with a 2e-3
    # floor separates that fusion noise from a real backward bug —
    # a detached branch or double-counted shortcut moves grads by O(1).
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-2, atol=2e-3)


def test_resnet_remat_validates():
    with pytest.raises(ValueError, match="remat"):
        models.resnet.resnet(18, remat="bogus")


def test_smallnet(rng):
    """The CIFAR-quick benchmark net (reference:
    benchmark/paddle/image/smallnet_mnist_cifar.py)."""
    model = models.smallnet.smallnet(num_classes=10)
    params, state, x, y = _forward_check(model, (2, 32, 32, 3), 10, rng)
    assert y.shape == (2, 10)


def test_resnet_cifar(rng):
    model = models.resnet.resnet_cifar(20, num_classes=10, width=8)
    _forward_check(model, (2, 32, 32, 3), 10, rng)


@pytest.mark.parametrize("depth", [11, 16])
def test_vgg(rng, depth):
    model = models.vgg.vgg(depth, num_classes=7, fc_dim=64)
    _forward_check(model, (2, 32, 32, 3), 7, rng, training=True)


def test_alexnet(rng):
    model = models.alexnet.alexnet(num_classes=4)
    _forward_check(model, (1, 127, 127, 3), 4, rng)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_googlenet(rng):
    model = models.googlenet.googlenet(num_classes=6)
    _forward_check(model, (1, 64, 64, 3), 6, rng)


def test_resnet50_bn_state_updates(rng):
    model = models.resnet.resnet(50, num_classes=3, width=8)
    shape = (2, 32, 32, 3)
    params, state = model.init(rng, ShapeSpec(shape))
    x = jnp.asarray(np.random.RandomState(0).rand(*shape), jnp.float32)
    _, new_state = model.apply(params, state, x, training=True, rng=rng)
    # running stats must move in training mode
    before = jax.tree_util.tree_leaves(state)
    after = jax.tree_util.tree_leaves(new_state)
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_inception_fused_equivalent(rng):
    """The fused-1x1 Inception layer == the plain Branches expression
    with the SAME params (the param trees are identical by design)."""
    from paddle_tpu.models import googlenet as G

    fused = G.Inception(8, 6, 12, 4, 8, 6, name="i")
    plain = G._inception_branches("i", 8, 6, 12, 4, 8, 6)
    params, state = fused.init(rng, ShapeSpec((2, 8, 8, 10)))
    params2, state2 = plain.init(rng, ShapeSpec((2, 8, 8, 10)))
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(params2))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 8, 10), jnp.float32)
    y_fused, _ = fused.apply(params, state, x, training=False)
    y_plain, _ = plain.apply(params, state2, x, training=False)
    assert y_fused.shape == (2, 8, 8, 8 + 12 + 8 + 6)
    np.testing.assert_allclose(y_fused, y_plain, rtol=1e-5, atol=1e-5)
