"""Multi-device tests on the virtual 8-CPU mesh — the in-process cluster
simulation strategy (reference: trainer/tests/test_TrainerOnePass.cpp:127
'test trainer + pserver' in one process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu import models, nn, optim, parallel
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.train import Trainer
from paddle_tpu.train.state import TrainState


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 host devices"
    return mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, model=2))


def _loss(logits, labels):
    return jnp.mean(losses.softmax_cross_entropy(logits, labels))


def test_data_parallel_matches_single_device(mesh8):
    """DP over 4 devices must be numerically equal to single-device: the
    cross-backend equivalence test style (reference:
    gserver/tests/test_NetworkCompare.cpp)."""
    model = models.lenet.mlp(10, hidden=(32,))
    opt = optim.sgd(0.1)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((16, 28, 28, 1)))
    state_single = TrainState.create(params, mstate, opt)

    x = np.random.RandomState(0).rand(16, 28, 28, 1).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 16)

    # single device step
    from paddle_tpu.train.trainer import make_train_step

    step1 = make_train_step(model, _loss, opt, donate=False)
    s1, loss1, _ = step1(state_single, rng, (jnp.asarray(x),), (jnp.asarray(y),))

    # sharded step
    state_sh = parallel.shard_train_state(
        TrainState.create(params, mstate, opt), mesh8
    )
    stepN = parallel.make_sharded_train_step(model, _loss, opt, mesh8, donate=False)
    bs = parallel.batch_sharding(mesh8)
    xs = jax.device_put(x, bs)
    ys = jax.device_put(y, bs)
    sN, lossN, _ = stepN(state_sh, rng, (xs,), (ys,))

    np.testing.assert_allclose(float(loss1), float(lossN), rtol=1e-5)
    w1 = np.asarray(jax.device_get(s1.params["fc1"]["kernel"]))
    wN = np.asarray(jax.device_get(sN.params["fc1"]["kernel"]))
    np.testing.assert_allclose(w1, wN, rtol=1e-4, atol=1e-5)


def test_tensor_parallel_dense(mesh8):
    """Dense kernel sharded over the model axis still computes correctly."""
    model = nn.Sequential(
        [nn.Dense(64, name="fc1", activation="relu"), nn.Dense(10, name="logits")]
    )
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((8, 32)))
    rules = [(r"fc1/kernel", P(None, "model")), (r"logits/kernel", P("model", None))]
    shardings = parallel.make_param_shardings(params, mesh8, rules)
    sharded = jax.tree.map(jax.device_put, params, shardings)

    x = jnp.asarray(np.random.RandomState(0).rand(8, 32), jnp.float32)
    out_ref, _ = model.apply(params, mstate, x)
    out_sh, _ = jax.jit(lambda p, x: model.apply(p, mstate, x))(sharded, x)
    np.testing.assert_allclose(
        np.asarray(out_ref), np.asarray(out_sh), rtol=1e-4, atol=1e-5
    )
    # kernel is actually sharded
    fc1_sh = sharded["fc1"]["kernel"].sharding
    assert fc1_sh.spec == P(None, "model")


def test_zero_optimizer_sharding(mesh8):
    model = models.lenet.mlp(10, hidden=(64,))
    opt = optim.adam(1e-3)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((8, 28, 28, 1)))
    state = parallel.shard_train_state(
        TrainState.create(params, mstate, opt), mesh8, zero=True
    )
    # at least one moment buffer should be sharded over data axis
    specs = [
        leaf.sharding.spec
        for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding")
    ]
    assert any(spec != P() for spec in specs), specs


def test_sharded_step_pins_state_shardings(mesh8):
    """Round-2 (VERDICT item 6): the updated state's shardings must equal
    the input state's under dp x tp rules AND ZeRO moments — nothing may
    reshard donated buffers between steps."""
    model = nn.Sequential(
        [nn.Dense(64, name="fc1", activation="relu"),
         nn.Dense(10, name="logits")]
    )
    opt = optim.adam(1e-3)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((8, 32)))
    rules = [(r"fc1/kernel", P(None, "model")),
             (r"logits/kernel", P("model", None))]
    state = parallel.shard_train_state(
        TrainState.create(params, mstate, opt), mesh8,
        param_rules=rules, zero=True)
    step = parallel.make_sharded_train_step(
        model, _loss, opt, mesh8, donate=False, param_rules=rules, zero=True)

    x = jax.device_put(
        np.random.RandomState(0).rand(8, 32).astype(np.float32),
        parallel.batch_sharding(mesh8))
    y = jax.device_put(np.random.RandomState(1).randint(0, 10, 8),
                       parallel.batch_sharding(mesh8))
    new_state, loss, _ = step(state, rng, (x,), (y,))

    def norm(spec):
        # strip trailing Nones: P('model',) == P('model', None)
        parts = tuple(spec)
        while parts and parts[-1] is None:
            parts = parts[:-1]
        return parts

    def specs(tree):
        return [norm(l.sharding.spec) for l in jax.tree.leaves(tree)
                if hasattr(l, "sharding")]

    assert specs(new_state.params) == specs(state.params)
    assert specs(new_state.opt_state) == specs(state.opt_state)
    # params actually TP-sharded, moments actually ZeRO-sharded
    assert norm(new_state.params["fc1"]["kernel"].sharding.spec) == \
        norm(P(None, "model"))
    assert any(s != () for s in specs(new_state.opt_state))


def test_gradient_accumulation_matches_full_batch(mesh8):
    """accum_steps=2 on a 2B batch == one full-batch step (mean losses)."""
    from paddle_tpu.train.trainer import make_train_step

    model = nn.Sequential(
        [nn.Dense(32, name="fc1", activation="tanh"),
         nn.Dense(5, name="logits")]
    )
    opt = optim.sgd(0.1)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((16, 12)))
    x = jnp.asarray(np.random.RandomState(0).rand(16, 12), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 5, 16))

    s_full = TrainState.create(params, mstate, opt)
    step_full = make_train_step(model, _loss, opt, donate=False)
    f_state, f_loss, _ = step_full(s_full, rng, (x,), (y,))

    s_acc = TrainState.create(params, mstate, opt)
    step_acc = make_train_step(model, _loss, opt, donate=False,
                               accum_steps=2)
    a_state, a_loss, _ = step_acc(s_acc, rng, (x,), (y,))

    np.testing.assert_allclose(float(f_loss), float(a_loss), rtol=1e-5)
    for wf, wa in zip(jax.tree.leaves(f_state.params),
                      jax.tree.leaves(a_state.params)):
        np.testing.assert_allclose(np.asarray(wf), np.asarray(wa),
                                   rtol=1e-5, atol=1e-6)


def test_sharded_step_with_accumulation(mesh8):
    """Accumulation composes with the sharded step builder."""
    model = nn.Sequential(
        [nn.Dense(16, name="fc1", activation="relu"),
         nn.Dense(4, name="logits")]
    )
    opt = optim.momentum(0.05, mu=0.9)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((16, 8)))
    state = parallel.shard_train_state(
        TrainState.create(params, mstate, opt), mesh8)
    step = parallel.make_sharded_train_step(
        model, _loss, opt, mesh8, donate=False, accum_steps=4)
    x = jax.device_put(np.random.RandomState(0).rand(16, 8).astype(np.float32),
                       parallel.batch_sharding(mesh8))
    y = jax.device_put(np.random.RandomState(1).randint(0, 4, 16),
                       parallel.batch_sharding(mesh8))
    new_state, loss, _ = step(state, rng, (x,), (y,))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1
