"""Layer/module-system tests incl. numeric gradient checks per layer —
the testLayerGrad analogue (reference: gserver/tests/test_LayerGrad.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import nn
from paddle_tpu.nn.module import ShapeSpec, merge_state

from gradcheck import directional_grad_check


def _init_apply(layer, rng, x, **kw):
    params, state = layer.init(rng, ShapeSpec(x.shape, x.dtype))
    out, _ = layer.apply(params, state, x, **kw)
    return params, state, out


class TestDense:
    def test_shapes_and_grad(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(4, 8), jnp.float32)
        layer = nn.Dense(16, activation="relu")
        params, state, out = _init_apply(layer, rng, x)
        assert out.shape == (4, 16)
        smooth = nn.Dense(16, activation="tanh")
        params2, _ = smooth.init(rng, nn.ShapeSpec(x.shape, x.dtype))
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(smooth.apply(p, {}, x)[0])), params2
        )

    def test_out_spec_matches(self, rng, np_rng):
        layer = nn.Dense(5)
        spec = layer.out_spec(ShapeSpec((2, 3)))
        assert spec.shape == (2, 5)

    def test_no_bias(self, rng):
        layer = nn.Dense(4, use_bias=False)
        params, _ = layer.init(rng, ShapeSpec((1, 3)))
        assert "bias" not in params


class TestConvLayers:
    def test_conv_stack_shapes(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(2, 28, 28, 1), jnp.float32)
        net = nn.Sequential([
            nn.Conv2D(8, 5, name="c1", activation="relu"),
            nn.MaxPool2D(2, name="p1"),
            nn.Conv2D(16, 5, name="c2", activation="relu"),
            nn.MaxPool2D(2, name="p2"),
            nn.Flatten(name="f"),
            nn.Dense(10, name="out"),
        ])
        params, state = net.init(rng, ShapeSpec(x.shape))
        out, _ = net.apply(params, state, x)
        assert out.shape == (2, 10)
        # abstract shape inference agrees with the real run
        spec = net.out_spec(ShapeSpec(x.shape))
        assert spec.shape == out.shape

    def test_conv_grad(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(2, 6, 6, 2), jnp.float32)
        layer = nn.Conv2D(3, 3)
        params, state = layer.init(rng, ShapeSpec(x.shape))
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(layer.apply(p, {}, x)[0])), params,
            eps=1e-2, rtol=6e-2,
        )


class TestBatchNorm:
    def test_state_updates_in_training(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(16, 4) + 3.0, jnp.float32)
        layer = nn.BatchNorm()
        params, state = layer.init(rng, ShapeSpec(x.shape))
        _, new_state = layer.apply(params, state, x, training=True)
        assert not np.allclose(np.asarray(new_state["mean"]), 0.0)
        _, eval_state = layer.apply(params, state, x, training=False)
        np.testing.assert_allclose(np.asarray(eval_state["mean"]), 0.0)

    def test_sequential_merges_state(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(8, 4), jnp.float32)
        net = nn.Sequential([nn.Dense(4, name="d"), nn.BatchNorm(name="bn")])
        params, state = net.init(rng, ShapeSpec(x.shape))
        _, new_state = net.apply(params, state, x, training=True)
        merged = merge_state(state, new_state)
        assert "bn" in merged and "mean" in merged["bn"]


class TestDropout:
    def test_eval_identity(self, rng, np_rng):
        x = jnp.asarray(np_rng.randn(4, 4), jnp.float32)
        layer = nn.Dropout(0.5)
        params, state = layer.init(rng, ShapeSpec(x.shape))
        out, _ = layer.apply(params, state, x, training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_train_zeroes_and_scales(self, rng, np_rng):
        x = jnp.ones((1000,), jnp.float32)
        layer = nn.Dropout(0.5)
        out, _ = layer.apply({}, {}, x, training=True, rng=rng)
        frac_zero = float(jnp.mean((out == 0).astype(jnp.float32)))
        assert 0.4 < frac_zero < 0.6
        nonzero = np.asarray(out)[np.asarray(out) != 0]
        np.testing.assert_allclose(nonzero, 2.0, rtol=1e-6)


class TestEmbedding:
    def test_lookup(self, rng):
        layer = nn.Embedding(10, 4)
        params, state = layer.init(rng, ShapeSpec((2, 3), jnp.int32))
        ids = jnp.asarray([[1, 2, 3], [4, 5, 6]])
        out, _ = layer.apply(params, state, ids)
        assert out.shape == (2, 3, 4)
        np.testing.assert_allclose(
            np.asarray(out[0, 0]), np.asarray(params["table"][1])
        )
