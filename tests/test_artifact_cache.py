"""AOT serving artifacts + persistent compile cache (ROADMAP item 3).

The fleet cold-start contract, proven at test size:

- an engine served THROUGH the exported artifact bundle is
  bit-identical to the jit path (greedy, including speculative) —
  an artifact may be slower to build, never different;
- a manifest mismatch (bucket shape, jax version) degrades to the
  jit path with `artifact_fallbacks` counted and a flight event,
  never a wrong answer and never a failed boot;
- a corrupt persistent-cache entry is a MISS (recompile), not an
  error;
- a fresh process against a warm cache dir reaches steady-state
  serving with zero RecompileGuard compile events after its warmup
  round and zero cache misses — the restart the cache exists for.

The cold-start *numbers* live in `bench.py --serving-only`
(cold-start stage); this file is the correctness side. Everything
here is CPU-fast and runs IN tier-1; `-m aot` (or
`scripts/perf_smoke.sh aot`) runs the lane alone.
"""

import json
import os
import subprocess
import sys
import tarfile
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import compilation_cache
from paddle_tpu.models import transformer as T
from paddle_tpu.obs.flight import FlightRecorder
from paddle_tpu.serve.artifact import (ArtifactMismatchError,
                                       load_engine_artifact,
                                       save_engine_artifact)
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.server import ServingServer

pytestmark = pytest.mark.aot

ROOT = Path(__file__).resolve().parents[1]

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")
GEOM = dict(slots=2, max_len=64, page_size=16, num_pages=8)
BUCKETS = (32,)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


def mk_engine(params):
    return DecodeEngine(params, CFG, **GEOM)


@pytest.fixture(scope="module")
def bundle(params, tmp_path_factory):
    """One exported engine bundle shared by the whole module — the
    export itself (trace + serialize, no compile) is the slow part."""
    path = str(tmp_path_factory.mktemp("art") / "engine.tar")
    save_engine_artifact(mk_engine(params), path, buckets=BUCKETS)
    return path


@pytest.fixture(scope="module")
def eng_art(params, bundle):
    """One artifact-adopted engine shared by the parity tests (same
    amortization as test_serve_server's module-scoped engines)."""
    return mk_engine(params)


def _prompts(seed, lens):
    r = np.random.RandomState(seed)
    return [r.randint(0, 61, (l,)).astype(np.int32) for l in lens]


def _serve(srv, prompts, max_new, **submit_kw):
    ids = [srv.submit(p, max_new=max_new, **submit_kw) for p in prompts]
    res = srv.run()
    for rid in ids:
        assert res[rid].outcome == "completed"
    return [res[rid].tokens for rid in ids]


# -- round-trip parity -----------------------------------------------------

def test_roundtrip_greedy_parity(params, bundle, eng_art):
    """Greedy serve through the bound artifact programs is
    bit-identical to the jit path, with the adoption counters
    proving the artifact actually served (loads=1, fallbacks=0 —
    any bound program that failed would have been dropped and
    counted)."""
    srv_jit = ServingServer(mk_engine(params), max_queue=8,
                            buckets=BUCKETS)
    srv_art = ServingServer(eng_art, max_queue=8, buckets=BUCKETS,
                            artifact_path=bundle)
    assert eng_art.artifact_loads == 1
    assert eng_art.artifact_fallbacks == 0
    assert eng_art._artifact is not None

    # 3 < page_size exercises the sub-page path; 20 pads into the 32
    # bucket; two requests overlap in flight across the 2 slots
    prompts = _prompts(seed=1, lens=[3, 20, 9])
    toks_jit = _serve(srv_jit, prompts, max_new=8)
    toks_art = _serve(srv_art, prompts, max_new=8)
    assert toks_jit == toks_art
    assert eng_art.artifact_fallbacks == 0
    c = srv_art.counters()
    assert c["artifact_loads"] == 1
    assert c["artifact_fallbacks"] == 0


@pytest.mark.slow


def test_roundtrip_speculative_parity(params, bundle, eng_art):
    """Speculative serving (draft + one-launch verify via the
    exported spec program) stays greedy-bit-identical to the plain
    jit path on the n-gram proposer's win case: repetitive prompts
    whose drafts actually land."""
    assert "spec" in eng_art._artifact
    srv_jit = ServingServer(mk_engine(params), max_queue=8,
                            buckets=BUCKETS, speculative=True)
    srv_art = ServingServer(eng_art, max_queue=8, buckets=BUCKETS,
                            speculative=True, artifact_path=bundle)
    base = _prompts(seed=2, lens=[6])[0]
    prompts = [np.concatenate([base] * 4)[:l] for l in (20, 24)]
    toks_jit = _serve(srv_jit, prompts, max_new=10)
    toks_art = _serve(srv_art, prompts, max_new=10)
    assert toks_jit == toks_art
    assert eng_art.artifact_fallbacks == 0


# -- manifest-mismatch fallback --------------------------------------------

def _ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jnp.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def _fallback_events(flight):
    return [e for e in flight.events()
            if e["kind"] == "artifact" and e["name"] == "fallback"]


def test_bucket_mismatch_falls_back_to_jit(params, bundle):
    """A bundle exported for different prefill buckets must NOT be
    adopted: the padded-prefill shapes it contains are wrong for this
    server. Boot succeeds on the jit path with the fallback counted
    and flight-recorded, and the served tokens are still correct."""
    flight = FlightRecorder()
    eng = mk_engine(params)
    srv = ServingServer(eng, max_queue=8, buckets=(16, 32),
                        flight=flight, artifact_path=bundle)
    assert eng.artifact_loads == 0
    assert eng.artifact_fallbacks == 1
    assert eng._artifact is None
    evs = _fallback_events(flight)
    assert len(evs) == 1
    assert evs[0]["member"] == "load"
    assert "bucket" in evs[0]["error"]
    c = srv.counters()
    assert c["artifact_fallbacks"] == 1

    prompt = _prompts(seed=3, lens=[5])[0]
    toks = _serve(srv, [prompt], max_new=6)
    assert toks[0] == _ref_tokens(params, prompt, 6)


def test_jax_version_mismatch_falls_back(params, bundle, tmp_path):
    """A bundle whose manifest names a different jax version is
    refused (ArtifactMismatchError on direct load; counted fallback
    through the server boot path) — versioned artifacts are never
    trusted across the toolchain that produced them."""
    tampered = str(tmp_path / "tampered.tar")
    with tarfile.open(bundle) as tf:
        members = {m.name: tf.extractfile(m).read()
                   for m in tf.getmembers() if m.isfile()}
    man = json.loads(members["manifest.json"])
    man["jax_version"] = "0.0.0-bogus"
    members["manifest.json"] = json.dumps(man).encode()
    with tarfile.open(tampered, "w") as tf:
        for name, data in members.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            import io
            tf.addfile(info, io.BytesIO(data))

    eng = mk_engine(params)
    with pytest.raises(ArtifactMismatchError, match="jax_version"):
        load_engine_artifact(eng, tampered, expect_buckets=BUCKETS)

    flight = FlightRecorder()
    ServingServer(eng, max_queue=8, buckets=BUCKETS, flight=flight,
                  artifact_path=tampered)
    assert eng.artifact_loads == 0
    assert eng.artifact_fallbacks == 1
    evs = _fallback_events(flight)
    assert len(evs) == 1
    assert "jax_version" in evs[0]["error"]


# -- persistent compile cache ----------------------------------------------

def test_corrupt_cache_entry_degrades_to_miss(tmp_path):
    """Garbage bytes where a cache entry should be cost ONE recompile
    and produce the right answer — `enable()` pins
    jax_raise_persistent_cache_errors=False so a truncated write from
    a killed process can never take a replica down."""
    try:
        d = compilation_cache.enable(str(tmp_path / "xla"))
        f = jax.jit(lambda x: x * 3.0 + 1.0)
        x = jnp.arange(17.0, dtype=jnp.float32)
        expect = np.asarray(jax.device_get(f(x)))
        entries = [p for p in Path(d).rglob("*") if p.is_file()]
        assert entries, "compile produced no persistent-cache entry"
        for p in entries:
            p.write_bytes(b"\x00garbage\xff" * 7)
        jax.clear_caches()
        compilation_cache.reset_counters()
        got = np.asarray(jax.device_get(f(x)))   # must not raise
        np.testing.assert_array_equal(got, expect)
        c = compilation_cache.counters()
        assert c["hits"] == 0
        assert c["misses"] >= 1
    finally:
        compilation_cache.disable()
        compilation_cache.reset_counters()


_WARM_CHILD = """
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from paddle_tpu import compilation_cache
from paddle_tpu.analysis.guards import RecompileGuard
from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.server import ServingServer

compilation_cache.enable(sys.argv[1])
cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")
params = T.init_params(jax.random.key(0), cfg)
eng = DecodeEngine(params, cfg, slots=2, max_len=64, page_size=16,
                   num_pages=8)
srv = ServingServer(eng, max_queue=8, buckets=(32,))
p1, p2 = (np.random.RandomState(s).randint(0, 61, (7,)).astype(np.int32)
          for s in (3, 4))
srv.submit(p1, max_new=3)
srv.run()                       # warmup: every compile happens here
with RecompileGuard(name="warm serve steady state") as g:
    rid = srv.submit(p2, max_new=3)   # fresh prompt, same bucket
    res = srv.run()
print(json.dumps({"guard_compiles": g.compiles,
                  "tokens": list(res[rid].tokens),
                  **compilation_cache.counters()}))
"""


def _run_warm_child(cache_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(ROOT) + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run([sys.executable, "-c", _WARM_CHILD, cache_dir],
                         capture_output=True, text=True, timeout=240,
                         env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    return json.loads(line)


@pytest.mark.slow


def test_subprocess_cache_warm_zero_recompiles(tmp_path):
    """The restart the cache exists for: a SECOND fresh process
    against the same cache dir serves with zero cache misses, and
    both processes are compile-free after their warmup round (the
    RecompileGuard would make the child exit nonzero on any
    steady-state compile)."""
    d = str(tmp_path / "xla")
    first = _run_warm_child(d)
    second = _run_warm_child(d)
    assert first["guard_compiles"] == 0
    assert second["guard_compiles"] == 0
    assert first["misses"] > 0           # cold run populated the cache
    assert second["hits"] > 0            # warm run read it back
    assert second["misses"] == 0
    assert first["tokens"] == second["tokens"]


# -- train-step AOT --------------------------------------------------------

def test_aot_compile_train_step_matches_jit(params):
    """`aot_compile_train_step` front-loads the compile and the
    resulting executable takes one numerically-identical step."""
    from paddle_tpu import models, optim, parallel
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.state import TrainState
    from paddle_tpu.train.trainer import make_train_step

    model = models.lenet.mlp(10, hidden=(16,))
    opt = optim.sgd(0.1)
    rng = jax.random.key(0)
    p, mstate = model.init(rng, ShapeSpec((4, 28, 28, 1)))

    def loss_fn(logits, labels):
        return jnp.mean(losses.softmax_cross_entropy(logits, labels))

    x = jnp.asarray(np.random.RandomState(0)
                    .rand(4, 28, 28, 1).astype(np.float32))
    y = jnp.asarray(np.random.RandomState(1).randint(0, 10, 4))

    step = make_train_step(model, loss_fn, opt, donate=False)
    state = TrainState.create(p, mstate, opt)
    compiled = parallel.aot_compile_train_step(
        step, state, rng, (x,), (y,))
    s_aot, loss_aot, _ = compiled(state, rng, (x,), (y,))
    s_jit, loss_jit, _ = step(state, rng, (x,), (y,))
    np.testing.assert_array_equal(float(loss_aot), float(loss_jit))
    w_aot = np.asarray(jax.device_get(s_aot.params["fc1"]["kernel"]))
    w_jit = np.asarray(jax.device_get(s_jit.params["fc1"]["kernel"]))
    np.testing.assert_array_equal(w_aot, w_jit)
