"""Cross-process serving fleet: socket transport + supervisor chaos.

The process-fleet claim (docs/RELIABILITY.md "Process-fleet fault
model"), proven at three depths:

- **Wire + transport edge cases** — the shared framing helper
  (`paddle_tpu.wire`) against scripted sockets (EINTR, short reads,
  the cap rejected BEFORE allocation, truncation mid-payload), and
  the replica RPC surface against real sockets (tag-replay
  idempotence, result redelivery until ACKed, garbage bytes answered
  in-band, connect-loss vs mid-flight-loss told apart).
- **Supervisor mechanics in-process** — the `spawn` seam swaps real
  children for duck types, so autoscale out/in, below-floor repair,
  submit failover, and rolling upgrades run in milliseconds.
- **The real thing** — actual spawned replica processes booted from a
  PR9 engine artifact: a supervisor SIGKILLed without drain leaves no
  orphans (the parent-death watchdog alone), and THE chaos
  heavyweight SIGKILLs a replica process mid-burst and asserts
  exactly-once outcomes, intact retry budgets, reconciled fleet
  counters, bit-exact greedy parity, and the below-floor respawn.
"""

import os
import pickle
import signal
import socket
import struct
import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.fleet import (EXIT_ORPHANED, AutoscalePolicy,
                                    FleetSupervisor, ReplicaSpec)
from paddle_tpu.serve.router import (QueueFullError, ReplicaDeadError,
                                     ServingRouter)
from paddle_tpu.serve.server import ServingServer
from paddle_tpu.testing.faults import FaultPlan
from paddle_tpu.testing.fleet import TINY, _IdleServer, save_tiny_artifact
from paddle_tpu.serve.transport import (ProcessReplica, ReplicaClient,
                                        ReplicaTransportServer,
                                        TransportCallError,
                                        TransportConnectError)
from paddle_tpu.wire import MAX_FRAME, recv_frame, recv_full, send_frame

pytestmark = [pytest.mark.fleet, pytest.mark.faults]

CFG = T.TransformerConfig(**TINY)

#: env every replica child gets: the parent conftest pins cpu +
#: 8 virtual devices, children re-assert cpu and need only 1
CHILD_ENV = {"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


@pytest.fixture(scope="module")
def engines(params):
    """Two warmed engines for the in-process transport / upgrade
    tests (two, because an old and a new replica are live at once
    during a rolling upgrade and may not share slot state)."""
    engs = [DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4)
            for _ in range(2)]
    warm = np.arange(5, dtype=np.int32)
    for e in engs:
        e.serve([warm], max_new=2, buckets=(16,))
    return engs


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def mk_prompts(n, seed=5):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, CFG.vocab, (4 + i % 5,)).astype(np.int32)
            for i in range(n)]


# ---------------------------------------------------------------------------
# wire framing (the shared helper all three protocols adopted)


class FakeSock:
    """Scripted `recv`: each entry is bytes handed back once (short
    reads by construction) or an exception instance raised in place."""

    def __init__(self, script):
        self.script = list(script)
        self.sent = b""

    def recv(self, n):
        if not self.script:
            return b""
        item = self.script.pop(0)
        if isinstance(item, Exception):
            raise item
        assert len(item) <= n
        return item

    def sendall(self, b):
        self.sent += bytes(b)


def test_wire_roundtrip_over_real_socket():
    a, b = socket.socketpair()
    try:
        payload = b"x" * 70000        # several recv() chunks
        send_frame(a, payload)
        assert recv_frame(b) == payload
    finally:
        a.close()
        b.close()


def test_wire_oversized_rejected_before_allocation():
    sock = FakeSock([struct.pack("<I", MAX_FRAME + 1)])
    with pytest.raises(ConnectionError, match="exceeds the"):
        recv_frame(sock)
    assert sock.script == []          # nothing read past the header


def test_wire_send_refuses_oversized():
    sock = FakeSock([])
    with pytest.raises(ValueError, match="refusing to send"):
        send_frame(sock, b"xx", max_frame=1)
    assert sock.sent == b""


def test_wire_eintr_and_short_reads():
    import errno
    sock = FakeSock([
        InterruptedError(),                   # EINTR on the header
        struct.pack("<I", 5)[:2],             # short header read
        struct.pack("<I", 5)[2:],
        OSError(errno.EINTR, "interrupted"),  # EINTR mid-payload
        b"he", b"llo",                        # short payload reads
    ])
    assert recv_frame(sock) == b"hello"


def test_wire_truncated_mid_payload():
    sock = FakeSock([struct.pack("<I", 5), b"he"])
    with pytest.raises(ConnectionError, match="mid-frame"):
        recv_frame(sock)


def test_wire_peer_closed_before_header():
    with pytest.raises(ConnectionError, match="peer closed"):
        recv_full(FakeSock([]), 4)


# ---------------------------------------------------------------------------
# transport RPC surface (in-thread server, real sockets)


@pytest.fixture
def transport(engines):
    """A real `ServingServer` behind an in-thread transport, plus a
    raw client. Torn down per test so idempotency ledgers and queue
    state never leak between tests."""
    srv = ServingServer(engines[0], max_queue=8, max_retries=2,
                        buckets=(16,))
    ts = ReplicaTransportServer(srv).start()
    client = ReplicaClient(ts.addr, connect_timeout=2.0,
                           io_timeout=30.0)
    yield ts, srv, client
    ts.shutdown()


def _submit_kwargs(prompt, tag="t-a", max_new=2):
    return dict(tag=tag, prompt=np.asarray(prompt, np.int32),
                max_new=max_new, deadline_ms=-1, sampling=None,
                retries_left=None, trace_id=None)


def test_submit_tag_replay_is_idempotent(transport):
    ts, srv, client = transport
    st1, rid1, state1 = client.call("submit",
                                    _submit_kwargs([1, 2, 3]))
    # the retry of a lost reply: same tag, same bytes
    st2, rid2, state2 = client.call("submit",
                                    _submit_kwargs([1, 2, 3]))
    assert (st1, st2) == ("ok", "ok")
    assert rid1 == rid2
    assert state2["counters"]["requests"] == 1   # never double-admitted


def test_submit_rejection_replays_the_same_verdict(transport):
    ts, srv, client = transport
    bad = _submit_kwargs(np.arange(40, dtype=np.int32) % CFG.vocab,
                         tag="t-bad")            # 40 > max_len=32
    st1, err1, _ = client.call("submit", bad)
    st2, err2, _ = client.call("submit", bad)
    assert (st1, st2) == ("err", "err")
    assert isinstance(err1, ValueError) and isinstance(err2, ValueError)
    # the cached verdict carries the SAME ledgered req_id
    assert getattr(err1, "req_id", None) == getattr(err2, "req_id",
                                                    None)


def test_results_redelivered_until_acked(transport):
    ts, srv, client = transport
    _, rid, _ = client.call("submit", _submit_kwargs([4, 5, 6]))
    state = None
    for _ in range(64):
        _, _, state = client.call("step")
        if rid in state["results"]:
            break
    assert rid in state["results"]
    # un-ACKed: every later reply redelivers it
    _, _, state = client.call("sync")
    assert rid in state["results"]
    # ACKed: gone from the next state block
    _, _, state = client.call("sync", acks=(rid,))
    assert rid not in state["results"]


def test_garbage_bytes_answered_in_band(transport):
    ts, srv, client = transport
    sock = socket.create_connection(ts.addr, timeout=5.0)
    try:
        send_frame(sock, b"\x80\x04 this is not a pickle")
        status, payload, state = pickle.loads(recv_frame(sock))
        assert status == "err"
        assert "undecodable" in str(payload)
        # the connection is dropped after a desynced-content frame
        assert sock.recv(1) == b""
    finally:
        sock.close()
    # the server survives and serves fresh connections
    assert client.call("ping")[0] == "ok"


def test_truncated_frame_does_not_kill_the_server(transport):
    ts, srv, client = transport
    sock = socket.create_connection(ts.addr, timeout=5.0)
    sock.sendall(struct.pack("<I", 100) + b"only ten b")
    sock.close()                      # peer closes mid-frame
    assert client.call("ping")[0] == "ok"


def test_oversized_frame_rejected_without_allocation(transport):
    ts, srv, client = transport
    sock = socket.create_connection(ts.addr, timeout=5.0)
    try:
        sock.sendall(struct.pack("<I", MAX_FRAME + 1))
        # the server refuses the header and closes; it never tries to
        # read (or allocate) the advertised 1 GiB body
        assert sock.recv(1) == b""
    finally:
        sock.close()
    assert client.call("ping")[0] == "ok"


def test_connect_loss_vs_midflight_loss():
    # CONNECT exhaustion: nothing listening — the op never ran
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_addr = probe.getsockname()
    probe.close()                     # port now has no listener
    client = ReplicaClient(dead_addr, connect_timeout=0.2,
                           retries=2, sleep=lambda s: None)
    with pytest.raises(TransportConnectError):
        client.call("ping")

    # MID-FLIGHT loss: the peer accepts, reads, then hangs — the op
    # may or may not have executed, and the error must say so
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    hung = []

    def black_hole():
        for _ in range(2):
            try:
                conn, _ = lst.accept()
            except OSError:
                return
            hung.append(conn)         # never reply

    t = threading.Thread(target=black_hole, daemon=True)
    t.start()
    try:
        client = ReplicaClient(lst.getsockname(), connect_timeout=2.0,
                               io_timeout=0.1, retries=2,
                               sleep=lambda s: None)
        with pytest.raises(TransportCallError):
            client.call("ping")
    finally:
        lst.close()
        for c in hung:
            c.close()


def test_double_handoff_complete_releases_once(transport):
    ts, srv, client = transport
    calls = []
    srv.handoff_complete = lambda rid: calls.append(("complete", rid))
    srv.cancel_handoff = lambda rid: calls.append(("cancel", rid))
    assert client.call("handoff_complete", dict(req_id=7))[0] == "ok"
    # the ACK replay (reply lost, destination resends): no-op
    assert client.call("handoff_complete", dict(req_id=7))[0] == "ok"
    # a stale cancel racing the completed handoff: also suppressed
    assert client.call("cancel_handoff", dict(req_id=7))[0] == "ok"
    assert calls == [("complete", 7)]


def test_process_replica_mirror_ledger(transport):
    """The router-side mirror answers the harvest surfaces without
    RPC and keeps the budgets the redistribution path carries."""
    ts, srv, client = transport
    rep = ProcessReplica(client)
    prompts = mk_prompts(2, seed=9)
    rids = [rep.submit(p, max_new=2) for p in prompts]
    pending = rep.pending_requests()
    assert [r.req_id for r in pending] == sorted(rids)
    assert all(r.retries_left == rep.max_retries for r in pending)
    for _ in range(64):
        if all(r in rep.results for r in rids):
            break
        rep.step()
    assert all(rep.results[r].outcome == "completed" for r in rids)
    assert rep.pending_requests() == []          # mirror drained
    assert rep.counters()["completed"] == 2
    # withdraw pops the mirror: submit-then-withdraw leaves no ghost
    rid = rep.submit(prompts[0], max_new=2)
    req = rep.withdraw_queued(rid)
    assert req is not None and req.req_id == rid
    assert rep.pending_requests() == []


# ---------------------------------------------------------------------------
# supervisor mechanics (spawn seam — no processes, no model)


class SeamServer(_IdleServer):
    """Idle replica duck type with a scriptable load and a shutdown
    counter — enough surface for the autoscaler and reap paths."""

    def __init__(self):
        super().__init__()
        self.live_load = 0
        self.shutdowns = 0

    def load(self):
        return self.live_load

    def shutdown(self):
        self.shutdowns += 1


class DyingSeam(SeamServer):
    def __init__(self):
        super().__init__()
        self.die = False

    def step(self):
        if self.die:
            raise ReplicaDeadError("seam replica killed")
        return False


def _seam_spec():
    return ReplicaSpec(builder="unused:unused")


def test_autoscale_out_under_load_and_back_to_floor():
    spawned = []

    def seam(spec):
        s = SeamServer()
        spawned.append(s)
        return s

    sup = FleetSupervisor(
        _seam_spec(), min_replicas=1, max_replicas=3,
        policy=AutoscalePolicy(queue_high=1.0, cooldown_sweeps=2,
                               idle_sweeps=3),
        spawn=seam)
    sup.start()
    spawned[0].live_load = 4          # the spike
    for _ in range(8):
        sup.sweep()
    assert sup.counters()["replicas_routable"] == 3   # hit the ceiling
    assert sup.stats["scale_out_events"] == 2
    for s in spawned:                 # the spike subsides
        s.live_load = 0
    for _ in range(30):
        sup.sweep()
    assert sup.counters()["replicas_routable"] == 1   # back to floor
    assert sup.stats["scale_in_events"] == 2
    assert sup.stats["reaped"] == 2
    assert sup.router.stats["replicas_reaped"] == 2
    # retired members were shut down exactly once, floor member never
    assert [s.shutdowns for s in spawned] == [0, 1, 1]
    sup.shutdown(drain=False)


def test_below_floor_repair_skips_cooldown():
    spawned = []

    def seam(spec):
        s = DyingSeam()
        spawned.append(s)
        return s

    sup = FleetSupervisor(
        _seam_spec(), min_replicas=2, max_replicas=3,
        policy=AutoscalePolicy(cooldown_sweeps=1000),   # cooldown huge
        spawn=seam)
    sup.start()
    sup.sweep()                       # healthy tick (starts cooldown)
    spawned[0].die = True
    sup.sweep()                       # death harvested + repaired
    assert sup.router.stats["replicas_lost"] == 1
    # repair bypassed the 1000-sweep cooldown: floor restored NOW
    assert sup.counters()["replicas_routable"] == 2
    assert sup.stats["scale_out_events"] == 1
    sup.shutdown(drain=False)


class AcceptingSeam(SeamServer):
    _next = [0]

    def __init__(self):
        super().__init__()
        self.submitted = []

    @property
    def queue_space(self):
        return 8

    def submit(self, prompt, **kwargs):
        self._next[0] += 1
        self.submitted.append(self._next[0])
        self.live_load += 1
        return self._next[0]


class FatalOnSubmit(AcceptingSeam):
    def submit(self, prompt, **kwargs):
        raise ReplicaDeadError("transport lost on submit")


def test_submit_fails_over_when_the_picked_replica_is_dead():
    """The router's submit retry loop: a replica-fatal failure during
    admission marks the replica dead and re-picks a survivor instead
    of surfacing the loss to the caller."""
    bad, good = FatalOnSubmit(), AcceptingSeam()
    good.live_load = 1                # least-loaded pick lands on bad
    router = ServingRouter([bad, good])
    rr = router.submit(np.arange(3, dtype=np.int32), max_new=2)
    assert good.submitted             # the survivor admitted it
    assert router.stats["replicas_lost"] == 1
    assert rr in router.replicas[1].pending.values()


@pytest.mark.slow


def test_rolling_upgrade_zero_sheds(engines, params):
    built = []

    def seam(spec):
        srv = ServingServer(engines[len(built) % 2], max_queue=8,
                            max_retries=1, buckets=(16,))
        built.append(srv)
        return srv

    sup = FleetSupervisor(_seam_spec(), min_replicas=1,
                          max_replicas=2, spawn=seam)
    sup.start()
    prompts = mk_prompts(3, seed=11)
    rids = [sup.submit(p, max_new=3) for p in prompts]
    sup.sweep()                       # get work in flight on the old
    sup.rolling_upgrade(_seam_spec())
    res = sup.run()
    sup.reconcile()
    c = sup.router.counters()
    assert c["shed"] == 0 and c["completed"] == 3
    assert sup.stats["upgrades"] == 1 and sup.stats["reaped"] == 1
    assert len(built) == 2            # replacement spawned exactly once
    for p, rid in zip(prompts, rids):
        assert res[rid].outcome == "completed"
        assert res[rid].tokens == ref_tokens(params, p, 3)
    sup.shutdown(drain=False)


# ---------------------------------------------------------------------------
# real processes


def _proc_gone(pid):
    """True when `pid` is dead (missing or a zombie awaiting reap)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
    except (FileNotFoundError, ProcessLookupError):
        return True
    return state == "Z"


def _await(cond, timeout_s=20.0, poll_s=0.1):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(poll_s)
    return cond()


def test_supervisor_sigkill_leaves_no_orphan_children():
    """Kill the SUPERVISOR (not a replica) with SIGKILL — no drain,
    no atexit — and assert every replica child exits on the
    parent-death watchdog alone. This is the orphan-leak fix: before
    the watchdog, children kept serving into the void."""
    import multiprocessing
    from paddle_tpu.testing.fleet import orphan_fleet_main

    ctx = multiprocessing.get_context("spawn")
    parent_conn, child_conn = ctx.Pipe()
    sup_proc = ctx.Process(target=orphan_fleet_main,
                           args=(child_conn,))
    sup_proc.start()
    child_conn.close()
    assert parent_conn.poll(60.0), "supervisor never reported pids"
    grandchildren = parent_conn.recv()
    assert len(grandchildren) == 2
    assert all(not _proc_gone(pid) for pid in grandchildren)
    os.kill(sup_proc.pid, signal.SIGKILL)     # no cleanup runs
    sup_proc.join(10.0)
    assert _await(lambda: all(_proc_gone(p) for p in grandchildren)), \
        f"orphaned replica processes survive: {grandchildren}"
    parent_conn.close()


@pytest.mark.slow
@pytest.mark.heavyweight
@pytest.mark.locks      # chaos lane re-run under LockOrderGuard
def test_sigkill_replica_mid_burst_exactly_once(tmp_path, params,
                                                lock_order_guard):
    """THE chaos acceptance bar, on real OS processes: 3 replica
    children booted from a PR9 artifact, one SIGKILLed mid-burst by
    `FaultPlan.wrap_fleet`. Every request must end in exactly one
    outcome, redistributed work must carry its retry budget (not burn
    it), fleet counters must reconcile across the process boundary
    (dead-banked + live sums == the router ledger), completions must
    match the solo decode bit-exactly, and the supervisor must repair
    the fleet back to its floor."""
    art = str(tmp_path / "engine.tar")
    save_tiny_artifact(art, buckets=(16,))
    spec = ReplicaSpec(
        builder="paddle_tpu.testing.fleet:build_tiny_server",
        kwargs=dict(artifact=art, buckets=(16,), max_retries=1),
        env=dict(CHILD_ENV))
    sup = FleetSupervisor(spec, min_replicas=3, max_replicas=3)
    sup.start()
    pids = [p.pid for p in sup.procs.values()]
    try:
        FaultPlan(fleet_sigkill_at=6,
                  fleet_sigkill_replica=1).wrap_fleet(sup)
        prompts = mk_prompts(9)
        rids = [sup.submit(p, max_new=4) for p in prompts]
        res = sup.run()
        sup.reconcile()               # the exactly-once audit
        c = sup.router.counters()
        # the kill landed and was harvested through the dead socket
        assert c["replicas_lost"] == 1
        assert c["redistributed"] >= 1
        # exactly one terminal outcome per request, all completed
        assert sorted(res) == sorted(rids)
        assert all(res[i].outcome == "completed" for i in rids)
        # budgets intact: redistribution is NOT a retry
        moved = [res[i] for i in rids if res[i].redistributions > 0]
        assert moved
        assert all(r.retries == 0 for r in res.values())
        # fleet counters reconcile across the process boundary
        assert c["completed"] == len(rids) == c["fleet_completed"]
        assert c["fleet_shed"] == 0 and c["fleet_failed"] == 0
        # bit-exact greedy parity with the solo decode
        for p, rid in zip(prompts, rids):
            assert res[rid].tokens == ref_tokens(params, p, 4)
        # below-floor repair: a replacement process was spawned and
        # the fleet is back at its floor
        assert sup.stats["spawned"] == 4
        assert sup.counters()["procs_alive"] == 3
    finally:
        sup.shutdown(drain=False)
    live = [p for p in pids if p is not None and not _proc_gone(p)]
    assert not live, f"replica processes outlived shutdown: {live}"
