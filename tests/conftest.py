"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's in-process multi-node simulation strategy
(reference: trainer/tests/test_TrainerOnePass.cpp:127 runs real pservers on
localhost) — here multi-chip sharding is validated on XLA's host platform
with 8 virtual devices. Must set flags before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's TPU plugin (sitecustomize) force-selects its platform
# at config level, which outranks the env var — override it back to cpu
# before any backend initializes so tests never touch the real chip.
jax.config.update("jax_platforms", "cpu")

# float64 available for numeric gradient checks (the fluid op_test.py
# approach: numeric grads in double precision); float32 remains the default
# dtype for params since initializers request it explicitly.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 fast gate "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "faults: fault-injection resilience suite "
        "(testing.faults) — fast and CPU-only, runs IN tier-1; the "
        "marker exists so `-m faults` can run recovery paths alone")
    config.addinivalue_line(
        "markers", "pserver: parameter-server fault-tolerance suite "
        "(native.pserver leases/replication/failover) — a subset of "
        "the faults lane, runs IN tier-1; `-m pserver` (or "
        "`scripts/fault_smoke.sh pserver`) runs it alone")
    config.addinivalue_line(
        "markers", "perf: CPU-runnable performance smoke lane "
        "(capacity/throughput assertions, e.g. the paged-pool 2x "
        "admission bound) — fast, runs IN tier-1; `-m perf` (or "
        "`scripts/perf_smoke.sh`) runs it alone")
    config.addinivalue_line(
        "markers", "analysis: static-analysis + compile-discipline "
        "suite (graftlint/locklint rule fixtures, the repo --check "
        "gate, RecompileGuard steady-state regressions) — fast and "
        "CPU-only, runs IN tier-1; `-m analysis` (or "
        "`scripts/lint_smoke.sh`) runs it alone")


@pytest.fixture
def rng():
    return jax.random.key(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
