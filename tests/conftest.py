"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's in-process multi-node simulation strategy
(reference: trainer/tests/test_TrainerOnePass.cpp:127 runs real pservers on
localhost) — here multi-chip sharding is validated on XLA's host platform
with 8 virtual devices. Must set flags before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's TPU plugin (sitecustomize) force-selects its platform
# at config level, which outranks the env var — override it back to cpu
# before any backend initializes so tests never touch the real chip.
jax.config.update("jax_platforms", "cpu")

# float64 available for numeric gradient checks (the fluid op_test.py
# approach: numeric grads in double precision); float32 remains the default
# dtype for params since initializers request it explicitly.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--budget-guard", type=float, default=None, metavar="SECONDS",
        help="tier-1 duration budget guard: FAIL the session when any "
             "non-slow test's call phase exceeds this many seconds "
             "(the suite runs near its 870s cap — a single creeping "
             "test eats everyone's headroom). Without the flag the "
             "guard still REPORTS offenders over the default "
             "threshold (10s) in the terminal summary.")


#: report-only threshold when --budget-guard is not passed
_BUDGET_DEFAULT_S = 10.0


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 fast gate "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "faults: fault-injection resilience suite "
        "(testing.faults) — fast and CPU-only, runs IN tier-1; the "
        "marker exists so `-m faults` can run recovery paths alone")
    config.addinivalue_line(
        "markers", "pserver: parameter-server fault-tolerance suite "
        "(native.pserver leases/replication/failover) — a subset of "
        "the faults lane, runs IN tier-1; `-m pserver` (or "
        "`scripts/fault_smoke.sh pserver`) runs it alone")
    config.addinivalue_line(
        "markers", "perf: CPU-runnable performance smoke lane "
        "(capacity/throughput assertions, e.g. the paged-pool 2x "
        "admission bound) — fast, runs IN tier-1; `-m perf` (or "
        "`scripts/perf_smoke.sh`) runs it alone")
    config.addinivalue_line(
        "markers", "analysis: static-analysis + compile-discipline "
        "suite (graftlint/locklint rule fixtures, the repo --check "
        "gate, RecompileGuard steady-state regressions) — fast and "
        "CPU-only, runs IN tier-1; `-m analysis` (or "
        "`scripts/lint_smoke.sh`) runs it alone")
    config.addinivalue_line(
        "markers", "obs: unified observability suite (obs registry/"
        "trace/flight, span exactly-once chaos audit, exporter "
        "schema) — fast and CPU-only, runs IN tier-1; `-m obs` (or "
        "`scripts/obs_smoke.sh`) runs it alone")
    config.addinivalue_line(
        "markers", "router: multi-replica serving-fleet suite "
        "(serve.router affinity/failover/redistribution chaos) — a "
        "subset of the faults lane, runs IN tier-1; `-m router` (or "
        "`scripts/fault_smoke.sh router`) runs it alone")
    config.addinivalue_line(
        "markers", "pallas: interpret-mode Pallas kernel parity suite "
        "(ragged paged-attention vs the jnp oracle, bit-identity "
        "under jit) — fast cases run IN tier-1, the heavy ragged "
        "sweeps are additionally marked slow; `-m pallas` (or "
        "`scripts/perf_smoke.sh pallas`) runs the lane alone")
    config.addinivalue_line(
        "markers", "kernels: sharded-matmul primitive suite "
        "(parallel.blocked_matmul ring/stream forms vs the jnp oracle "
        "across shard counts, pipeline tensor-parallel opt-in parity) "
        "— fast cases run IN tier-1; `-m kernels` (or "
        "`scripts/perf_smoke.sh kernels`, which adds the pallas lane "
        "and `bench.py --kernels-only`) runs the lane alone")
    config.addinivalue_line(
        "markers", "speculative: speculative-decoding suite (n-gram "
        "draft proposer, verify/commit/rollback, greedy parity vs "
        "baseline under transfer_guard) — fast, runs IN tier-1; "
        "`-m speculative` runs it alone")
    config.addinivalue_line(
        "markers", "disagg: disaggregated prefill/decode fleet suite "
        "(tiered routing, live KV-block migration, prefix seeding, "
        "migration chaos) — fast, runs IN tier-1; `-m disagg` (or "
        "`scripts/fault_smoke.sh disagg` / `scripts/perf_smoke.sh "
        "disagg`) runs it alone")
    config.addinivalue_line(
        "markers", "fleet: cross-process serving-fleet suite "
        "(serve.fleet/serve.transport: socket-transport replicas, "
        "SIGKILL chaos, elastic autoscaling, rolling upgrades, the "
        "orphan watchdog) — runs IN tier-1; `-m fleet` (or "
        "`scripts/fault_smoke.sh fleet`, which runs "
        "-m 'fleet and faults') runs it alone")
    config.addinivalue_line(
        "markers", "edge: HTTP front-door suite (serve.http_edge + "
        "testing.traffic: chunked streaming, disconnect cancellation, "
        "overload backpressure, slow-loris hardening, graceful drain) "
        "— fast cases run IN tier-1, the live-load SIGKILL chaos case "
        "is heavyweight/slow; `-m edge` (or `scripts/fault_smoke.sh "
        "edge`, which runs -m 'edge and faults' plus `bench.py "
        "--edge-only`) runs the lane alone")
    config.addinivalue_line(
        "markers", "heavyweight: the ONE deliberate chaos heavyweight "
        "a suite may carry — exempt from the tier-1 budget guard "
        "(real process boots + a mid-burst SIGKILL cannot fit the "
        "per-test threshold; everything else must)")
    config.addinivalue_line(
        "markers", "aot: AOT serving-artifact + persistent "
        "compile-cache suite (engine bundle round-trip parity, "
        "manifest-mismatch fallback, corrupt-entry miss, subprocess "
        "cache-warm restart) — fast, runs IN tier-1; `-m aot` (or "
        "`scripts/perf_smoke.sh aot`) runs it alone")
    config.addinivalue_line(
        "markers", "cluster: multi-host control-plane suite "
        "(cluster.membership lease/epoch fencing, per-host agents, "
        "standby failover, membership-resolved topology) — fast "
        "cases run IN tier-1, the real-process chaos case is "
        "heavyweight/slow; `-m cluster` (or `scripts/fault_smoke.sh "
        "cluster`) runs the lane alone")
    config.addinivalue_line(
        "markers", "elastic: elastic gang-training suite (ZeRO-"
        "sharded optimizer state, reshard-on-restore checkpoints, "
        "gang supervision chaos) — fast cases run IN tier-1, the "
        "real-process chaos cases are heavyweight/slow; `-m elastic` "
        "(or `scripts/fault_smoke.sh elastic`) runs the lane alone")
    config.addinivalue_line(
        "markers", "data: zero-copy data-plane suite "
        "(serve.shm_arena: shared-memory KV arena, orphan "
        "reclamation, stale-ticket refusal, pickle-fallback parity, "
        "batched control RPC) — fast cases run IN tier-1, the "
        "real-process SIGKILL chaos cases are heavyweight/slow; "
        "`-m data` (or `scripts/fault_smoke.sh data`, which runs "
        "-m 'data and faults' plus `bench.py --data-only`) runs the "
        "lane alone")
    config.addinivalue_line(
        "markers", "locks: graftlock concurrency suite (locklint "
        "LK002-LK005 rule fixtures, the LockOrderGuard runtime "
        "sanitizer, chaos lanes re-run under the guard) — fast and "
        "CPU-only, runs IN tier-1; `-m locks` (or "
        "`scripts/lint_smoke.sh`, which adds the `--check` gate and "
        "one fault-lane run under the guard) runs it alone")
    config.addinivalue_line(
        "markers", "ctr: tiered embedding-cache + CTR serving suite "
        "(serve.embed_cache staleness bounds / batched miss-fill / "
        "zero-recompile gather, train.online streaming exactly-once, "
        "shard-failover + reform-mid-stream chaos) — fast cases run "
        "IN tier-1; `-m ctr` (or `scripts/perf_smoke.sh ctr` / "
        "`scripts/fault_smoke.sh ctr`, which add `bench.py "
        "--ctr-only`) runs the lane alone")


def pytest_runtest_logreport(report):
    """Collect call-phase durations of tests that are NOT marked slow
    for the tier-1 budget guard (the slow lane is excluded from the
    870s gate, so only fast-lane creep matters)."""
    if report.when != "call":
        return
    keywords = getattr(report, "keywords", {})
    # `heavyweight` is the budget guard's one sanctioned exemption:
    # the chaos test that boots real replica processes and SIGKILLs
    # one mid-burst cannot meet the per-test threshold
    if "slow" in keywords or "heavyweight" in keywords:
        return
    # stash on the report's session via terminal summary access below
    _budget_records.append((report.nodeid, report.duration))


_budget_records = []


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """The tier-1 budget guard (docs: ROADMAP 'near the 870s cap'):
    list every non-slow test whose call phase ran past the threshold.
    Report-only by default; `--budget-guard S` makes offenders FAIL
    the session so the cap regression is caught at review time, and
    `scripts/lint_smoke.sh` documents the invocation."""
    limit = config.getoption("--budget-guard")
    threshold = _BUDGET_DEFAULT_S if limit is None else limit
    offenders = sorted((d, nid) for nid, d in _budget_records
                       if d > threshold)
    if not offenders:
        return
    terminalreporter.section("tier-1 budget guard")
    for d, nid in offenders:
        terminalreporter.write_line(
            f"  {d:7.1f}s  {nid}   (non-slow test over "
            f"{threshold:.0f}s — mark it `slow` or shrink it)")
    if limit is not None:
        terminalreporter.write_line(
            f"budget guard FAILING the session: {len(offenders)} "
            f"non-slow test(s) over {limit:.0f}s")


def pytest_sessionfinish(session, exitstatus):
    # computed from the raw records, not the summary stash: hook
    # ordering between this and the terminal reporter's own
    # sessionfinish is not guaranteed
    limit = session.config.getoption("--budget-guard")
    if limit is not None and any(d > limit
                                 for _, d in _budget_records):
        session.exitstatus = 1


@pytest.fixture(autouse=True, scope="session")
def _hermetic_compile_cache(tmp_path_factory):
    """Point the CLI's default persistent compile cache at a per-
    session tmp dir. In-process `cli.main(["serve"/"train"/"infer",
    ...])` calls (test_cli, test_serve_server, test_router) enable the
    cache PROCESS-GLOBALLY at DEFAULT_COMPILE_CACHE — the user-global
    ~/.cache/paddle_tpu/xla — and every later jit in the pytest
    process then reads whatever entries previous runs on the box left
    there. A stale entry deserializes into a wrong executable
    SILENTLY (observed: the HostOffloadEmbedding host-scatter update
    becoming a no-op whenever a CLI serve test ran first — a
    wrong-ANSWER ordering flake, not a crash). Tests must never read
    or write the operator's real cache; the default-enabled code path
    itself stays exercised against the fresh dir."""
    from paddle_tpu import cli

    cli.DEFAULT_COMPILE_CACHE = str(tmp_path_factory.mktemp("xla-cache"))
    yield


@pytest.fixture
def lock_order_guard():
    """Run a chaos test under the graftlock runtime sanitizer: every
    threading.Lock/RLock the test's stack creates is instrumented,
    the process-global acquisition-order graph is checked on every
    acquire, and the test FAILS (at teardown) if any order inversion
    was observed. `raise_on_violation=False` so a violation does not
    kill a worker thread mid-scenario and cascade into unrelated
    assertion noise — the teardown assert reports every recorded
    violation at once."""
    from paddle_tpu.analysis.guards import LockOrderGuard

    with LockOrderGuard(raise_on_violation=False,
                        name="chaos-lane") as g:
        yield g
    assert g.violations == [], (
        "lock-order violations under the chaos lane:\n  "
        + "\n  ".join(g.violations))


@pytest.fixture
def rng():
    return jax.random.key(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
