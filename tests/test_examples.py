"""Smoke-run every example script with tiny settings (reference analog:
the demos under v1_api_demo each ship a runnable train loop; these
assert ours keep running end-to-end — import rot, API drift, or a
broken arg surface fails here, not in a user's hands)."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")

CASES = {
    "fit_a_line.py": ["--passes", "2"],
    "mnist_train.py": ["--passes", "1", "--batch", "32"],
    "seq2seq_nmt.py": ["--steps", "30", "--batch", "8", "--vocab", "20"],
    "ctr_distributed.py": ["--steps", "5", "--batch", "64", "--slots", "4",
                           "--vocab", "1000", "--dim", "8"],
    "transformer_lm.py": ["--steps", "20", "--batch", "4", "--seq-len", "16",
                          "--dim", "32", "--layers", "1"],
    "transformer_lm.py --moe": ["--steps", "20", "--batch", "4", "--seq-len",
                                "16", "--dim", "32", "--layers", "1",
                                "--moe"],
    "serving.py": ["--steps", "30"],
    "serving.py --no-quant": ["--steps", "30", "--no-quant"],
}


#: cases whose smoke run exceeds the tier-1 duration budget (10s —
#: conftest budget guard): they run in the slow lane instead
_SLOW_CASES = {"serving.py", "serving.py --no-quant", "mnist_train.py",
               "transformer_lm.py", "transformer_lm.py --moe",
               "seq2seq_nmt.py"}


@pytest.mark.parametrize(
    "case", [pytest.param(c, marks=[pytest.mark.slow])
             if c in _SLOW_CASES else c for c in sorted(CASES)])
def test_example_runs(case):
    script = case.split()[0]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # examples must never touch the real chip from the test suite
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)] + CASES[case],
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
