"""Golden-topology tests for the model zoo (reference analog:
python/paddle/trainer_config_helpers/tests/configs/ golden-proto
comparisons + ProtobufEqualMain.cpp — a config helper change that
silently alters a topology must fail a diff against a committed
golden, not go unnoticed).

Each case builds a zoo model's parameter tree ABSTRACTLY (eval_shape —
no math runs) and compares names + shapes + total parameter count
against tests/golden/zoo_topology.json. Regenerate deliberately with:

    python tests/test_zoo_golden.py --regen
"""

import json
import math
import os
import sys

# must precede the paddle_tpu imports so the documented regen command
# (`python tests/test_zoo_golden.py --regen`) resolves the package when
# run from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import pytest

from paddle_tpu import models
from paddle_tpu.nn.module import ShapeSpec

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "zoo_topology.json")


def _layer(model, spec):
    # Layer-based zoo entries: init returns (params, state)
    return lambda rng: model.init(rng, spec)[0]


def _cases():
    from paddle_tpu.models import transformer as tf

    return {
        "lenet": _layer(models.lenet.lenet(10), ShapeSpec((4, 28, 28, 1))),
        "mlp": _layer(models.lenet.mlp(10, hidden=(64, 32)),
                      ShapeSpec((4, 28, 28, 1))),
        "smallnet": _layer(models.smallnet.smallnet(10),
                           ShapeSpec((4, 32, 32, 3))),
        "alexnet": _layer(models.alexnet.alexnet(num_classes=1000),
                          ShapeSpec((2, 224, 224, 3))),
        "googlenet": _layer(models.googlenet.googlenet(num_classes=1000),
                            ShapeSpec((2, 224, 224, 3))),
        "vgg19": _layer(models.vgg.vgg(19, num_classes=10),
                        ShapeSpec((2, 32, 32, 3))),
        "resnet18": _layer(models.resnet.resnet(18, num_classes=10),
                           ShapeSpec((2, 32, 32, 3))),
        "resnet50": _layer(models.resnet.resnet(50, num_classes=1000),
                           ShapeSpec((2, 224, 224, 3))),
        "text_lstm": lambda rng: models.text_lstm.init_params(
            rng, 1000, 2, embed_dim=32, hidden=64),
        "seq2seq_attn": lambda rng: models.seq2seq_attn.init_params(
            rng, 500, 600, embed_dim=32, hidden=48),
        "bow_lr": lambda rng: models.quick_start.init_bow_lr(rng, 1000),
        "text_cnn": lambda rng: models.quick_start.init_text_cnn(rng, 1000),
        "bidi_lstm": lambda rng: models.quick_start.init_bidi_lstm(rng, 1000),
        "transformer_small": lambda rng: tf.init_params(
            rng, tf.TransformerConfig(vocab=512, dim=64, n_layers=2,
                                      n_heads=4)),
        "transformer_moe": lambda rng: tf.init_params(
            rng, tf.TransformerConfig(vocab=512, dim=64, n_layers=2,
                                      n_heads=4, moe_experts=4)),
        "word2vec": lambda rng: models.word2vec.init_params(
            rng, 1000, embed_dim=32, hidden=64),
        "recommender": lambda rng: models.recommender.init_params(
            rng, models.recommender.RecommenderConfig(
                n_users=400, n_movies=600, title_vocab=256)),
        "srl_db_lstm": lambda rng: models.srl.init_params(
            rng, word_vocab=500, pred_vocab=50, num_labels=9, hidden=32),
    }


def _topology(build):
    params = jax.eval_shape(build, jax.random.key(0))
    flat = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        flat[name] = list(leaf.shape)
    return {
        "parameters": flat,
        "num_parameters": int(sum(
            math.prod(s) if s else 1 for s in flat.values())),
    }


@pytest.mark.parametrize("name", sorted(_cases()))
def test_zoo_topology_matches_golden(name):
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert name in golden, (
        f"no golden for {name}; regenerate: python {__file__} --regen")
    got = _topology(_cases()[name])
    exp = golden[name]
    assert got["parameters"] == exp["parameters"], (
        f"{name} topology drifted from golden "
        f"(regen deliberately if intended)")
    assert got["num_parameters"] == exp["num_parameters"]


if __name__ == "__main__":
    if "--regen" in sys.argv:
        jax.config.update("jax_platforms", "cpu")
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            json.dump({name: _topology(b) for name, b in _cases().items()},
                      f, indent=1, sort_keys=True)
        print(f"wrote {GOLDEN}")
