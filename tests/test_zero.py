"""ZeRO-sharded optimizer update (parallel.make_zero_train_step).

The oracle is bit-exactness, not allclose: both arms of
make_zero_train_step share the SAME psum_scatter reduction, and every
FirstOrder optimizer update is elementwise, so partitioning the update
across the data axis and all-gathering the params afterwards must
produce the IDENTICAL bits a replicated update produces. The memory
win (opt-state bytes per replica ~ 1/N) is asserted, not claimed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import nn
from paddle_tpu.core.mesh import MeshConfig, batch_sharding, build_mesh
from paddle_tpu.optim import optimizers as O
from paddle_tpu.parallel import (
    make_zero_train_step,
    opt_state_bytes_per_replica,
)
from paddle_tpu.parallel.sharding import replicated
from paddle_tpu.train.state import TrainState
from paddle_tpu.train.trainer import make_train_step

pytestmark = pytest.mark.elastic


def _model():
    # deliberately awkward leaf sizes (56, 7, 21, 3): every bias needs
    # zero-padding to shard over 8 replicas
    return nn.Sequential([
        nn.Dense(7, name="fc", activation="relu"),
        nn.Dense(3, name="out"),
    ])


def _loss(out, y):
    return jnp.mean((out - y) ** 2)


def _data(mesh=None):
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(16, 3).astype(np.float32)
    if mesh is None:
        return jnp.asarray(x), jnp.asarray(y)
    return (jax.device_put(x, batch_sharding(mesh)),
            jax.device_put(y, batch_sharding(mesh)))


def _replicate_opt(state, mesh):
    """The baseline arm consumes the SAME flat-padded opt layout but
    fully replicated (its update runs on the whole buffer)."""
    return state._replace(opt_state=jax.tree.map(
        lambda v: jax.device_put(np.asarray(v), replicated(mesh)),
        state.opt_state))


@pytest.mark.parametrize("opt_fn", [
    lambda: O.sgd(0.1),
    lambda: O.momentum(0.05, 0.9),
    lambda: O.adam(1e-2),
], ids=["sgd", "momentum", "adam"])
def test_zero_update_bit_exact_vs_replicated(opt_fn):
    """The tentpole oracle: sharded update == replicated update, bit
    for bit, because both arms share one psum_scatter and the update
    is elementwise. Any drift here means the two arms saw different
    gradients — a correctness bug, not a tolerance question."""
    model, opt = _model(), opt_fn()
    mesh = build_mesh(MeshConfig(data=8))
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    sz = TrainState.create_zero(params, mstate, opt, mesh)
    sb = _replicate_opt(TrainState.create_zero(params, mstate, opt,
                                               mesh), mesh)
    step_z = make_zero_train_step(model, _loss, opt, mesh, donate=False)
    step_b = make_zero_train_step(model, _loss, opt, mesh, donate=False,
                                  zero_update=False)
    x, y = _data(mesh)
    rng = jax.random.key(7)
    for _ in range(2):
        sz, lz, _ = step_z(sz, rng, x, y)
        sb, lb, _ = step_b(sb, rng, x, y)
    assert float(lz) == float(lb)
    for pa, pb in zip(jax.tree.leaves(sz.params),
                      jax.tree.leaves(sb.params)):
        assert np.array_equal(np.asarray(pa), np.asarray(pb))


def test_zero_matches_plain_train_step():
    """Cross-check against the completely independent single-device
    make_train_step (different reduction order => allclose, not ==)."""
    model, opt = _model(), O.adam(1e-2)
    mesh = build_mesh(MeshConfig(data=8))
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    sz = TrainState.create_zero(params, mstate, opt, mesh)
    sr = TrainState.create(params, mstate, opt)
    step_z = make_zero_train_step(model, _loss, opt, mesh, donate=False)
    step_r = make_train_step(model, _loss, opt, donate=False)
    xg, yg = _data(mesh)
    x, y = _data()
    rng = jax.random.key(7)
    for _ in range(3):
        sz, lz, _ = step_z(sz, rng, xg, yg)
        sr, lr, _ = step_r(sr, rng, x, y)
    np.testing.assert_allclose(float(lz), float(lr), rtol=1e-5)
    for pa, pb in zip(jax.tree.leaves(sz.params),
                      jax.tree.leaves(sr.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=1e-5, atol=1e-6)


def test_zero_opt_state_bytes_per_replica_shrink():
    """The point of ZeRO: each replica addresses ~1/N of the moment
    buffers. Measured from the arrays' addressable shards, not
    computed from the formula that produced them."""
    model, opt = _model(), O.adam(1e-2)
    mesh = build_mesh(MeshConfig(data=8))
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    sz = TrainState.create_zero(params, mstate, opt, mesh)
    sb = _replicate_opt(TrainState.create_zero(params, mstate, opt,
                                               mesh), mesh)
    bz = opt_state_bytes_per_replica(sz.opt_state)
    bb = opt_state_bytes_per_replica(sb.opt_state)
    # padding + the replicated step scalar keep it shy of exactly 8x
    assert bz * 7 < bb, (bz, bb)


@pytest.mark.analysis
def test_zero_step_steady_state_no_recompiles():
    """One warmup compile, then the jitted shard_map step must be
    recompile-free across steps (the RecompileGuard discipline every
    other step in the repo meets)."""
    from paddle_tpu.analysis.guards import RecompileGuard

    model, opt = _model(), O.momentum(0.05, 0.9)
    mesh = build_mesh(MeshConfig(data=8))
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    state = TrainState.create_zero(params, mstate, opt, mesh)
    step = make_zero_train_step(model, _loss, opt, mesh, donate=False)
    x, y = _data(mesh)
    rng = jax.random.key(7)
    state, _, _ = step(state, rng, x, y)    # warmup: the ONE compile
    with RecompileGuard(name="zero train step") as g:
        for _ in range(3):
            state, _, _ = step(state, rng, x, y)
    assert g.compiles == 0


@pytest.mark.aot
def test_zero_step_aot_compile_cache_compose(tmp_path):
    """The PR9 compose seam: aot_compile_train_step accepts the
    ZeRO step, and with the persistent compile cache enabled a FRESH
    jit object (what a reformed gang member builds after restore)
    AOT-compiles as pure cache hits — 0 misses, so a reform never
    pays a recompile storm."""
    from paddle_tpu import compilation_cache as cc
    from paddle_tpu.parallel import aot_compile_train_step

    model, opt = _model(), O.adam(1e-2)
    mesh = build_mesh(MeshConfig(data=8))
    params, mstate = model.init(jax.random.key(0),
                                jnp.zeros((8, 8), jnp.float32))
    state = TrainState.create_zero(params, mstate, opt, mesh)
    x, y = _data(mesh)
    rng = jax.random.key(7)
    cc.enable(str(tmp_path))
    try:
        warm = make_zero_train_step(model, _loss, opt, mesh,
                                    donate=False)
        aot_compile_train_step(warm, state, rng, x, y)      # writes
        cc.reset_counters()
        fresh = make_zero_train_step(model, _loss, opt, mesh,
                                     donate=False)
        compiled = aot_compile_train_step(fresh, state, rng, x, y)
        stats = cc.counters()
        assert stats["hits"] > 0 and stats["misses"] == 0, stats
        new_state, loss, _ = compiled(state, rng, x, y)
        assert np.isfinite(float(loss))
        assert int(new_state.step) == 1
    finally:
        cc.disable()
        cc.reset_counters()
