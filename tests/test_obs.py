"""Unified observability layer: registry, tracing, flight recorder.

Three layers under test, separately and then composed under chaos:

- `obs.registry.MetricsRegistry`: bounded-cardinality counters /
  gauges / histograms plus read-through sources, with Prometheus-text
  and JSON-lines exporters reading the SAME books `reconcile()` does.
- `obs.trace.Tracer`: request spans minted at `ServingRouter.submit`
  (`rr<N>`), ended exactly once at the terminal outcome; second ends
  and late events are tallied, never raised.
- `obs.flight.FlightRecorder`: last-N ring, dumped on faults.

THE acceptance chaos run (ISSUE 8): kill a replica mid-burst with
full instrumentation on and assert every minted rr id has exactly one
terminal span, span outcome tallies equal the fleet counters, the
replica-death flight dump on disk reconciles with the fleet ledger,
and the whole instrumented run stays clean under
`jax.transfer_guard("disallow")` — observability adds zero implicit
transfers.
"""

import json
import os

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.obs import (FlightRecorder, MetricsRegistry, Tracer,
                            sanitize_value)
from paddle_tpu.obs.flight import peek_default, set_default
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.router import ServingRouter
from paddle_tpu.serve.server import ServingServer
from paddle_tpu.testing.faults import (FaultPlan, ManualClock,
                                       garbage_prompts)

pytestmark = pytest.mark.obs


class FakeClock:
    """Deterministic injectable clock (obs components never sleep, so
    a manual tick is all the tests need)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- registry ---------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        clk = FakeClock()
        reg = MetricsRegistry(clock=clk)
        c = reg.counter("reqs_total", "requests")
        g = reg.gauge("queue_depth", "queued")
        h = reg.histogram("latency_s", "latency",
                          buckets=(0.1, 1.0))
        c.inc()
        c.inc(2, labels={"outcome": "completed"})
        g.set(7)
        h.observe(0.05)
        h.observe(5.0)
        snap = reg.snapshot()
        assert snap["ts"] == clk.t
        by_name = {}
        for s in snap["series"]:
            key = (s["name"], tuple(sorted(s["labels"].items())))
            by_name[key] = s["value"]
        assert by_name[("reqs_total", ())] == 1
        assert by_name[("reqs_total",
                        (("outcome", "completed"),))] == 2
        assert by_name[("queue_depth", ())] == 7
        assert by_name[("latency_s_count", ())] == 2
        assert by_name[("latency_s_sum", ())] == pytest.approx(5.05)
        assert by_name[("latency_s_bucket", (("le", "0.1"),))] == 1
        assert by_name[("latency_s_bucket", (("le", "+Inf"),))] == 2

    def test_same_name_returns_same_metric(self):
        reg = MetricsRegistry()
        a = reg.counter("x", "")
        b = reg.counter("x", "")
        assert a is b
        with pytest.raises(TypeError):
            reg.gauge("x", "")       # kind change is a bug, not a new metric

    def test_cardinality_bound_overflows_not_grows(self):
        reg = MetricsRegistry(max_series_per_metric=4)
        c = reg.counter("per_req", "")
        for i in range(50):
            c.inc(labels={"req": str(i)})
        rows = [s for s in reg.snapshot()["series"]
                if s["name"] == "per_req"]
        assert len(rows) <= 5         # 4 admitted + the overflow bucket
        overflow = [s for s in rows
                    if s["labels"].get("overflow") == "true"]
        assert overflow and overflow[0]["value"] == 46
        assert reg.snapshot()["dropped_series"] == 46

    def test_register_source_reads_live_books(self):
        reg = MetricsRegistry()
        stats = {"completed": 0, "alive": True, "note": "text"}
        reg.register_source("srv", lambda: dict(stats))
        stats["completed"] = 3
        vals = {s["name"]: s["value"]
                for s in reg.snapshot()["series"]}
        assert vals["srv_completed"] == 3     # read-through, not a copy
        assert vals["srv_alive"] == 1         # bool -> 0/1
        assert "srv_note" not in vals         # non-numeric dropped
        assert sanitize_value("text") is None

    def test_broken_source_counted_not_raised(self):
        reg = MetricsRegistry()

        def bad():
            raise RuntimeError("source died")

        reg.register_source("bad", bad)
        reg.counter("ok", "").inc()
        snap = reg.snapshot()
        assert snap["source_errors"] == 1
        assert any(s["name"] == "ok" for s in snap["series"])

    def test_exporters_cover_the_same_series(self):
        reg = MetricsRegistry(clock=FakeClock())
        reg.counter("a_total", "help a").inc(4)
        reg.gauge("b", "help b").set(1.5, labels={"shard": "0"})
        prom = reg.to_prometheus()
        assert "# TYPE a_total counter" in prom
        assert "a_total 4" in prom
        assert 'b{shard="0"} 1.5' in prom
        lines = [json.loads(ln) for ln in
                 reg.to_jsonl().strip().splitlines()]
        names = {ln["name"] for ln in lines if "name" in ln}
        assert {"a_total", "b"} <= names
        assert lines[-1]["meta"] == {"dropped_series": 0,
                                     "source_errors": 0}


# -- tracer -----------------------------------------------------------------


class TestTracer:
    def test_span_lifecycle_and_duration(self):
        clk = FakeClock()
        tr = Tracer(clock=clk)
        span = tr.start("rr1", "fleet.request", rr_id=1)
        clk.advance(0.5)
        span.event("admitted", replica=2)
        clk.advance(0.5)
        tr.end("rr1", "completed", replica=2)
        assert span.duration() == pytest.approx(1.0)
        assert span.outcome == "completed"
        assert span.events[0]["name"] == "admitted"
        assert tr.counters() == {
            "spans_started": 1, "spans_ended": 1, "spans_live": 0,
            "double_ends": 0, "late_events": 0}

    def test_double_end_tallied_never_raises(self):
        tr = Tracer(clock=FakeClock())
        span = tr.start("rr1", "x")
        tr.end("rr1", "completed")
        tr.end(span, "failed")        # a second end must not flip it
        assert span.outcome == "completed"
        assert tr.counters()["double_ends"] == 1
        assert tr.terminal_outcomes() == {"rr1": ["completed"]}

    def test_late_event_is_noop(self):
        tr = Tracer(clock=FakeClock())
        span = tr.start("rr1", "x")
        tr.end("rr1", "completed")
        span.event("straggler")       # stale hook after terminal
        assert span.events == []
        assert tr.counters()["late_events"] == 1

    def test_restart_live_id_does_not_fork(self):
        tr = Tracer(clock=FakeClock())
        a = tr.start("rr1", "x")
        b = tr.start("rr1", "x")      # instrumentation bug: same id
        assert a is b and a.tags["respan"] == 1
        assert tr.counters()["spans_started"] == 1

    def test_sink_receives_finished_spans(self):
        fr = FlightRecorder(clock=FakeClock())
        tr = Tracer(clock=FakeClock(), sink=fr.note_span)
        tr.start("rr1", "x")
        tr.end("rr1", "completed")
        evts = fr.events()
        assert len(evts) == 1 and evts[0]["kind"] == "span"
        assert evts[0]["span"]["tags"]["outcome"] == "completed"


# -- flight recorder --------------------------------------------------------


class TestFlightRecorder:
    def test_ring_keeps_last_n(self):
        fr = FlightRecorder(capacity=4, clock=FakeClock())
        for i in range(10):
            fr.record("pool", "admit", seq=i)
        evts = fr.events()
        assert [e["seq"] for e in evts] == [6, 7, 8, 9]
        assert fr.counters() == {"events": 4, "recorded": 10,
                                 "dumps": 0}

    def test_dump_to_dir_is_loadable_json(self, tmp_path):
        fr = FlightRecorder(clock=FakeClock())
        fr.record("fault", "replica-death", replica=1)
        path = fr.dump(str(tmp_path), "replica-death-r1",
                       extra={"counters": {"requests": 5}})
        assert path and os.path.dirname(path) == str(tmp_path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["kind"] == "flight_dump"
        assert payload["reason"] == "replica-death-r1"
        assert payload["n_events"] == 1
        assert payload["extra"]["counters"]["requests"] == 5
        assert fr.last_dump_path == path

    def test_dump_failure_returns_none(self, tmp_path):
        fr = FlightRecorder()
        bad = tmp_path / "f"
        bad.write_text("")
        # a FILE where a directory component is expected: open fails,
        # dump swallows it (fault paths must not raise from telemetry)
        assert fr.dump(str(bad / "sub" / "x.json"), "r") is None

    def test_module_default_is_peek_only(self):
        prev = peek_default()
        try:
            set_default(None)
            assert peek_default() is None   # no allocation on peek
            fr = FlightRecorder()
            set_default(fr)
            assert peek_default() is fr
        finally:
            set_default(prev)


# -- the chaos audit: spans exactly-once, dump reconciles -------------------

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def engines():
    params = T.init_params(jax.random.key(0), CFG)
    engs = [DecodeEngine(params, CFG, slots=2, max_len=32,
                         page_size=4)
            for _ in range(3)]
    warm = np.arange(11, dtype=np.int32)
    for e in engs:
        e.serve([warm], max_new=2, buckets=(16,))
    return engs


def family_prompts(n, seed, n_families=3):
    r = np.random.RandomState(seed)
    prefixes = [r.randint(0, 61, (8,)).astype(np.int32)
                for _ in range(n_families)]
    return [np.concatenate(
        [prefixes[i % n_families],
         r.randint(0, 61, (3,)).astype(np.int32)]) for i in range(n)]


class TestChaosSpanAudit:
    def test_kill_midburst_every_request_one_terminal_span(
            self, engines, tmp_path):
        """Replica 0 dies at a decode step mid-burst with the full
        obs stack on. The audit: exactly one terminal span per rr id,
        span outcomes == fleet ledger, flight dump reconciles, zero
        implicit transfers."""
        clk = ManualClock()
        registry = MetricsRegistry(clock=clk)
        flight = FlightRecorder(clock=clk)
        tracer = Tracer(clock=clk, sink=flight.note_span)
        plan = FaultPlan()
        servers = []
        for i, eng in enumerate(engines):
            if i == 0:
                eng = plan.wrap_replica_engine(eng, clock=clk)
            servers.append(ServingServer(
                eng, max_queue=16, clock=clk, buckets=(16,),
                max_retries=2, tracer=tracer, flight=flight))
        router = ServingRouter(servers, clock=clk, tracer=tracer,
                               flight=flight,
                               flight_dir=str(tmp_path))
        router.bind_metrics(registry)

        # mixed burst: 9 family requests + 6 garbage rejections, the
        # kill armed at the 5th decode step of the burst
        plan.router_kill_decode_at = plan._router_decode_counter + 4
        ids = [router.submit(p, max_new=4)
               for p in family_prompts(9, seed=12)]
        for g in garbage_prompts(61, 16).values():
            try:
                router.submit(g, max_new=2)
            except ValueError:
                pass
        with jax.transfer_guard("disallow"):
            res = router.run()
        router.reconcile()
        assert plan.count("replicakill") == 1
        c = router.counters()
        assert c["replicas_lost"] == 1
        for rid in ids:
            assert res[rid].outcome == "completed"

        # -- exactly one terminal span per minted rr id
        outcomes = tracer.terminal_outcomes()
        assert set(outcomes) == {ServingRouter.trace_id(r)
                                 for r in res}
        assert all(len(v) == 1 for v in outcomes.values()), outcomes
        tc = tracer.counters()
        assert tc["double_ends"] == 0 and tc["spans_live"] == 0
        assert tc["spans_started"] == tc["spans_ended"] == len(res)

        # -- span outcome tallies are the ledger, number for number
        tally = tracer.outcome_counts()
        for oc in ("completed", "failed", "shed", "expired"):
            assert tally.get(oc, 0) == c[oc], (oc, tally, c)

        # -- a redistributed request's span names the handoff
        moved = [r for r in ids if res[r].redistributions > 0]
        assert moved
        span = next(s for s in tracer.finished
                    if s.trace_id == ServingRouter.trace_id(moved[0]))
        assert any(e["name"] == "redistributed" for e in span.events)
        assert res[moved[0]].replica != 0

        # -- the registry exports the same books reconcile() read
        vals = {s["name"]: s["value"]
                for s in registry.snapshot()["series"]}
        assert vals["fleet_requests"] == c["requests"]
        assert vals["fleet_completed"] == c["completed"]
        assert vals["fleet_replicas_lost"] == 1
        assert vals["fleet_trace_double_ends"] == 0
        assert vals["fleet_flight_dumps"] == 1

        # -- the replica-death dump is on disk and reconciles
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight-replica-death")]
        assert len(dumps) == 1
        with open(tmp_path / dumps[0]) as f:
            payload = json.load(f)
        assert payload["kind"] == "flight_dump"
        # the dump snapshot was taken AT death, mid-run: its request
        # count is final (all submitted pre-kill) and its death event
        # is in the ring
        assert payload["extra"]["counters"]["requests"] \
            == c["requests"]
        assert payload["extra"]["counters"]["replicas_lost"] == 1
        deaths = [e for e in payload["events"]
                  if e["kind"] == "fault"
                  and e["name"] == "replica-death"]
        assert len(deaths) == 1 and deaths[0]["replica"] == 0
        # span events rode the sink into the same ring
        assert any(e["kind"] == "span" for e in payload["events"])
