"""Paged KV pool: shared-prefix reuse, chunked prefill, exhaustion.

The block-paged pool (serve.paged + ops.paged_attention) must keep the
engine's foundational contract — greedy tokens EXACTLY equal solo
`generate()` — under every new mechanism it introduces: prefix-cache
hits, copy-on-write splits at block boundaries, chunked prefill
interleaved with live decodes, and recompute preemption when an
over-subscribed pool runs out of pages. On top of parity, the pool's
books must balance (PagePool.reconcile) and the capacity win must be
real: at equal HBM budget the paged layout admits >= 2x the dense
layout's concurrent requests on mixed-length traffic (the ISSUE 4
acceptance bound, asserted via page math AND a live run).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.paged import PagePool, PoolExhaustedError
from paddle_tpu.serve.server import ServingServer
from paddle_tpu.testing.faults import FaultPlan

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


def ref_tokens(params, prompt, max_new, eos_id=None):
    out = T.generate(params, CFG, jnp.asarray(prompt)[None, :],
                     steps=max_new, eos_id=eos_id)
    toks = [int(t) for t in np.asarray(out[0, len(prompt):])]
    if eos_id is not None and eos_id in toks:
        toks = toks[:toks.index(eos_id) + 1]
    return toks


def rng_tokens(n, seed=0):
    return np.random.RandomState(seed).randint(0, 61, (n,)) \
        .astype(np.int32)


# -- the host allocator alone (no device work) ---------------------------


class TestPagePool:
    def _pool(self, **kw):
        base = dict(num_pages=8, page_size=4, slots=4,
                    max_pages_per_slot=4)
        base.update(kw)
        return PagePool(**base)

    def test_admit_extend_release_roundtrip(self):
        pool = self._pool()
        toks = rng_tokens(9)
        pages, shared = pool.admit(0, toks, 9)   # blocks 0..2 (pos 9)
        assert len(pages) == 3 and shared == 0
        assert pool.pages_in_use == 3
        # positions 10, 11 stay in block 2; 12 maps block 3
        assert pool.extend(0) is None
        assert pool.extend(0) is None
        blk, page = pool.extend(0)
        assert blk == 3 and pool.pages_in_use == 4
        pool.release(0)
        assert pool.pages_in_use == 0
        pool.release(0)                          # idempotent
        pool.reconcile()

    def test_prefix_share_refcount_and_cow_split(self):
        pool = self._pool()
        toks = rng_tokens(10, seed=1)
        pool.admit(0, toks, 10)
        pool.register(0, toks, 10)               # blocks 0,1 published
        # same leading 8 tokens, divergent block 2: shares 2 pages
        other = np.concatenate([toks[:8], rng_tokens(3, seed=2)])
        pages, shared_len = pool.admit(1, other, 11)
        assert shared_len == 8
        assert pages[:2] == pool.slot_pages[0][:2]      # shared
        assert pages[2] not in pool.slot_pages[0]       # the CoW split
        pool.reconcile()
        pool.release(0)
        # shared pages survive for slot 1 + the cache
        assert all(p in pages for p in pool.slot_pages[1])
        pool.reconcile()
        pool.release(1)
        pool.reconcile()
        # cache still holds the two registered blocks (evictable)
        assert pool.pages_in_use == 2 and pool.evictable() == 2

    def test_alloc_reclaims_cache_only_pages_then_raises(self):
        pool = self._pool(num_pages=4)
        toks = rng_tokens(9, seed=3)
        pool.admit(0, toks, 9)                   # 3 pages
        pool.register(0, toks, 9)                # blocks 0,1 cached
        pool.release(0)                          # 2 cache-only remain
        assert pool.headroom() == 4
        pool.admit(1, rng_tokens(13, seed=4), 13)   # needs 4: evicts
        assert pool.pages_in_use == 4
        with pytest.raises(PoolExhaustedError):
            pool.alloc(1)
        pool.reconcile()

    def test_shareable_blocks_always_leaves_one_position(self):
        pool = self._pool()
        # a fully-cached prompt must still compute its last position
        assert pool.shareable_blocks(8) == 1     # page 4: not 2
        assert pool.shareable_blocks(9) == 2

    def test_admissible_excludes_own_prefix_from_reclaimable(self):
        """The admission gate must mirror admit()'s arithmetic: a
        request's OWN cache-only prefix pages are ref'd before alloc
        (anti-aliasing order), so they are not reclaimable for its own
        allocation. A naive pages_needed<=headroom gate admits this
        shape and admit() then raises spuriously."""
        pool = self._pool()
        toks = rng_tokens(10, seed=30)
        pool.admit(0, toks, 10)
        pool.register(0, toks, 10)           # blocks 0,1 cached
        pool.release(0)                      # ... cache-only now
        pool.admit(1, rng_tokens(20, seed=31), 20)  # co-tenant: 6 pages
        assert pool.pages_free == 0 and pool.evictable() == 2
        # same prefix, block 2 private: need 1 past the 2 shared
        again = np.concatenate([toks[:8], rng_tokens(2, seed=32)])
        assert pool.pages_needed(again, 10) == 1
        assert pool.pages_needed(again, 10) <= pool.headroom()  # naive
        assert not pool.admissible(again, 10)    # the honest gate
        with pytest.raises(PoolExhaustedError):
            pool.admit(2, again, 10)
        pool.reconcile()                     # admit left no residue
        # the gate opens the moment the co-tenant frees its pages
        pool.release(1)
        assert pool.admissible(again, 10)
        pages, shared_len = pool.admit(2, again, 10)
        assert shared_len == 8
        pool.reconcile()

    def test_pages_needed_is_a_pure_probe(self):
        """pages_needed/admissible are re-asked every server loop for
        a deferred queue head — they must not LRU-touch entries (that
        would skew reclaim order) nor fire the fault hook."""
        pool = self._pool()
        a = rng_tokens(10, seed=33)
        pool.admit(0, a, 10)
        pool.register(0, a, 10)
        b = rng_tokens(10, seed=34)
        pool.admit(1, b, 10)
        pool.register(1, b, 10)
        order_before = list(pool._cache)
        events = []
        pool.fault_hook = lambda ev, ctx: events.append(ev)
        assert pool.pages_needed(a, 10) == 1     # shares blocks 0,1
        assert pool.admissible(a, 10)
        assert list(pool._cache) == order_before  # no LRU reorder
        assert events == []                       # no hook traffic


# -- parity under the new mechanisms -------------------------------------


class TestPrefixReuseParity:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_shared_system_prefix_hits_and_matches(self, params):
        """Co-tenants sharing a 16-token system prefix (2 pages of 8):
        later admissions hit the cache, skip that prefill work, and
        still decode EXACTLY their solo generate() tokens."""
        sys_prefix = rng_tokens(16, seed=10)
        prompts = [np.concatenate([sys_prefix, rng_tokens(n, seed=s)])
                   for n, s in ((5, 11), (3, 12), (7, 13))]
        eng = DecodeEngine(params, CFG, slots=2, max_len=48,
                           page_size=8)
        got = eng.serve(prompts, max_new=8)
        for p, g in zip(prompts, got):
            assert g == ref_tokens(params, p, 8), len(p)
        st = eng.last_stats
        assert st.prefix_hits >= 2, st           # request 2 and 3 hit
        assert st.prefix_misses == 1, st
        eng.pool.reconcile()

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_divergence_exactly_at_page_boundary(self, params):
        """Two prompts identical through block 0 and divergent at
        position page_size exactly: block 0 is shared, block 1 is the
        copy-on-write split — both decode to their solo tokens."""
        head = rng_tokens(8, seed=20)
        a = np.concatenate([head, rng_tokens(6, seed=21)])
        b = np.concatenate([head, rng_tokens(6, seed=22)])
        eng = DecodeEngine(params, CFG, slots=2, max_len=48,
                           page_size=8)
        got = eng.serve([a, b], max_new=8)
        assert got[0] == ref_tokens(params, a, 8)
        assert got[1] == ref_tokens(params, b, 8)
        assert eng.last_stats.prefix_hits == 1
        pool = eng.pool
        pool.reconcile()

    @pytest.mark.slow

    def test_prefix_cache_off(self, params):
        eng = DecodeEngine(params, CFG, slots=2, max_len=48,
                           page_size=8, prefix_cache=False)
        sys_prefix = rng_tokens(16, seed=23)
        prompts = [np.concatenate([sys_prefix, rng_tokens(4, seed=s)])
                   for s in (24, 25)]
        got = eng.serve(prompts, max_new=6)
        for p, g in zip(prompts, got):
            assert g == ref_tokens(params, p, 6)
        assert eng.last_stats.prefix_hits == 0


class TestChunkedPrefill:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_long_prompt_chunks_and_matches(self, params):
        """A prompt longer than one chunk prefills in fixed chunks
        with decodes interleaved; tokens match solo generate() for
        every co-tenant."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=48,
                           page_size=8, prefill_chunk=8)
        prompts = [rng_tokens(23, seed=30), rng_tokens(4, seed=31),
                   rng_tokens(17, seed=32)]
        got = eng.serve(prompts, max_new=8)
        for p, g in zip(prompts, got):
            assert g == ref_tokens(params, p, 8), len(p)
        # 23 -> 3 chunks, 4 -> 1, 17 -> 3
        assert eng.last_stats.prefill_chunks == 7, eng.last_stats

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_decode_interleaves_with_chunks(self, params):
        """The head-of-line property itself: while the long prompt is
        mid-prefill, the already-active short request keeps emitting —
        decode steps are observed BETWEEN that prompt's chunks."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=64,
                           page_size=8, prefill_chunk=8)
        events = []
        orig_adv, orig_step = eng.prefill_advance, eng.decode_step
        eng.prefill_advance = lambda s, t: (
            events.append("chunk"), orig_adv(s, t))[1]
        eng.decode_step = lambda s: (
            events.append("step"), orig_step(s))[1]
        # short first (admits + activates), then a 4-chunk prompt
        got = eng.serve([rng_tokens(4, seed=33),
                         rng_tokens(30, seed=34)], max_new=12)
        assert got[0] == ref_tokens(params, rng_tokens(4, seed=33), 12)
        assert got[1] == ref_tokens(params, rng_tokens(30, seed=34), 12)
        chunk_idx = [i for i, e in enumerate(events) if e == "chunk"]
        # decode steps happened between the long prompt's chunks
        between = any(
            "step" in events[a + 1:b]
            for a, b in zip(chunk_idx, chunk_idx[1:]))
        assert between, events

    @pytest.mark.slow

    def test_chunked_plus_prefix_hit(self, params):
        """A prefix hit under chunked prefill starts chunking at the
        first private block — both mechanisms compose, parity holds."""
        sys_prefix = rng_tokens(16, seed=35)
        p0 = np.concatenate([sys_prefix, rng_tokens(9, seed=36)])
        p1 = np.concatenate([sys_prefix, rng_tokens(5, seed=37)])
        eng = DecodeEngine(params, CFG, slots=1, max_len=48,
                           page_size=8, prefill_chunk=8)
        got = eng.serve([p0, p1], max_new=6)
        assert got[0] == ref_tokens(params, p0, 6)
        assert got[1] == ref_tokens(params, p1, 6)
        assert eng.last_stats.prefix_hits == 1


# -- exhaustion: preemption, shed/requeue, chaos -------------------------


class TestPoolExhaustion:
    def test_entry_validation_page_granular(self, params):
        """A prompt that fits max_len but not the whole page pool is
        rejected up front — engine.serve() AND server.submit()."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=32,
                           page_size=8, num_pages=2)
        with pytest.raises(ValueError, match="pages"):
            eng.serve([rng_tokens(20, seed=40)], max_new=2)
        srv = ServingServer(eng)
        with pytest.raises(ValueError, match="pages"):
            srv.submit(rng_tokens(20, seed=40), max_new=2)
        assert srv.results[0].outcome == "failed"

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_serve_preempts_and_still_matches(self, params):
        """Over-subscribed plain serve(): slots outnumber pages, so
        mid-decode exhaustion preempts co-tenants back onto the queue
        (stats.retried) — and every request STILL ends with exactly
        its solo generate() prefix (full for completed-at-max_new,
        truncated only by pool capacity)."""
        eng = DecodeEngine(params, CFG, slots=3, max_len=32,
                           page_size=4, num_pages=9)
        prompts = [rng_tokens(n, seed=41 + i)
                   for i, n in enumerate((10, 9, 11, 8))]
        got = eng.serve(prompts, max_new=12)
        for p, g in zip(prompts, got):
            ref = ref_tokens(params, p, 12)
            assert g == ref[:len(g)] and len(g) >= 1, (len(p), g, ref)
        assert sum(len(g) == 12 for g in got) >= 2, got
        eng.pool.reconcile()

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_server_sheds_requeues_on_exhaustion_chaos(self, params):
        """ACCEPTANCE CHAOS: a mixed-length burst through an
        over-subscribed server pool — page exhaustion mid-burst drives
        the preempt/requeue path, every request ends in EXACTLY ONE
        outcome, and the page books balance."""
        eng = DecodeEngine(params, CFG, slots=4, max_len=32,
                           page_size=4, num_pages=12)
        srv = ServingServer(eng, max_queue=16, max_retries=3)
        prompts = [rng_tokens(4 + (3 * i) % 14, seed=50 + i)
                   for i in range(10)]
        for p in prompts:
            srv.submit(p, max_new=10)
        results = srv.run()
        assert len(results) == 10
        srv.reconcile()
        c = srv.counters()
        assert c["completed"] >= 1
        assert c["completed"] + c["failed"] + c["shed"] \
            + c["expired"] == 10
        # completed requests kept greedy parity through preemption
        for p, rid in zip(prompts, range(10)):
            r = results[rid]
            if r.outcome == "completed" and len(r.tokens) == 10:
                assert r.tokens == ref_tokens(params, p, 10), rid
        assert c["pages_in_use"] - eng.pool.evictable() == 0

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_page_alloc_fault_injection(self, params):
        """FaultPlan pool exhaustion: the nth allocation reports
        exhaustion against a HEALTHY pool — the requeue path must
        carry the victim to completion (retried >= 1, all
        completed)."""
        plan = FaultPlan(serve_page_alloc_error_at=2)
        eng = plan.wrap_engine(
            DecodeEngine(params, CFG, slots=2, max_len=32,
                         page_size=8))
        srv = ServingServer(eng, max_retries=2)
        prompts = [rng_tokens(5, seed=60), rng_tokens(7, seed=61),
                   rng_tokens(6, seed=62)]
        for p in prompts:
            srv.submit(p, max_new=6)
        results = srv.run()
        assert plan.count("pagealloc") == 1, plan.fired
        srv.reconcile()
        assert all(r.outcome == "completed"
                   for r in results.values()), results
        for p, rid in zip(prompts, range(3)):
            assert results[rid].tokens == ref_tokens(params, p, 6)
        assert srv.counters()["retried"] >= 1

    def test_prefix_corruption_detected_and_rejected(self, params):
        """FaultPlan prefix corruption: a poisoned cache entry is
        caught by the lookup's token re-verification — degraded to a
        miss, evicted (prefix_rejected), greedy parity preserved."""
        sys_prefix = rng_tokens(16, seed=70)
        prompts = [np.concatenate([sys_prefix, rng_tokens(4, seed=s)])
                   for s in (71, 72, 73)]
        plan = FaultPlan(serve_prefix_corrupt_at=0)
        eng = plan.wrap_engine(
            DecodeEngine(params, CFG, slots=1, max_len=48,
                         page_size=8))
        srv = ServingServer(eng)
        for p in prompts:
            srv.submit(p, max_new=6)
        results = srv.run()
        assert plan.count("prefixcorrupt") == 1, plan.fired
        for p, rid in zip(prompts, range(3)):
            assert results[rid].tokens == ref_tokens(params, p, 6), rid
        c = srv.counters()
        assert c["prefix_rejected"] == 1, c
        srv.reconcile()


# -- observability -------------------------------------------------------


def test_server_counters_and_drain_report_carry_pool_stats(
        params, tmp_path):
    report_path = str(tmp_path / "drain.json")
    eng = DecodeEngine(params, CFG, slots=2, max_len=48, page_size=8)
    srv = ServingServer(eng, drain_report_path=report_path)
    sys_prefix = rng_tokens(16, seed=80)
    for s in (81, 82):
        srv.submit(np.concatenate([sys_prefix, rng_tokens(4, seed=s)]),
                   max_new=4)
    srv.run()
    c = srv.counters()
    for key in ("pages_in_use", "pages_free", "peak_pages_in_use",
                "prefix_hits", "prefix_misses", "prefill_chunks"):
        assert key in c, key
    assert c["prefill_chunks"] >= 2 and c["peak_pages_in_use"] >= 2
    assert c["prefix_hits"] == 1 and c["prefix_misses"] == 1
    srv.reconcile()
    srv.drain(reason="test")
    srv.run()
    import json

    report = json.loads(open(report_path).read())
    assert "prefix_hits" in report["counters"]


def test_engine_stats_pool_fields(params):
    eng = DecodeEngine(params, CFG, slots=2, max_len=32, page_size=8)
    eng.serve([rng_tokens(5, seed=90), rng_tokens(7, seed=91)],
              max_new=4)
    st = eng.last_stats
    assert st.pages_in_use == 0          # all released at the end
    assert st.pages_free == eng.num_pages
    assert st.peak_pages_in_use >= 2
    assert st.prefill_chunks == 2


# -- the capacity acceptance bound ---------------------------------------


@pytest.mark.perf
@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_paged_admits_2x_dense_slots_at_equal_budget(params):
    """ISSUE 4 acceptance: at EQUAL HBM budget the paged pool admits
    >= 2x the dense layout's concurrent requests on a mixed-length
    workload. Dense budget: S_dense slots x max_len positions. Paged:
    the same positions as num_pages x page_size, slots bounded only by
    actual lengths. Asserted twice — by page math over the workload,
    and by a live run's observed concurrency."""
    s_dense, max_len, page = 2, 64, 8
    budget_pages = s_dense * (max_len // page)          # 16 pages
    lens = [6, 7, 5, 7, 6, 5, 7, 6]                     # mixed, short
    prompts = [rng_tokens(n, seed=100 + i)
               for i, n in enumerate(lens)]
    max_new = 4
    # page math: worst-case concurrent need per request (prompt +
    # generated, no prefix sharing assumed)
    need = [(n + max_new) // page + 1 for n in lens]
    fit = 0
    acc = 0
    for n in sorted(need):
        if acc + n > budget_pages:
            break
        acc += n
        fit += 1
    assert fit >= 2 * s_dense, (fit, need, budget_pages)

    eng = DecodeEngine(params, CFG, slots=len(prompts),
                       max_len=max_len, page_size=page,
                       num_pages=budget_pages)
    srv = ServingServer(eng, max_queue=len(prompts))
    peak = {"active": 0}
    srv.on_step.append(lambda s, _: peak.__setitem__(
        "active", max(peak["active"],
                      sum(r is not None for r in s._slot_req))))
    for p in prompts:
        srv.submit(p, max_new=max_new)
    results = srv.run()
    srv.reconcile()
    assert all(r.outcome == "completed" for r in results.values())
    for p, rid in zip(prompts, range(len(prompts))):
        assert results[rid].tokens == ref_tokens(params, p, max_new)
    assert peak["active"] >= 2 * s_dense, (peak, srv.counters())
    assert srv.counters()["peak_pages_in_use"] <= budget_pages
