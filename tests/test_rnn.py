"""RNN tests: shapes, ragged masking semantics, gradcheck, and
impl-equivalence against a plain python step loop (the reference's
topology-equivalence style, e.g. recurrent_group vs fused LstmLayer,
gserver/tests/test_CompareTwoNets.cpp)."""

import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import rnn as R
from gradcheck import directional_grad_check


def _np_lstm_ref(params, x):
    """Step-by-step reference implementation (no masking)."""
    w_ih, w_hh, b = map(np.asarray, (params["w_ih"], params["w_hh"], params["b"]))
    bsz, t, f = x.shape
    h_dim = w_hh.shape[0]
    h = np.zeros((bsz, h_dim), np.float32)
    c = np.zeros((bsz, h_dim), np.float32)
    outs = []

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    for step in range(t):
        gates = x[:, step] @ w_ih + h @ w_hh + b
        i, fgt, g, o = np.split(gates, 4, axis=-1)
        c = sig(fgt) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        outs.append(h.copy())
    return np.stack(outs, axis=1)


class TestLSTM:
    def test_matches_reference_loop(self, rng, np_rng):
        params = R.init_lstm_params(rng, 4, 6)
        x = np_rng.randn(3, 5, 4).astype(np.float32)
        out, final = R.lstm(params, jnp.asarray(x))
        want = _np_lstm_ref(params, x)
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(final.h), want[:, -1], rtol=2e-4, atol=1e-5)

    def test_ragged_masking(self, rng, np_rng):
        params = R.init_lstm_params(rng, 4, 6)
        x = np_rng.randn(2, 6, 4).astype(np.float32)
        lengths = jnp.asarray([3, 6])
        out, final = R.lstm(params, jnp.asarray(x), lengths)
        # outputs past length are zero
        np.testing.assert_allclose(np.asarray(out)[0, 3:], 0.0)
        # final state equals state at step len-1
        out_full, _ = R.lstm(params, jnp.asarray(x[:, :3]))
        np.testing.assert_allclose(
            np.asarray(final.h)[0], np.asarray(out_full)[0, -1], rtol=1e-5
        )

    def test_reverse_matches_flipped(self, rng, np_rng):
        params = R.init_lstm_params(rng, 3, 5)
        x = np_rng.randn(2, 4, 3).astype(np.float32)
        out_rev, _ = R.lstm(params, jnp.asarray(x), reverse=True)
        out_flip, _ = R.lstm(params, jnp.asarray(x[:, ::-1]))
        np.testing.assert_allclose(
            np.asarray(out_rev), np.asarray(out_flip)[:, ::-1], rtol=1e-4, atol=1e-5
        )

    def test_grad(self, rng, np_rng):
        params = R.init_lstm_params(rng, 3, 4)
        x = jnp.asarray(np_rng.randn(2, 5, 3), jnp.float32)
        lengths = jnp.asarray([3, 5])
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(R.lstm(p, x, lengths)[0])), params
        )


class TestGRU:
    def test_shapes_and_finite(self, rng, np_rng):
        params = R.init_gru_params(rng, 4, 7)
        x = jnp.asarray(np_rng.randn(3, 5, 4), jnp.float32)
        out, final = R.gru(params, x, jnp.asarray([5, 2, 4]))
        assert out.shape == (3, 5, 7)
        assert final.shape == (3, 7)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_ragged_final_state(self, rng, np_rng):
        params = R.init_gru_params(rng, 4, 7)
        x = np_rng.randn(2, 6, 4).astype(np.float32)
        out, final = R.gru(params, jnp.asarray(x), jnp.asarray([2, 6]))
        out_short, final_short = R.gru(params, jnp.asarray(x[:, :2]))
        np.testing.assert_allclose(
            np.asarray(final)[0], np.asarray(final_short)[0], rtol=1e-5
        )

    def test_grad(self, rng, np_rng):
        params = R.init_gru_params(rng, 3, 4)
        x = jnp.asarray(np_rng.randn(2, 4, 3), jnp.float32)
        directional_grad_check(
            lambda p: jnp.sum(jnp.square(R.gru(p, x)[0])), params
        )


class TestSimpleRNNAndBidi:
    def test_simple_rnn(self, rng, np_rng):
        params = R.init_rnn_params(rng, 3, 5)
        x = jnp.asarray(np_rng.randn(2, 4, 3), jnp.float32)
        out, final = R.simple_rnn(params, x)
        assert out.shape == (2, 4, 5)

    def test_bidirectional_concat(self, rng, np_rng):
        k1, k2 = jax.random.split(rng)
        fwd = R.init_lstm_params(k1, 3, 4)
        bwd = R.init_lstm_params(k2, 3, 4)
        x = jnp.asarray(np_rng.randn(2, 5, 3), jnp.float32)
        out, _ = R.bidirectional(R.lstm, fwd, bwd, x, jnp.asarray([5, 3]))
        assert out.shape == (2, 5, 8)
        f_out, _ = R.lstm(fwd, x, jnp.asarray([5, 3]))
        np.testing.assert_allclose(np.asarray(out)[..., :4], np.asarray(f_out))


class TestLayers:
    def test_lstm_layer_in_module_system(self, rng, np_rng):
        from paddle_tpu import nn

        layer = nn.BiLSTM(6)
        x = jnp.asarray(np_rng.randn(2, 5, 3), jnp.float32)
        params, state = layer.init(rng, nn.ShapeSpec(x.shape))
        out, _ = layer.apply(params, state, x, jnp.asarray([5, 2]))
        assert out.shape == (2, 5, 12)


class TestFusedPallasLstm:
    """The fused Pallas time-loop kernel vs the lax.scan reference
    (ops/pallas_lstm.py; interpret mode on CPU — impl-vs-impl
    equivalence per SURVEY §4)."""

    def _setup(self, b=4, t=9, f=12, h=16):
        rs = np.random.RandomState(0)
        params = R.init_lstm_params(jax.random.key(0), f, h)
        x = jnp.asarray(rs.randn(b, t, f), jnp.float32)
        return params, x

    def test_forward_matches_scan(self):
        params, x = self._setup()
        o_xla, st_xla = R.lstm(params, x, impl="xla")
        o_pl, st_pl = R.lstm(params, x, impl="pallas")
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pl.h, st_xla.h, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pl.c, st_xla.c, rtol=1e-5, atol=1e-6)

    def test_reverse_matches_scan(self):
        params, x = self._setup()
        o_xla, st_xla = R.lstm(params, x, impl="xla", reverse=True)
        o_pl, st_pl = R.lstm(params, x, impl="pallas", reverse=True)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pl.h, st_xla.h, rtol=1e-5, atol=1e-6)

    def test_grads_match_scan(self):
        params, x = self._setup()

        def loss(params, x, impl):
            o, st = R.lstm(params, x, impl=impl)
            return jnp.sum(o * o) + jnp.sum(st.c ** 2) + jnp.sum(st.h ** 2)

        g_xla = jax.grad(loss, argnums=(0, 1))(params, x, "xla")
        g_pl = jax.grad(loss, argnums=(0, 1))(params, x, "pallas")
        for a, b in zip(jax.tree_util.tree_leaves(g_xla),
                        jax.tree_util.tree_leaves(g_pl)):
            np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("reverse", [False, True])
    def test_lengths_match_scan(self, reverse):
        """Variable-length batches run through the fused kernel's
        in-kernel [start, end) windows and must match the masked scan —
        outputs, final state AND gradients."""
        params, x = self._setup()
        lens = jnp.asarray([9, 4, 1, 7])
        o_xla, st_xla = R.lstm(params, x, lens, impl="xla", reverse=reverse)
        o_pl, st_pl = R.lstm(params, x, lens, impl="pallas", reverse=reverse)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pl.h, st_xla.h, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pl.c, st_xla.c, rtol=1e-5, atol=1e-6)
        assert float(jnp.abs(o_pl[1, 4:]).sum()) == 0.0  # masked zeroed

        def loss(params, impl):
            o, st = R.lstm(params, x, lens, impl=impl, reverse=reverse)
            return jnp.sum(o * o) + jnp.sum(st.c ** 2) + jnp.sum(st.h ** 2)

        g_xla = jax.grad(loss)(params, "xla")
        g_pl = jax.grad(loss)(params, "pallas")
        for a, b in zip(jax.tree_util.tree_leaves(g_xla),
                        jax.tree_util.tree_leaves(g_pl)):
            np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)

    def test_initial_state_carries(self):
        params, x = self._setup()
        h0 = jnp.full((4, 16), 0.3, jnp.float32)
        c0 = jnp.full((4, 16), -0.2, jnp.float32)
        st = R.LSTMState(h0, c0)
        o_xla, _ = R.lstm(params, x, impl="xla", initial_state=st)
        o_pl, _ = R.lstm(params, x, impl="pallas", initial_state=st)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)

    def test_forced_pallas_fails_loudly(self):
        from paddle_tpu.core.errors import PaddleTpuError

        params, x = self._setup()
        with pytest.raises(PaddleTpuError):
            R.lstm(params, x, impl="fused")  # unknown impl string
        big = R.init_lstm_params(jax.random.key(1), 8, 1024)
        xb = jnp.zeros((64, 4, 8), jnp.float32)
        with pytest.raises(PaddleTpuError):
            R.lstm(big, xb, impl="pallas")  # exceeds VMEM budget


class TestFusedPallasGru:
    """ops/pallas_gru.py vs the masked lax.scan (interpret mode)."""

    def _setup(self, b=4, t=9, f=12, h=16):
        rs = np.random.RandomState(3)
        params = R.init_gru_params(jax.random.key(0), f, h)
        x = jnp.asarray(rs.randn(b, t, f), jnp.float32)
        return params, x

    @pytest.mark.parametrize("reverse", [False, True])
    @pytest.mark.parametrize("with_lengths", [False, True])
    def test_matches_scan(self, reverse, with_lengths):
        params, x = self._setup()
        lens = jnp.asarray([9, 4, 1, 7]) if with_lengths else None
        o_xla, h_xla = R.gru(params, x, lens, impl="xla", reverse=reverse)
        o_pl, h_pl = R.gru(params, x, lens, impl="pallas", reverse=reverse)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h_pl, h_xla, rtol=1e-5, atol=1e-6)

        def loss(params, impl):
            o, h = R.gru(params, x, lens, impl=impl, reverse=reverse)
            return jnp.sum(o * o) + jnp.sum(h ** 2)

        g_xla = jax.grad(loss)(params, "xla")
        g_pl = jax.grad(loss)(params, "pallas")
        for a, b in zip(jax.tree_util.tree_leaves(g_xla),
                        jax.tree_util.tree_leaves(g_pl)):
            np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)

    def test_bidirectional_through_fused(self):
        params, x = self._setup()
        params2 = R.init_gru_params(jax.random.key(5), 12, 16)
        lens = jnp.asarray([9, 7, 5, 3])
        o_xla, _ = R.bidirectional(
            functools.partial(R.gru, impl="xla"), params, params2, x, lens)
        o_pl, _ = R.bidirectional(
            functools.partial(R.gru, impl="pallas"), params, params2, x, lens)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)


class TestFusedPallasSimpleRnn:
    @pytest.mark.parametrize("reverse", [False, True])
    @pytest.mark.parametrize("with_lengths", [False, True])
    def test_matches_scan(self, reverse, with_lengths):
        rs = np.random.RandomState(7)
        params = R.init_rnn_params(jax.random.key(0), 12, 16)
        x = jnp.asarray(rs.randn(4, 9, 12), jnp.float32)
        lens = jnp.asarray([9, 4, 1, 7]) if with_lengths else None
        o_xla, h_xla = R.simple_rnn(params, x, lens, impl="xla",
                                    reverse=reverse)
        o_pl, h_pl = R.simple_rnn(params, x, lens, impl="pallas",
                                  reverse=reverse)
        np.testing.assert_allclose(o_pl, o_xla, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(h_pl, h_xla, rtol=1e-5, atol=1e-6)

        def loss(params, impl):
            o, h = R.simple_rnn(params, x, lens, impl=impl,
                                reverse=reverse)
            return jnp.sum(o * o) + jnp.sum(h ** 2)

        g_xla = jax.grad(loss)(params, "xla")
        g_pl = jax.grad(loss)(params, "pallas")
        for a, b in zip(jax.tree_util.tree_leaves(g_xla),
                        jax.tree_util.tree_leaves(g_pl)):
            np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)

    def test_custom_activation_rejected_when_forced(self):
        from paddle_tpu.core.errors import PaddleTpuError

        params = R.init_rnn_params(jax.random.key(0), 4, 8)
        x = jnp.zeros((2, 3, 4), jnp.float32)
        with pytest.raises(PaddleTpuError):
            R.simple_rnn(params, x, activation=jnp.abs, impl="pallas")
        # auto with a custom activation silently keeps the scan
        o, _ = R.simple_rnn(params, x, activation=jnp.abs, impl="auto")
        assert o.shape == (2, 3, 8)


class TestMDLSTM:
    """2-D MDLSTM: the wavefront scan must equal a cell-at-a-time naive
    reference (the reference's CoordIterator order), gradients must
    pass the numeric check, and direction flags must mean what the
    reference's `directions` meant."""

    @staticmethod
    def _naive(params, x):
        """Literal cell-by-cell 2-D LSTM — the CoordIterator semantics
        of MDLstmLayer.cpp, trusted by being too simple to be wrong."""
        b, h, w, f = x.shape
        hdim = params["w_row"].shape[0]
        hs = np.zeros((b, h, w, hdim), np.float64)
        cs = np.zeros((b, h, w, hdim), np.float64)

        def sig(a):
            return 1.0 / (1.0 + np.exp(-a))

        for i in range(h):
            for j in range(w):
                h_up = hs[:, i - 1, j] if i > 0 else np.zeros((b, hdim))
                c_up = cs[:, i - 1, j] if i > 0 else np.zeros((b, hdim))
                h_l = hs[:, i, j - 1] if j > 0 else np.zeros((b, hdim))
                c_l = cs[:, i, j - 1] if j > 0 else np.zeros((b, hdim))
                z = (np.asarray(x[:, i, j], np.float64)
                     @ np.asarray(params["w_ih"], np.float64)
                     + np.asarray(params["b"], np.float64)
                     + h_up @ np.asarray(params["w_row"], np.float64)
                     + h_l @ np.asarray(params["w_col"], np.float64))
                g, ig, fr, fc, o = (z[:, k * hdim:(k + 1) * hdim]
                                    for k in range(5))
                c = sig(ig) * np.tanh(g) + sig(fr) * c_up + sig(fc) * c_l
                hs[:, i, j] = sig(o) * np.tanh(c)
                cs[:, i, j] = c
        return hs

    def test_matches_naive_reference(self):
        params = R.init_md_lstm_params(jax.random.key(0), 3, 5)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 6, 3),
                        jnp.float32)
        got = np.asarray(R.md_lstm(params, x))
        want = self._naive(params, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_direction_flags(self):
        """reverse_rows/cols must equal flipping the input grid, running
        forward, and flipping back — the reference's `directions`."""
        params = R.init_md_lstm_params(jax.random.key(1), 3, 4)
        x = jnp.asarray(np.random.RandomState(1).randn(1, 3, 5, 3),
                        jnp.float32)
        got = np.asarray(R.md_lstm(params, x, reverse_rows=True,
                                   reverse_cols=True))
        want = np.asarray(
            R.md_lstm(params, x[:, ::-1, ::-1])[:, ::-1, ::-1])
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_gradcheck(self):
        from gradcheck import directional_grad_check

        params = R.init_md_lstm_params(jax.random.key(2), 2, 3)
        x = jnp.asarray(np.random.RandomState(2).randn(1, 3, 3, 2),
                        jnp.float32)

        def f(p):
            return jnp.sum(R.md_lstm(p, x) ** 2)

        directional_grad_check(f, params)

    def test_layer_wrapper(self):
        from paddle_tpu import nn
        from paddle_tpu.nn.module import ShapeSpec

        layer = nn.MDLSTM(6, name="md")
        params, state = layer.init(jax.random.key(3),
                                   ShapeSpec((2, 4, 5, 3)))
        x = jnp.asarray(np.random.RandomState(3).randn(2, 4, 5, 3),
                        jnp.float32)
        out, _ = layer.apply(params, state, x, training=False, rng=None)
        assert out.shape == (2, 4, 5, 6)
        # the wrapper runs the same op
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(R.md_lstm(params, x)),
            rtol=1e-6, atol=1e-6)
