"""Real multi-process jax.distributed gang: two local processes join a
coordinator, form one global mesh, and run an all-reduce and a sharded
train step whose results must match a single-process run.

Every other multi-device test in this suite runs single-process on the
virtual 8-CPU mesh; this is the one that exercises the actual multi-host
join path that parallel/launch.py promises (reference analog: the
in-process multi-node simulation of
paddle/trainer/tests/test_TrainerOnePass.cpp:245-258 with real server
objects, and go/pserver/etcd_client.go's init barrier).

Historical note (these three failed from the seed until diagnosed):
two independent root causes. (1) XLA:CPU refuses multi-process
computations unless a cross-process collectives transport is
configured — distributed.initialize() now selects jax's bundled gloo
TCP transport when the job is pinned to CPU, which un-wedged all
three gangs. (2) The CTR gang then still diverged from the
single-process reference in the FIRST forward pass: ShardedEmbedding
drew its init over the PADDED table shape, and jax.random draws are
shape-dependent, so every row's init differed per mesh-axis size —
fixed by drawing over the real vocab and zero-padding.
"""

import json
import os
import pathlib
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

CHILD = r"""
import json, os, sys
import scripts.cpu_guard  # pins cpu; config-only, backend stays cold

from paddle_tpu.parallel import distributed as D

addr, pid = sys.argv[1], int(sys.argv[2])
D.initialize(coordinator_address=addr, num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

assert jax.process_count() == 2, jax.process_count()
assert D.process_count() == 2
assert D.is_primary() == (pid == 0)

devs = jax.devices()
assert len(devs) == 2, devs  # one cpu device per process, global view
mesh = Mesh(np.array(devs), ("data",))

# global [8, 4] array, each process owning its 4-row half
rows = np.arange(32, dtype=np.float32).reshape(8, 4)
local = rows[pid * 4:(pid + 1) * 4]
sharding = NamedSharding(mesh, P("data"))
garr = jax.make_array_from_process_local_data(sharding, local, (8, 4))

# all-reduce: global sum must see BOTH halves
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
D.sync_hosts("after-allreduce")

# one sharded train step on the global mesh (batch over `data`)
from paddle_tpu import nn, optim, parallel
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.train.state import TrainState

gmesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=2), devices=devs)
model = nn.Sequential([nn.Dense(8, name="fc", activation="relu"),
                       nn.Dense(3, name="out")])
params, mstate = model.init(jax.random.key(0), ShapeSpec((8, 4)))
opt = optim.sgd(0.1)
state = parallel.shard_train_state(
    TrainState.create(params, mstate, opt), gmesh)
step = parallel.make_sharded_train_step(
    model, lambda lg, y: jnp.mean(losses.softmax_cross_entropy(lg, y)),
    opt, gmesh)
y_all = (np.arange(8) % 3).astype(np.int32)
x_g = jax.make_array_from_process_local_data(
    parallel.batch_sharding(gmesh), local, (8, 4))
y_g = jax.make_array_from_process_local_data(
    parallel.batch_sharding(gmesh), y_all[pid * 4:(pid + 1) * 4], (8,))
new_state, loss, _ = step(state, jax.random.key(1), (x_g,), (y_g,))
kernel_sum = float(jnp.sum(jnp.abs(new_state.params["fc"]["kernel"])))

if D.is_primary():
    print(json.dumps({"total": float(total), "loss": float(loss),
                      "kernel_sum": kernel_sum}), flush=True)
D.sync_hosts("done")
"""


CTR_CHILD = r"""
import json, os, sys
import scripts.cpu_guard  # pins cpu; config-only, backend stays cold

from paddle_tpu.parallel import distributed as D

addr, pid = sys.argv[1], int(sys.argv[2])
D.initialize(coordinator_address=addr, num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu import optim
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.models.ctr import CTRModel

devs = jax.devices()
assert len(devs) == 2, devs
gmesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=2),
                            devices=devs)

# flat id count 8*4=32 divides the 2-way model axis -> the owner-routed
# ALL-TO-ALL lookup/push path, now crossing a real process boundary
model = CTRModel(vocab=64, embed_dim=8, mesh=gmesh, hidden=(16,))
params, mlp_state = model.init(jax.random.key(0), 8, 4)
opt = optim.adam(1e-2)
opt_state = opt.init(params["mlp"])
step = model.make_train_step(opt, mlp_state)

rng = np.random.RandomState(0)
ids = rng.randint(0, 64, (8, 4)).astype(np.int32)     # uncommitted =>
labels = rng.randint(0, 2, 8).astype(np.int32)        # replicated input
lr = np.float32(0.05)
si = np.int32(0)
losses = []
for _ in range(2):
    params, opt_state, loss = step(params, opt_state, ids, labels, lr,
                                   si, jax.random.key(1))
    losses.append(float(loss))
D.sync_hosts("after-steps")

# compare REAL rows only: ShardedEmbedding pads the vocab to a
# multiple of the mesh axis, so the n=2 table has one extra (zero)
# pad row the n=1 reference doesn't
rsum = jax.jit(lambda t: jnp.sum(jnp.abs(t[:65])),
               out_shardings=NamedSharding(gmesh, P()))
# SPMD: EVERY process must run the collective reductions; only the
# print is primary-only
deep_sum = float(rsum(params["deep"]))
wide_sum = float(rsum(params["wide"]))
if D.is_primary():
    print(json.dumps({"losses": losses, "deep_sum": deep_sum,
                      "wide_sum": wide_sum}), flush=True)
D.sync_hosts("done")
"""


MOE_CHILD = r"""
import json, os, sys
import scripts.cpu_guard  # pins cpu; config-only, backend stays cold

from paddle_tpu.parallel import distributed as D

addr, pid = sys.argv[1], int(sys.argv[2])
D.initialize(coordinator_address=addr, num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.parallel import moe

devs = jax.devices()
assert len(devs) == 2, devs
gmesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=2),
                            devices=devs)

# 4 experts over the 2-process model axis: the shard_map EP dispatch's
# all-to-all token exchange crosses a real process boundary
t, d, e, f = 16, 8, 4, 16
params = moe.init_moe_params(jax.random.key(3), e, d, f)
sharded = moe.shard_moe_params(params, gmesh)
x = jnp.asarray(np.random.RandomState(4).randn(t, d), jnp.float32)

ep = moe.make_expert_parallel_ffn(gmesh, k=2, capacity_factor=8.0)

@jax.jit
def fwd_and_grad(p, x):
    def loss(p):
        out = ep(p, x)
        return jnp.mean(out.y ** 2) + 0.01 * out.aux_loss, out
    (l, out), grads = jax.value_and_grad(loss, has_aux=True)(p)
    return l, out.y, grads

l, y, grads = fwd_and_grad(sharded, x)
D.sync_hosts("after-step")

rsum = jax.jit(lambda t: jnp.sum(jnp.abs(t)),
               out_shardings=NamedSharding(gmesh, P()))
# SPMD: every process runs the reductions; only the print is primary's
y_sum = float(rsum(y))
g_sum = float(sum(rsum(g) for g in jax.tree.leaves(grads)))
if D.is_primary():
    print(json.dumps({"loss": float(l), "y_sum": y_sum,
                      "g_sum": g_sum}), flush=True)
D.sync_hosts("done")
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_gang(tmp_path, child_src):
    addr = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "gang_child.py"
    script.write_text(child_src)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), addr, str(pid)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
    return json.loads(outs[0][1].strip().splitlines()[-1])


def test_two_process_gang_matches_single_process(tmp_path):
    # bounded by _run_gang's 240s communicate() timeout, not a marker
    # (pytest-timeout isn't installed here)
    rec = _run_gang(tmp_path, CHILD)

    # the all-reduce saw both halves
    assert rec["total"] == float(np.arange(32).sum())

    # single-process reference for the same global step
    import jax
    import jax.numpy as jnp
    from paddle_tpu import nn, optim, parallel
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.state import TrainState

    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1),
                               devices=jax.devices()[:1])
    model = nn.Sequential([nn.Dense(8, name="fc", activation="relu"),
                           nn.Dense(3, name="out")])
    params, mstate = model.init(jax.random.key(0), ShapeSpec((8, 4)))
    opt = optim.sgd(0.1)
    state = parallel.shard_train_state(
        TrainState.create(params, mstate, opt), mesh)
    step = parallel.make_sharded_train_step(
        model, lambda lg, y: jnp.mean(losses.softmax_cross_entropy(lg, y)),
        opt, mesh)
    x = jnp.asarray(np.arange(32, dtype=np.float32).reshape(8, 4))
    y = jnp.asarray((np.arange(8) % 3).astype(np.int32))
    new_state, loss, _ = step(state, jax.random.key(1), (x,), (y,))
    np.testing.assert_allclose(rec["loss"], float(loss), rtol=1e-5)
    np.testing.assert_allclose(
        rec["kernel_sum"],
        float(jnp.sum(jnp.abs(new_state.params["fc"]["kernel"]))),
        rtol=1e-5)


# the two workload variants are ~10s each (two fresh python processes
# + gloo bootstrap + their own compiles): slow-demoted under the
# tier-1 870s cap discipline. The transport/bootstrap fix they share
# stays tier-1-proven by the 4s two-process test above; run these via
# `pytest tests/test_distributed_gang.py` (or -m slow).
@pytest.mark.slow
def test_ctr_sparse_alltoall_gang_matches_single_process(tmp_path):
    """The collective-heavy path across a REAL process boundary (r4
    verdict weak #7: the only gang case was a toy MLP): the CTR train
    step's owner-routed all-to-all sparse lookup + row-grad push runs
    on a 2-process model-axis mesh, and two optimizer steps must land
    on the same losses and table contents as single-process."""
    rec = _run_gang(tmp_path, CTR_CHILD)

    import jax
    import jax.numpy as jnp
    from paddle_tpu import optim
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.models.ctr import CTRModel

    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=1),
                               devices=jax.devices()[:1])
    model = CTRModel(vocab=64, embed_dim=8, mesh=mesh, hidden=(16,))
    params, mlp_state = model.init(jax.random.key(0), 8, 4)
    opt = optim.adam(1e-2)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (8, 4)).astype(np.int32)
    labels = rng.randint(0, 2, 8).astype(np.int32)
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, ids, labels,
                                       np.float32(0.05), np.int32(0),
                                       jax.random.key(1))
        losses.append(float(loss))
    np.testing.assert_allclose(rec["losses"], losses, rtol=1e-5)
    # [:65] mirrors the child: only the real vocab rows are compared
    # (the sharded table pads to a multiple of the mesh axis)
    np.testing.assert_allclose(
        rec["deep_sum"], float(jnp.sum(jnp.abs(params["deep"][:65]))),
        rtol=1e-5)
    np.testing.assert_allclose(
        rec["wide_sum"], float(jnp.sum(jnp.abs(params["wide"][:65]))),
        rtol=1e-5)


@pytest.mark.slow
def test_moe_expert_parallel_gang_matches_single_process(tmp_path):
    """Third gang case: the MoE expert-parallel shard_map (all-to-all
    token dispatch + combine, and its BACKWARD) across a real
    2-process model-axis mesh must reproduce the single-device
    moe_ffn's loss, outputs, and gradient magnitudes."""
    rec = _run_gang(tmp_path, MOE_CHILD)

    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import moe

    t, d, e, f = 16, 8, 4, 16
    params = moe.init_moe_params(jax.random.key(3), e, d, f)
    x = jnp.asarray(
        np.random.RandomState(4).randn(t, d), jnp.float32)

    def loss(p):
        out = moe.moe_ffn(p, x, k=2, capacity_factor=8.0)
        return jnp.mean(out.y ** 2) + 0.01 * out.aux_loss, out

    (l, out), grads = jax.value_and_grad(loss, has_aux=True)(params)
    np.testing.assert_allclose(rec["loss"], float(l), rtol=1e-5)
    np.testing.assert_allclose(
        rec["y_sum"], float(jnp.sum(jnp.abs(out.y))), rtol=1e-4)
    np.testing.assert_allclose(
        rec["g_sum"],
        float(sum(jnp.sum(jnp.abs(g)) for g in jax.tree.leaves(grads))),
        rtol=1e-4)
