"""Continuous-batching DecodeEngine consistency: a request served
through the slot pool must yield EXACTLY the tokens generate() produces
for the same prompt — independent of pool co-tenants and admission
order (the whole point of per-slot positions over lockstep batching).

Reference frame: the reference's SequenceGenerator decodes a fixed
batch in lockstep (api/PaddleAPI.h:1025); the engine is the
streaming-traffic generalization.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


def ref_tokens(params, prompt, max_new, eos_id=None):
    """generate()'s new tokens for one prompt, truncated at eos
    (inclusive) the way the engine reports a finished request."""
    out = T.generate(params, CFG, jnp.asarray(prompt)[None, :],
                     steps=max_new, eos_id=eos_id)
    toks = [int(t) for t in np.asarray(out[0, len(prompt):])]
    if eos_id is not None and eos_id in toks:
        toks = toks[:toks.index(eos_id) + 1]
    return toks


def prompts_rng(n, lens, seed=0):
    r = np.random.RandomState(seed)
    return [r.randint(0, 61, (l,)).astype(np.int32)
            for l, _ in zip(list(lens) * n, range(n))]


class TestEngineConsistency:
    def test_single_request_matches_generate(self, params):
        eng = DecodeEngine(params, CFG, slots=2, max_len=32)
        p = prompts_rng(1, [7])[0]
        got = eng.serve([p], max_new=12)
        assert got[0] == ref_tokens(params, p, 12)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_pool_crosstalk_free(self, params):
        """4 requests of different lengths through 2 slots: every
        request must equal its SOLO generate() decode — co-tenants and
        admission timing must not leak into the math."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=32)
        ps = prompts_rng(4, [5, 9, 3, 7], seed=1)
        got = eng.serve(ps, max_new=10)
        for p, g in zip(ps, got):
            assert g == ref_tokens(params, p, 10), p

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_eos_frees_slot_and_is_emitted(self, params):
        """Pick an eos that actually occurs early for one prompt; the
        request must end WITH the eos token and its slot must serve the
        next queued request to the same tokens as solo."""
        ps = prompts_rng(6, [4, 6, 5, 8, 3, 7], seed=2)
        # choose the most common first-generated token as eos so at
        # least one request terminates early
        firsts = [ref_tokens(params, p, 1)[0] for p in ps]
        eos = max(set(firsts), key=firsts.count)
        eng = DecodeEngine(params, CFG, slots=2, max_len=32, eos_id=eos)
        got = eng.serve(ps, max_new=8)
        ended_early = 0
        for p, g in zip(ps, got):
            ref = ref_tokens(params, p, 8, eos_id=eos)
            assert g == ref, (p, g, ref)
            if g and g[-1] == eos and len(g) < 8:
                ended_early += 1
        assert ended_early >= 1  # the scenario actually exercised eos

    def test_capacity_finish(self, params):
        """A request that hits its slot's cache capacity retires
        cleanly with t0 + emitted <= max_len."""
        eng = DecodeEngine(params, CFG, slots=1, max_len=12)
        p = prompts_rng(1, [8], seed=3)[0]
        got = eng.serve([p], max_new=50)
        # generated tokens occupy cache positions t0..max_len-1
        assert len(got[0]) == 12 - 8
        assert got[0] == ref_tokens(params, p, len(got[0]))

    def test_unsupported_configs_raise(self, params):
        with pytest.raises(ValueError):
            DecodeEngine(params,
                         dataclasses.replace(CFG, kv_cache_dtype="fp4"),
                         slots=2, max_len=16)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_int8_kv_pool_matches_int8_generate(self, params):
        """The int8-KV slot pool must reproduce generate()'s int8-KV
        decode: both quantize the same vectors with the same
        per-vector scales, so tokens agree (bit-identical quant data;
        only float-accum order differs)."""
        cfg = dataclasses.replace(CFG, kv_cache_dtype="int8")
        eng = DecodeEngine(params, cfg, slots=2, max_len=32)
        ps = prompts_rng(3, [5, 8, 6], seed=11)
        got = eng.serve(ps, max_new=10)
        agree_total = n_total = 0
        for p, g in zip(ps, got):
            out = T.generate(params, cfg, jnp.asarray(p)[None, :],
                             steps=10)
            ref = [int(t) for t in np.asarray(out[0, len(p):])]
            agree_total += sum(a == b for a, b in zip(g, ref))
            n_total += len(ref)
        assert agree_total / n_total >= 0.95, (agree_total, n_total)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_gqa_pool(self):
        cfg = dataclasses.replace(CFG, n_kv_heads=2)
        p_ = T.init_params(jax.random.key(5), cfg)
        eng = DecodeEngine(p_, cfg, slots=2, max_len=24)
        ps = prompts_rng(3, [5, 6, 4], seed=5)
        got = eng.serve(ps, max_new=8)
        for p, g in zip(ps, got):
            out = T.generate(p_, cfg, jnp.asarray(p)[None, :], steps=8)
            assert g == [int(t) for t in np.asarray(out[0, len(p):])]


class TestBuckets:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_bucketed_prompts_match_unpadded(self, params):
        """Padding to a bucket + true_len must not change a single
        token vs the unpadded solo decode (the masked-prefill
        contract), while compiling prefill only once per bucket."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=40)
        ps = prompts_rng(5, [3, 7, 5, 9, 4], seed=7)
        got = eng.serve(ps, max_new=8, buckets=(8, 16))
        for p, g in zip(ps, got):
            assert g == ref_tokens(params, p, 8), (p, g)

    def test_bucket_too_small_raises(self, params):
        eng = DecodeEngine(params, CFG, slots=1, max_len=40)
        with pytest.raises(ValueError, match="bucket"):
            eng.serve(prompts_rng(1, [9], seed=8), max_new=4,
                      buckets=(4, 8))

    def test_max_new_validated(self, params):
        eng = DecodeEngine(params, CFG, slots=1, max_len=16)
        with pytest.raises(ValueError, match="max_new"):
            eng.serve(prompts_rng(1, [4], seed=9), max_new=0)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_int8_weights_pool(params):
    """Quantized WEIGHTS through the engine (the generate() streaming
    split: hoisted dequant for prefill, in-body for the step): tokens
    match the quantized generate()."""
    from paddle_tpu.serve import quant
    qp = quant.quantize_params(params)
    eng = DecodeEngine(qp, CFG, slots=2, max_len=24)
    ps = prompts_rng(3, [4, 6, 5], seed=17)
    got = eng.serve(ps, max_new=6)
    for p, g in zip(ps, got):
        out = T.generate(qp, CFG, jnp.asarray(p)[None, :], steps=6)
        assert g == [int(t) for t in np.asarray(out[0, len(p):])], p


class TestEngineSampling:
    @pytest.mark.slow
    def test_temperature_zero_equals_greedy(self, params):
        ps = prompts_rng(3, [5, 7, 4], seed=21)
        greedy = DecodeEngine(params, CFG, slots=2, max_len=24) \
            .serve(ps, max_new=6)
        t0 = DecodeEngine(params, CFG, slots=2, max_len=24,
                          select_fn=T.make_sampler(temperature=0.0)) \
            .serve(ps, max_new=6)
        assert greedy == t0

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_sampling_deterministic_per_seed_and_varies(self, params):
        ps = prompts_rng(4, [5, 6, 4, 7], seed=22)
        mk = lambda seed: DecodeEngine(
            params, CFG, slots=2, max_len=24,
            select_fn=T.make_sampler(temperature=1.2, top_p=0.95),
            seed=seed).serve(ps, max_new=6)
        a, b, c = mk(0), mk(0), mk(7)
        assert a == b                      # reproducible per seed
        assert a != c                      # and the seed matters


class TestPerRequestSampling:
    @pytest.mark.slow
    def test_greedy_contract_survives_sampled_cotenants(self, params):
        """Per-request sampling: greedy requests must still match their
        solo generate() exactly while sampled requests share the
        pool (the per-slot params isolate them)."""
        ps = prompts_rng(4, [5, 7, 4, 6], seed=31)
        sampling = [None, {"temperature": 1.1, "top_p": 0.9},
                    None, {"temperature": 0.8, "top_k": 10}]
        eng = DecodeEngine(params, CFG, slots=2, max_len=24)
        got = eng.serve(ps, max_new=6,
                        sampling=[s or {} for s in sampling])
        for i in (0, 2):   # the greedy requests
            assert got[i] == ref_tokens(params, ps[i], 6), i
        for i in (1, 3):   # sampled: right length, in-vocab
            assert len(got[i]) == 6
            assert all(0 <= t < 61 for t in got[i])

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_reproducible_and_seed_sensitive(self, params):
        ps = prompts_rng(3, [5, 6, 4], seed=32)
        sampling = [{"temperature": 1.0}] * 3
        mk = lambda seed: DecodeEngine(
            params, CFG, slots=2, max_len=24, seed=seed) \
            .serve(ps, max_new=6, sampling=sampling)
        assert mk(1) == mk(1)
        assert mk(1) != mk(5)

    def test_select_fn_conflict_and_bad_keys(self, params):
        eng = DecodeEngine(params, CFG, slots=1, max_len=16,
                           select_fn=T.make_sampler(temperature=0.5))
        with pytest.raises(ValueError, match="mutually exclusive"):
            eng.serve(prompts_rng(1, [4], seed=33), max_new=2,
                      sampling=[{"temperature": 1.0}])
        eng2 = DecodeEngine(params, CFG, slots=1, max_len=16)
        with pytest.raises(ValueError, match="unknown sampling"):
            eng2.serve(prompts_rng(1, [4], seed=34), max_new=2,
                       sampling=[{"temp": 1.0}])
        with pytest.raises(ValueError, match="entries for"):
            eng2.serve(prompts_rng(2, [4, 5], seed=35), max_new=2,
                       sampling=[{}])


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_scheduling_efficiency_vs_lockstep(params):
    """The utilization claim, measured chip-independently in STEP
    INVOCATIONS (each step = one fixed-size batch of device work):
    on eos-staggered traffic the engine re-fills freed slots, so it
    issues materially fewer steps than lockstep batches that idle
    finished rows until the whole batch drains."""
    ps = prompts_rng(12, [4, 5, 6, 4, 5, 6, 4, 5, 6, 4, 5, 6], seed=51)
    firsts = [ref_tokens(params, p, 1)[0] for p in ps]
    eos = max(set(firsts), key=firsts.count)
    max_new = 24

    eng = DecodeEngine(params, CFG, slots=2, max_len=32, eos_id=eos)
    steps = 0
    orig = eng.decode_step

    def counting(state):
        nonlocal steps
        steps += 1
        return orig(state)

    eng.decode_step = counting
    got = eng.serve(ps, max_new=max_new)
    lens = [len(g) for g in got]
    assert any(l < max_new for l in lens)  # staggering actually happened

    # lockstep cost on the same workload: each batch of 2 runs until
    # its LONGEST request finishes (finished rows idle)
    lock_steps = sum(max(lens[i:i + 2]) for i in range(0, len(ps), 2))
    assert steps < lock_steps, (steps, lock_steps, lens)
    # and the engine's slot utilization (useful row-steps over issued
    # row-steps) beats lockstep's by a real margin on this workload
    used = sum(lens)
    eng_util = used / (2 * steps)
    lock_util = used / (2 * lock_steps)
    assert eng_util > lock_util + 0.05, (eng_util, lock_util, lens)


class TestSlidingWindowPool:
    """Rolling ring pool (attn_window): per-row ring arithmetic through
    the shared vector-slot _cached_attention must reproduce
    generate()'s rolling-cache decode exactly."""

    def _cfg(self, **kw):
        base = dict(vocab=61, dim=32, n_layers=2, n_heads=4,
                    attn_impl="dense", attn_window=6)
        base.update(kw)
        return T.TransformerConfig(**base)

    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_pool_matches_generate_rolling(self):
        cfg = self._cfg()
        p = T.init_params(jax.random.key(6), cfg)
        eng = DecodeEngine(p, cfg, slots=2, max_len=40)
        # prompts BOTH shorter and longer than the window
        ps = prompts_rng(4, [3, 9, 5, 11], seed=61)
        got = eng.serve(ps, max_new=10)
        for pr, g in zip(ps, got):
            out = T.generate(p, cfg, jnp.asarray(pr)[None, :], steps=10)
            assert g == [int(t) for t in np.asarray(out[0, len(pr):])], pr

    @pytest.mark.slow

    def test_bucketed_window_matches_unpadded(self):
        """Bucket padding + window: the ring takes REAL positions only,
        so the decode matches generate() on the unpadded prompt (a
        combination generate() itself cannot serve — it raises on
        attn_window + prompt_lens)."""
        cfg = self._cfg()
        p = T.init_params(jax.random.key(6), cfg)
        eng = DecodeEngine(p, cfg, slots=2, max_len=40)
        ps = prompts_rng(3, [4, 9, 7], seed=62)
        got = eng.serve(ps, max_new=8, buckets=(12,))
        for pr, g in zip(ps, got):
            out = T.generate(p, cfg, jnp.asarray(pr)[None, :], steps=8)
            assert g == [int(t) for t in np.asarray(out[0, len(pr):])], pr

    @pytest.mark.slow

    def test_int8_ring_pool(self):
        cfg = self._cfg(kv_cache_dtype="int8")
        p = T.init_params(jax.random.key(6), cfg)
        eng = DecodeEngine(p, cfg, slots=2, max_len=40)
        ps = prompts_rng(3, [5, 9, 4], seed=63)
        got = eng.serve(ps, max_new=8)
        agree = n = 0
        for pr, g in zip(ps, got):
            out = T.generate(p, cfg, jnp.asarray(pr)[None, :], steps=8)
            ref = [int(t) for t in np.asarray(out[0, len(pr):])]
            agree += sum(a == b for a, b in zip(g, ref)); n += len(ref)
        assert agree / n >= 0.9, (agree, n)

    @pytest.mark.slow

    def test_window_requests_unbounded_by_max_len(self):
        """The ring has no physical capacity bound: a windowed request
        decodes past max_len (bounded by max_new/eos alone), and a
        prompt LONGER than max_len admits fine (the ring keeps its
        last W positions) — both match generate()."""
        cfg = self._cfg()
        p = T.init_params(jax.random.key(6), cfg)
        eng = DecodeEngine(p, cfg, slots=1, max_len=10)
        long_prompt = prompts_rng(1, [14], seed=64)[0]  # > max_len
        got = eng.serve([long_prompt], max_new=18)      # past max_len
        out = T.generate(p, cfg, jnp.asarray(long_prompt)[None, :],
                         steps=18)
        assert got[0] == [int(t) for t in
                          np.asarray(out[0, len(long_prompt):])]
        assert len(got[0]) == 18


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_moe_pool_matches_generate():
    """MoE configs through the pool: the shared _block_parts body makes
    the engine's per-request decode match solo generate() (capacity is
    per-step-token-count in BOTH paths; at test scale no drops)."""
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense", moe_experts=4,
                              moe_every=2)
    p = T.init_params(jax.random.key(0), cfg)
    eng = DecodeEngine(p, cfg, slots=2, max_len=24)
    ps = prompts_rng(3, [5, 8, 4], seed=81)
    got = eng.serve(ps, max_new=8)
    for pr, g in zip(ps, got):
        out = T.generate(p, cfg, jnp.asarray(pr)[None, :], steps=8)
        assert g == [int(t) for t in np.asarray(out[0, len(pr):])], pr


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_moe_buckets_tight_capacity_matches_generate():
    """MoE + bucket padding + inactive slots under a TIGHT capacity
    factor: bucket-pad tokens (prefill) and inactive slots (decode)
    must claim NO expert capacity — with the masks missing, padding
    would evict real tokens at capacity_factor=1.0 and the engine
    would diverge from the documented exact-greedy generate() parity
    (ADVICE r5 medium finding)."""
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense", moe_experts=4,
                              moe_every=2, moe_capacity_factor=1.0)
    p = T.init_params(jax.random.key(0), cfg)
    eng = DecodeEngine(p, cfg, slots=2, max_len=24)
    # short prompts in a 16-wide bucket: most prefill tokens are pads
    ps = prompts_rng(3, [4, 9, 6], seed=83)
    got = eng.serve(ps, max_new=6, buckets=(16,))
    for pr, g in zip(ps, got):
        out = T.generate(p, cfg, jnp.asarray(pr)[None, :], steps=6)
        assert g == [int(t) for t in np.asarray(out[0, len(pr):])], pr
    # decode with an INACTIVE co-slot (solo request in a 2-slot pool):
    # the dead slot must not eat capacity from the live one
    solo = eng.serve([ps[0]], max_new=6, buckets=(16,))
    out = T.generate(p, cfg, jnp.asarray(ps[0])[None, :], steps=6)
    assert solo[0] == [int(t) for t in np.asarray(out[0, len(ps[0]):])]


class TestPrefillLengthValidation:
    """ADVICE r5 low finding: validate the REAL length, not the padded
    bucket length, and reject impossible buckets before any decode."""

    def test_bucket_equal_to_max_len_serves_short_prompts(self, params):
        """serve(buckets=(max_len,)) used to raise mid-run for every
        prompt (padded t0 >= max_len); short prompts physically fit
        and must decode exactly like generate()."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=16)
        ps = prompts_rng(3, [3, 7, 5], seed=87)
        got = eng.serve(ps, max_new=4, buckets=(16,))
        for p, g in zip(ps, got):
            assert g == ref_tokens(params, p, 4), (p, g)

    def test_bucket_beyond_max_len_fails_up_front(self, params):
        """An unservable bucket is rejected in serve() BEFORE any
        prefill/decode work, not mid-run from admit()."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=16)
        with pytest.raises(ValueError, match="max_len"):
            eng.serve(prompts_rng(2, [3, 5], seed=88), max_new=4,
                      buckets=(24,))

    def test_true_len_at_capacity_rejected(self, params):
        """A REAL length with no room for even one generated token is
        still an error (the physical bound that remains)."""
        eng = DecodeEngine(params, CFG, slots=1, max_len=16)
        with pytest.raises(ValueError, match="true_len"):
            eng.prefill(eng.init_state(), 0,
                        np.arange(16, dtype=np.int32))

    def test_padded_len_beyond_cache_rejected(self, params):
        eng = DecodeEngine(params, CFG, slots=1, max_len=16)
        with pytest.raises(ValueError, match="padded"):
            eng.prefill(eng.init_state(), 0,
                        np.arange(20, dtype=np.int32), true_len=4)


class TestServeEntryValidation:
    """ISSUE 2 satellite: unservable traffic must reject AT serve()
    ENTRY — before ANY request burns prefill/decode work — not deep in
    prefill mid-run."""

    def test_oversized_prompt_rejected_before_any_work(self, params):
        """Prompt longer than the largest bucket: entry ValueError,
        zero prefills — even when OTHER prompts are servable."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=32)
        ps = prompts_rng(2, [5, 4], seed=41) + \
            prompts_rng(1, [12], seed=42)       # last one oversized
        with pytest.raises(ValueError, match="largest bucket"):
            eng.serve(ps, max_new=4, buckets=(8,))
        assert not hasattr(eng, "last_stats")   # no serve work ran

    def test_prompt_at_max_len_rejected_at_entry(self, params):
        """A full-cache prompt with no room for one generated token is
        an entry error (was: mid-run, from prefill)."""
        eng = DecodeEngine(params, CFG, slots=2, max_len=8)
        ps = prompts_rng(1, [4], seed=43) + prompts_rng(1, [8], seed=44)
        with pytest.raises(ValueError, match="max_len"):
            eng.serve(ps, max_new=4)

    def test_empty_prompt_rejected_at_entry(self, params):
        eng = DecodeEngine(params, CFG, slots=1, max_len=16)
        with pytest.raises(ValueError, match="empty"):
            eng.serve([np.zeros((0,), np.int32)], max_new=4)

    def test_windowed_long_prompts_still_admitted(self, params):
        """The ring pool has no physical length bound — entry checks
        must NOT reject what the window can serve."""
        cfg = dataclasses.replace(CFG, attn_window=6)
        p_ = T.init_params(jax.random.key(6), cfg)
        eng = DecodeEngine(p_, cfg, slots=1, max_len=10)
        long_prompt = prompts_rng(1, [14], seed=45)[0]
        got = eng.serve([long_prompt], max_new=4)
        out = T.generate(p_, cfg, jnp.asarray(long_prompt)[None, :],
                         steps=4)
        assert got[0] == [int(t) for t in
                          np.asarray(out[0, len(long_prompt):])]


def test_engine_serve_golden():
    """Golden serving transcript (the seq2seq_gen_golden idiom): a
    fixed pool + fixed traffic must reproduce the committed outputs
    byte-for-byte — any decode-math drift (mask, ring, head, eos
    accounting) fails here even if self-consistency still holds."""
    import json
    import pathlib

    golden = json.loads((pathlib.Path(__file__).parent / "golden" /
                         "engine_serve_golden.json").read_text())
    params = T.init_params(jax.random.key(0), CFG)
    eng = DecodeEngine(params, CFG, slots=2, max_len=32,
                       eos_id=golden["eos_id"])
    outs = eng.serve([np.asarray(p, np.int32) for p in golden["prompts"]],
                     max_new=golden["max_new"],
                     buckets=tuple(golden["buckets"]))
    assert outs == golden["outputs"], (outs, golden["outputs"])


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_explicit_seed_is_cotenancy_invariant(params):
    """A sampled request with an explicit per-request seed draws from
    its OWN stream: identical tokens whether served alone, in a busy
    pool, or admitted in a different order — the guarantee per-slot
    rng streams exist for."""
    target = prompts_rng(1, [6], seed=91)[0]
    spec = {"temperature": 1.0, "top_p": 0.95, "seed": 1234}
    mk = lambda: DecodeEngine(params, CFG, slots=2, max_len=24, seed=5)

    solo = mk().serve([target], max_new=6, sampling=[spec])[0]

    others = prompts_rng(3, [4, 8, 5], seed=92)
    crowd = [{"temperature": 0.8, "seed": 7}, {}, {"top_k": 9,
             "temperature": 1.3, "seed": 8}]
    first = mk().serve([target] + others, max_new=6,
                       sampling=[spec] + crowd)[0]
    last = mk().serve(others + [target], max_new=6,
                      sampling=crowd + [spec])[-1]
    assert solo == first == last, (solo, first, last)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_logprobs_match_score(params):
    """serve(return_logprobs=True): each emitted token's logprob must
    equal transformer.score()'s gold log-probability at the same
    position of the full (prompt + generated) sequence — the engine
    reports the same rescoring quantity the reference's
    SequenceGenerator scores carry."""
    ps = prompts_rng(3, [5, 8, 4], seed=71)
    eng = DecodeEngine(params, CFG, slots=2, max_len=24)
    toks, lps = eng.serve(ps, max_new=6, return_logprobs=True)
    for p, g, lp in zip(ps, toks, lps):
        full = jnp.asarray(np.concatenate([p, np.asarray(g)]),
                           jnp.int32)[None, :]
        gold, _ = T.score(params, CFG, full)
        want = np.asarray(gold[0, len(p) - 1:len(p) - 1 + len(g)])
        np.testing.assert_allclose(np.asarray(lp), want, atol=2e-5)


def test_pool_stats(params):
    """serve() leaves a PoolStats on the engine: token/step accounting
    consistent with the outputs, utilization in (0, 1]."""
    ps = prompts_rng(5, [4, 6, 5, 7, 4], seed=85)
    eng = DecodeEngine(params, CFG, slots=2, max_len=24)
    got = eng.serve(ps, max_new=6)
    st = eng.last_stats
    assert st.requests == 5 and st.prefills == 5
    assert st.tokens == sum(len(g) for g in got)
    assert st.steps >= max(len(g) for g in got)
    assert 0 < st.utilization(2) <= 1


@pytest.mark.slow


def test_edge_empty_and_single_token(params):
    """Edge traffic: an empty request list returns immediately; a
    single-token prompt (t0=1) prefills and decodes correctly."""
    eng = DecodeEngine(params, CFG, slots=2, max_len=16)
    assert eng.serve([], max_new=4) == []
    one = np.asarray([7], np.int32)
    got = eng.serve([one], max_new=5)
    out = T.generate(params, CFG, jnp.asarray(one)[None, :], steps=5)
    assert got[0] == [int(t) for t in np.asarray(out[0, 1:])]
