"""Sharded matmul primitives vs the jnp oracle, across shard counts.

Every `parallel.blocked_matmul` form — output-dim ring, contracting-dim
reduce ring, weight-streaming blocked matmul, and the row-parallel
`tp_dense` consumer seam — must match `matmul_reference` on the
virtual CPU mesh in BOTH its overlap and naive arms, for even AND odd
ring sizes (the bidirectional gather ring takes a different final hop
on even rings; an off-by-one in the block bookkeeping passes one
parity and fails the other). Tolerances are allclose, not bit-equal:
the ring adds partial products in ring order while the oracle reduces
one big contraction, and fp reassociation differs — `atol`/`rtol`
2e-6 on f32 is ulp-scale for these magnitudes, anything real fails it.

The pipeline tests pin the consumer contract: `tp_axis` routes every
stage matmul through `tp_dense` over a second mesh axis and must
reproduce the plain pipeline's outputs (and gradients — ppermute's
transpose runs backward through the ring).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.parallel import blocked_matmul as BM
from paddle_tpu.parallel import pipeline as PP

pytestmark = pytest.mark.kernels

TOL = dict(rtol=2e-6, atol=2e-6)


def _mesh(p):
    if len(jax.devices()) < p:
        pytest.skip(f"needs {p} devices")
    return Mesh(np.array(jax.devices()[:p]), ("x",))


def _xw(np_rng, m, k, n, dtype=np.float32):
    return (jnp.asarray(np_rng.standard_normal((m, k)).astype(dtype)),
            jnp.asarray(np_rng.standard_normal((k, n)).astype(dtype)))


class TestCollectiveMatmul:
    @pytest.mark.parametrize("p", [2, 4])
    @pytest.mark.parametrize("mode", ["gather", "reduce"])
    @pytest.mark.parametrize("overlap", [True, False])
    def test_matches_oracle(self, np_rng, p, mode, overlap):
        mesh = _mesh(p)
        x, w = _xw(np_rng, 4 * p, 6 * p, 5 * p)
        ref = BM.matmul_reference(x, w)
        fn = jax.jit(BM.collective_matmul(mesh, axis="x", mode=mode,
                                          overlap=overlap))
        got = fn(x, w)
        assert got.shape == ref.shape and got.dtype == ref.dtype
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   **TOL)

    @pytest.mark.slow
    @pytest.mark.parametrize("p", [3, 8])
    def test_odd_and_full_rings(self, np_rng, p):
        # odd ring: the bidirectional gather has NO antipodal extra
        # hop; p=8: the full mesh, deepest reduce chain
        mesh = _mesh(p)
        x, w = _xw(np_rng, 3 * p, 4 * p, 2 * p)
        ref = BM.matmul_reference(x, w)
        fns = {  # explicit literal: one jit wrapper per arm (GL004)
            "gather": jax.jit(BM.collective_matmul(
                mesh, axis="x", mode="gather", overlap=True)),
            "reduce": jax.jit(BM.collective_matmul(
                mesh, axis="x", mode="reduce", overlap=True)),
        }
        for mode, fn in fns.items():
            np.testing.assert_allclose(np.asarray(fn(x, w)),
                                       np.asarray(ref), **TOL)

    def test_reduce_rejects_untileable_rows(self, np_rng):
        mesh = _mesh(2)
        x, w = _xw(np_rng, 5, 8, 4)  # M=5 not divisible by p=2
        with pytest.raises(ValueError, match="M % p"):
            jax.jit(BM.collective_matmul(mesh, axis="x",
                                         mode="reduce"))(x, w)

    def test_bf16_accumulates_in_f32(self, np_rng):
        # the >=f32 accumulation contract: bf16 operands, bf16 result,
        # but partial products summed wide — matches the oracle, which
        # does the same (a bf16-accumulated ring would drift visibly)
        mesh = _mesh(4)
        x, w = _xw(np_rng, 8, 32, 8)
        x, w = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
        ref = BM.matmul_reference(x, w)
        assert ref.dtype == jnp.bfloat16
        fns = {  # explicit literal: one jit wrapper per arm (GL004)
            "gather": jax.jit(BM.collective_matmul(
                mesh, axis="x", mode="gather", overlap=True)),
            "reduce": jax.jit(BM.collective_matmul(
                mesh, axis="x", mode="reduce", overlap=True)),
        }
        for mode, fn in fns.items():
            got = fn(x, w)
            assert got.dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(got, np.float32), np.asarray(ref, np.float32),
                rtol=2e-2, atol=2e-2)


class TestStreamMatmul:
    @pytest.mark.parametrize("p", [2, 4])
    def test_matches_oracle(self, np_rng, p):
        mesh = _mesh(p)
        x, w = _xw(np_rng, 6, 4 * p, 3 * p)
        ref = BM.matmul_reference(x, w)
        got = jax.jit(BM.blocked_matmul(mesh, axis="x"))(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   **TOL)


class TestTpDense:
    @pytest.mark.parametrize("overlap", [True, False])
    def test_matches_oracle(self, np_rng, overlap):
        mesh = _mesh(4)
        x, w = _xw(np_rng, 8, 16, 12)
        ref = BM.matmul_reference(x, w)

        from jax.sharding import PartitionSpec as P
        from paddle_tpu.parallel import compat

        fn = compat.shard_map(
            lambda a, b: BM.tp_dense(a, b, axis="x", overlap=overlap),
            mesh=mesh, in_specs=(P(None, None), P("x", None)),
            out_specs=P(None, None), check_vma=False)
        got = jax.jit(fn)(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   **TOL)

    def test_untileable_batch_falls_back_to_psum(self, np_rng):
        # B=5 doesn't tile over p=4: the ring form must degrade to the
        # textbook psum, not crash — same numbers either way
        mesh = _mesh(4)
        x, w = _xw(np_rng, 5, 16, 12)
        ref = BM.matmul_reference(x, w)

        from jax.sharding import PartitionSpec as P
        from paddle_tpu.parallel import compat

        fn = compat.shard_map(
            lambda a, b: BM.tp_dense(a, b, axis="x", overlap=True),
            mesh=mesh, in_specs=(P(None, None), P("x", None)),
            out_specs=P(None, None), check_vma=False)
        got = jax.jit(fn)(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   **TOL)


def _stage_params(np_rng, n_stage, k):
    return [{"w": jnp.asarray(
                 np_rng.standard_normal((k, k)).astype(np.float32)) * 0.3,
             "b": jnp.asarray(
                 np_rng.standard_normal((k,)).astype(np.float32))}
            for _ in range(n_stage)]


def _stage_plain(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def _stage_tp(p, x, mm):
    return jax.nn.relu(mm(x, p["w"]) + p["b"])


class TestPipelineTensorParallel:
    @pytest.mark.parametrize("n_pipe,n_tp", [(2, 4), (4, 2)])
    def test_forward_matches_plain_pipeline(self, np_rng, n_pipe,
                                            n_tp):
        if len(jax.devices()) < n_pipe * n_tp:
            pytest.skip(f"needs {n_pipe * n_tp} devices")
        mesh = Mesh(np.array(jax.devices()).reshape(n_pipe, n_tp),
                    ("pipe", "tp"))
        pipe_mesh = Mesh(np.array(jax.devices()[:n_pipe]), ("pipe",))
        k, m, bm = 16, 5, 8
        stacked = PP.stack_stage_params(
            _stage_params(np_rng, n_pipe, k))
        micro_x = jnp.asarray(
            np_rng.standard_normal((m, bm, k)).astype(np.float32))
        ref = jax.jit(PP.make_pipeline_forward(_stage_plain,
                                               pipe_mesh))(
            PP.shard_stage_params(stacked, pipe_mesh), micro_x)
        sharded = PP.shard_stage_params(stacked, mesh, tp_axis="tp")
        fwds = {  # explicit literal: one jit wrapper per arm (GL004)
            "overlap": jax.jit(PP.make_pipeline_forward(
                _stage_tp, mesh, tp_axis="tp", tp_overlap=True)),
            "naive": jax.jit(PP.make_pipeline_forward(
                _stage_tp, mesh, tp_axis="tp", tp_overlap=False)),
        }
        for arm, fwd in fwds.items():
            np.testing.assert_allclose(np.asarray(fwd(sharded, micro_x)),
                                       np.asarray(ref), **TOL)

    @pytest.mark.slow
    def test_gradients_flow_through_ring(self, np_rng):
        """autodiff through scan + ppermute + the reduce ring: the tp
        pipeline's parameter gradients must match the plain pipeline's
        (ppermute transposes to the reverse permute; a broken ring
        transpose shows up here, not in forward)."""
        n_pipe, n_tp = 2, 4
        mesh = Mesh(np.array(jax.devices()).reshape(n_pipe, n_tp),
                    ("pipe", "tp"))
        pipe_mesh = Mesh(np.array(jax.devices()[:n_pipe]), ("pipe",))
        k, m, bm = 8, 4, 4
        stacked = PP.stack_stage_params(
            _stage_params(np_rng, n_pipe, k))
        micro_x = jnp.asarray(
            np_rng.standard_normal((m, bm, k)).astype(np.float32))

        def loss_of(fwd, params):
            return lambda p: jnp.sum(fwd(p, micro_x) ** 2)

        fwd_ref = PP.make_pipeline_forward(_stage_plain, pipe_mesh)
        g_ref = jax.jit(jax.grad(loss_of(fwd_ref, stacked)))(
            PP.shard_stage_params(stacked, pipe_mesh))
        fwd_tp = PP.make_pipeline_forward(_stage_tp, mesh,
                                          tp_axis="tp")
        g_tp = jax.jit(jax.grad(loss_of(fwd_tp, stacked)))(
            PP.shard_stage_params(stacked, mesh, tp_axis="tp"))
        for leaf_ref, leaf_tp in zip(jax.tree.leaves(g_ref),
                                     jax.tree.leaves(g_tp)):
            np.testing.assert_allclose(np.asarray(leaf_tp),
                                       np.asarray(leaf_ref),
                                       rtol=1e-5, atol=1e-5)
