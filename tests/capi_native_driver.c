/* Pure-C driver for the Python-free native inference engine.
 *
 * Compiled WITHOUT any Python flags (see test_native_infer.py: the link
 * line is just -lpaddle_tpu_infer -lm -lpthread) — the proof the serving
 * path needs no interpreter, matching the reference's C inference API
 * (reference: capi/gradient_machine.h:36, examples/model_inference).
 *
 * Also exercises the reference's multi-thread serving pattern
 * (capi/gradient_machine.h:62 create_shared_param: N threads share one
 * parameter set): T threads run forwards CONCURRENTLY on one model
 * handle and every thread must reproduce the golden outputs.
 *
 * usage: driver model.ptni input.f32 golden.f32 batch n_threads
 */

#include <math.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern void* ptn_load(const char* path);
extern void ptn_free(void* model);
extern int ptn_input_rank(void* model);
extern long long ptn_input_dim(void* model, int i);
extern long long ptn_output_dim(void* model);
extern int ptn_forward(void* model, const float* in, long long batch,
                       float* out);
extern const char* ptn_last_error(void);

static float* read_f32(const char* path, long* count) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path);
    exit(2);
  }
  fseek(f, 0, SEEK_END);
  long bytes = ftell(f);
  fseek(f, 0, SEEK_SET);
  float* buf = (float*)malloc(bytes);
  if (fread(buf, 1, bytes, f) != (size_t)bytes) {
    fprintf(stderr, "short read %s\n", path);
    exit(2);
  }
  fclose(f);
  *count = bytes / 4;
  return buf;
}

struct job {
  void* model;
  const float* in;
  const float* golden;
  long long batch;
  long long out_per;
  int id;
  int failed;
};

static void* worker(void* arg) {
  struct job* j = (struct job*)arg;
  long long n = j->batch * j->out_per;
  float* out = (float*)malloc(n * sizeof(float));
  int rounds;
  for (rounds = 0; rounds < 3; rounds++) {
    memset(out, 0, n * sizeof(float));
    if (ptn_forward(j->model, j->in, j->batch, out) != 0) {
      fprintf(stderr, "thread %d: forward failed: %s\n", j->id,
              ptn_last_error());
      j->failed = 1;
      break;
    }
    long long i;
    for (i = 0; i < n; i++) {
      float diff = fabsf(out[i] - j->golden[i]);
      float tol = 1e-4f + 1e-4f * fabsf(j->golden[i]);
      if (diff > tol) {
        fprintf(stderr, "thread %d round %d: out[%lld]=%g golden=%g\n",
                j->id, rounds, i, out[i], j->golden[i]);
        j->failed = 1;
        break;
      }
    }
    if (j->failed) break;
  }
  free(out);
  return NULL;
}

int main(int argc, char** argv) {
  if (argc != 6) {
    fprintf(stderr,
            "usage: %s model.ptni input.f32 golden.f32 batch n_threads\n",
            argv[0]);
    return 2;
  }
  long long batch = atoll(argv[4]);
  int n_threads = atoi(argv[5]);

  void* model = ptn_load(argv[1]);
  if (!model) {
    fprintf(stderr, "load failed: %s\n", ptn_last_error());
    return 1;
  }

  long in_count, golden_count;
  float* in = read_f32(argv[2], &in_count);
  float* golden = read_f32(argv[3], &golden_count);

  /* sanity: input element count must match batch x input dims */
  long long expect_in = batch;
  int r, rank = ptn_input_rank(model);
  for (r = 1; r < rank; r++) expect_in *= ptn_input_dim(model, r);
  if (expect_in != in_count) {
    fprintf(stderr, "input count %ld != expected %lld\n", in_count,
            expect_in);
    return 2;
  }
  long long out_per = ptn_output_dim(model);
  if (batch * out_per != golden_count) {
    fprintf(stderr, "golden count %ld != %lld\n", golden_count,
            batch * out_per);
    return 2;
  }

  /* single-shot correctness */
  struct job j0 = {model, in, golden, batch, out_per, 0, 0};
  worker(&j0);
  if (j0.failed) return 1;
  printf("single-thread forward matches golden (%lld x %lld)\n", batch,
         out_per);

  /* concurrent serving: N threads share ONE model handle */
  pthread_t threads[64];
  struct job jobs[64];
  int t;
  if (n_threads > 64) n_threads = 64;
  for (t = 0; t < n_threads; t++) {
    jobs[t] = j0;
    jobs[t].id = t + 1;
    pthread_create(&threads[t], NULL, worker, &jobs[t]);
  }
  int failed = 0;
  for (t = 0; t < n_threads; t++) {
    pthread_join(threads[t], NULL);
    failed |= jobs[t].failed;
  }
  if (failed) return 1;
  printf("%d concurrent threads x 3 rounds all match golden\n", n_threads);

  ptn_free(model);
  free(in);
  free(golden);
  return 0;
}
