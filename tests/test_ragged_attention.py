"""Interpret-mode parity suite for the fused ragged paged-attention
kernel: the Pallas page-table walk must match the jnp oracle
BIT-FOR-BIT, under jit on both sides — jit is what the engine runs,
and eager-vs-jit XLA fusion differs by ulps, so comparing compiled
against compiled is the honest contract (the kernel and the jitted
oracle agree exactly; see test_jit_is_the_contract for the pin).

The bench chip gate has been wedged since r03, so CPU interpret mode
IS the acceptance currency: it executes the same primitive sequence
the TPU kernel issues (DMA walk per page-table entry, shared attention
body over VMEM scratch) on the same XLA CPU backend as the oracle."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import paged_attention as PA
from paddle_tpu.ops import ragged_paged_attention as RPA

pytestmark = pytest.mark.pallas

PAGE, HKV, DH = 4, 2, 8


def _arena(np_rng, num_pages):
    shape = (num_pages, PAGE, HKV, DH)
    return (jnp.asarray(np_rng.standard_normal(shape), jnp.float32),
            jnp.asarray(np_rng.standard_normal(shape), jnp.float32))


def _jit(fn, **static):
    return jax.jit(functools.partial(fn, **static))


def assert_kernel_matches_oracle(q, ka, va, pt, pos0, active, *,
                                 page_size, max_len):
    kw = dict(page_size=page_size, max_len=max_len)
    ref = _jit(RPA.ragged_reference, **kw)(q, ka, va, pt, pos0, active)
    ker = _jit(RPA.ragged_pallas, **kw)(q, ka, va, pt, pos0, active)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(ker))
    return ref


class TestRaggedParity:
    """Bit-identity across the ragged shape zoo."""

    def test_single_token_decode(self, np_rng):
        ka, va = _arena(np_rng, 9)
        pt = jnp.asarray(np_rng.randint(0, 9, (5, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((5, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 3, 7, 13, 5], jnp.int32)
        active = jnp.ones((5,), bool)
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=14)

    def test_page_boundary_crossing_window(self, np_rng):
        # TQ=3 windows straddling page boundaries: pos0 = PAGE-1 puts
        # queries on both sides of a block edge; pos0 = PAGE-2 ends
        # exactly ON the edge
        ka, va = _arena(np_rng, 8)
        pt = jnp.asarray(np_rng.randint(0, 8, (4, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 3, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([PAGE - 1, PAGE - 2, 2 * PAGE - 1, 0],
                           jnp.int32)
        active = jnp.ones((4,), bool)
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=16)

    def test_full_page_prompt_and_max_len_edge(self, np_rng):
        # rows at exactly-full pages, and the last query landing on
        # max_len - 1 (the static slice edge)
        ka, va = _arena(np_rng, 8)
        pt = jnp.asarray(np_rng.randint(0, 8, (3, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((3, 2, 4, DH)),
                        jnp.float32)
        max_len = 4 * PAGE
        pos0 = jnp.asarray([PAGE, 2 * PAGE, max_len - 2], jnp.int32)
        active = jnp.ones((3,), bool)
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=max_len)

    def test_mixed_chunk_and_decode_batch(self, np_rng):
        # one launch, ragged mix: a prefill chunk mid-prompt (TQ real
        # queries), a fresh prompt at position 0, a deep decode row
        # (TQ padding beyond its single real query), an inactive row
        ka, va = _arena(np_rng, 12)
        pt = jnp.asarray(np_rng.randint(0, 12, (4, 5)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 4, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([6, 0, 15, 19], jnp.int32)
        active = jnp.asarray([True, True, True, False])
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=19)

    def test_sentinel_and_inactive_rows(self, np_rng):
        # unmapped table entries carry the sentinel id (= num_pages):
        # the kernel's min-clip must read the same clipped page the
        # oracle's mode="clip" gather reads, and inactive rows must
        # reproduce the oracle's all-masked softmax exactly
        ka, va = _arena(np_rng, 6)
        pt = jnp.asarray(np_rng.randint(0, 6, (3, 4)), jnp.int32)
        pt = pt.at[0, 2:].set(6).at[2, :].set(6)
        q = jnp.asarray(np_rng.standard_normal((3, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([5, 9, 21], jnp.int32)
        active = jnp.asarray([True, True, False])
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=12)

    def test_mha_no_grouping(self, np_rng):
        # H == Hkv (group size 1): the grouped path degenerates to MHA
        ka, va = _arena(np_rng, 6)
        pt = jnp.asarray(np_rng.randint(0, 6, (2, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((2, 2, HKV, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([1, 6], jnp.int32)
        active = jnp.ones((2,), bool)
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=10)

    def test_max_len_not_page_multiple(self, np_rng):
        # the static slice cuts mid-page: the walk's last block is
        # partially exposed
        ka, va = _arena(np_rng, 7)
        pt = jnp.asarray(np_rng.randint(0, 7, (3, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((3, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 5, 9], jnp.int32)
        active = jnp.ones((3,), bool)
        assert_kernel_matches_oracle(q, ka, va, pt, pos0, active,
                                     page_size=PAGE, max_len=10)

    @pytest.mark.slow
    def test_ragged_shape_sweep(self, np_rng):
        # randomized sweep over (rows, TQ, pages-per-slot, max_len,
        # positions): the wide net behind the targeted cases above.
        # 6 trials: every trial is a fresh compile (distinct shapes),
        # so the count is a direct tier-1 budget lever — the targeted
        # cases above carry the known-tricky geometries
        for trial in range(6):
            num_pages = int(np_rng.randint(4, 14))
            mp = int(np_rng.randint(2, 6))
            r = int(np_rng.randint(1, 7))
            tq = int(np_rng.randint(1, 6))
            max_len = int(np_rng.randint(tq, mp * PAGE + 1))
            ka, va = _arena(np_rng, num_pages)
            pt = jnp.asarray(
                np_rng.randint(0, num_pages + 1, (r, mp)), jnp.int32)
            q = jnp.asarray(
                np_rng.standard_normal((r, tq, 2 * HKV, DH)),
                jnp.float32)
            pos0 = jnp.asarray(
                np_rng.randint(0, max(1, max_len - tq + 1), (r,)),
                jnp.int32)
            active = jnp.asarray(np_rng.randint(0, 2, (r,)) > 0)
            assert_kernel_matches_oracle(
                q, ka, va, pt, pos0, active, page_size=PAGE,
                max_len=max_len)


class TestDispatchAndIntegration:
    def test_jit_is_the_contract(self, np_rng):
        """Pin WHY the suite compares under jit: the eager oracle and
        the jitted oracle differ by ulps (XLA fusion), while the
        kernel matches the jitted oracle exactly. If this ever starts
        failing because eager == jit, the comment in the module header
        is stale — not a bug."""
        ka, va = _arena(np_rng, 9)
        pt = jnp.asarray(np_rng.randint(0, 9, (5, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((5, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 3, 7, 13, 5], jnp.int32)
        active = jnp.ones((5,), bool)
        kw = dict(page_size=PAGE, max_len=14)
        ref_j = _jit(RPA.ragged_reference, **kw)(q, ka, va, pt, pos0,
                                                 active)
        ker_e = RPA.ragged_pallas(q, ka, va, pt, pos0, active, **kw)
        np.testing.assert_array_equal(np.asarray(ref_j),
                                      np.asarray(ker_e))

    def test_auto_dispatch_is_jnp_off_tpu(self, np_rng):
        ka, va = _arena(np_rng, 6)
        pt = jnp.asarray(np_rng.randint(0, 6, (2, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((2, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([2, 7], jnp.int32)
        active = jnp.ones((2,), bool)
        kw = dict(page_size=PAGE, max_len=9)
        auto = RPA.ragged_attention(q, ka, va, pt, pos0, active, **kw)
        ref = RPA.ragged_reference(q, ka, va, pt, pos0, active, **kw)
        # auto off-TPU must be the EAGER jnp path, byte-for-byte
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))

    def test_int8_arena_dispatches_through_kernel(self, np_rng):
        """int8 `(s8, scale)` pair arenas no longer exclude the kernel:
        forced pallas runs the dequant-fused walk and must match the
        jnp dequant-gather oracle bit-for-bit (the deep parity zoo
        lives in tests/test_ragged_int8.py; this pins the DISPATCH
        contract flip from the pre-fusion fallback behaviour)."""
        ka, va = _arena(np_rng, 6)
        ka8 = PA.kv_quantize(ka)
        va8 = PA.kv_quantize(va)
        pt = jnp.asarray(np_rng.randint(0, 6, (2, 3)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((2, 1, 4, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([2, 7], jnp.int32)
        active = jnp.ones((2,), bool)
        kw = dict(page_size=PAGE, max_len=9)
        forced = _jit(RPA.ragged_attention, impl="pallas", **kw)(
            q, ka8, va8, pt, pos0, active)
        ref = _jit(RPA.ragged_reference, **kw)(q, ka8, va8, pt, pos0,
                                               active)
        np.testing.assert_array_equal(np.asarray(forced),
                                      np.asarray(ref))
        # a small int8 walk fits VMEM (data + scale planes + dequant
        # scratch all accounted) — auto-dispatch on TPU would fuse it
        assert RPA.fits_vmem(ka8, pt, page_size=PAGE, max_len=9)

    def test_fits_vmem_gate(self, np_rng):
        ka, _ = _arena(np_rng, 6)
        pt = jnp.zeros((2, 3), jnp.int32)
        assert RPA.fits_vmem(ka, pt, page_size=PAGE, max_len=12)
        huge = jnp.zeros((4, 2048, 32, 128), jnp.float32)
        pt_huge = jnp.zeros((1, 4), jnp.int32)
        assert not RPA.fits_vmem(huge, pt_huge, page_size=2048,
                                 max_len=8192)

    def test_verify_tq1_is_decode(self, np_rng):
        """paged_verify_attention with a one-token window must be
        paged_decode_attention, bit-for-bit — the spec path's K=0
        degenerate IS a plain decode step."""
        ka, va = _arena(np_rng, 9)
        pt = jnp.asarray(np_rng.randint(0, 9, (4, 4)), jnp.int32)
        q = jnp.asarray(np_rng.standard_normal((4, 1, 4, DH)),
                        jnp.float32)
        k = jnp.asarray(np_rng.standard_normal((4, 1, HKV, DH)),
                        jnp.float32)
        v = jnp.asarray(np_rng.standard_normal((4, 1, HKV, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([0, 5, 9, 30], jnp.int32)
        active = jnp.asarray([True, True, True, False])
        kw = dict(page_size=PAGE, max_len=14)
        out_d, ka_d, va_d = _jit(PA.paged_decode_attention, **kw)(
            q, k, v, ka, va, pt, pos0, active)
        out_v, ka_v, va_v = _jit(PA.paged_verify_attention, **kw)(
            q, k, v, ka, va, pt, pos0, active)
        for a, b in ((out_d, out_v), (ka_d, ka_v), (va_d, va_v)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_verify_window_matches_sequential_decode(self, np_rng):
        """A TQ=3 verify window must equal three sequential decode
        steps' attention reads: same writes, same causal exposure —
        the property that makes verify-in-one-launch sound. Page
        tables are DISJOINT across rows — the pool invariant (slots
        never share a writable page; shared prefix pages are read-only
        because decode writes land beyond them) that makes the
        one-launch write sound."""
        ka, va = _arena(np_rng, 9)
        pt = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
        tq = 3
        q = jnp.asarray(np_rng.standard_normal((2, tq, 4, DH)),
                        jnp.float32)
        k = jnp.asarray(np_rng.standard_normal((2, tq, HKV, DH)),
                        jnp.float32)
        v = jnp.asarray(np_rng.standard_normal((2, tq, HKV, DH)),
                        jnp.float32)
        pos0 = jnp.asarray([2, PAGE - 1], jnp.int32)
        active = jnp.ones((2,), bool)
        kw = dict(page_size=PAGE, max_len=14)
        out_v, ka_v, va_v = _jit(PA.paged_verify_attention, **kw)(
            q, k, v, ka, va, pt, pos0, active)
        ka_s, va_s = ka, va
        outs = []
        step = _jit(PA.paged_decode_attention, **kw)
        for i in range(tq):
            o, ka_s, va_s = step(q[:, i:i + 1], k[:, i:i + 1],
                                 v[:, i:i + 1], ka_s, va_s, pt,
                                 pos0 + i, active)
            outs.append(o)
        np.testing.assert_array_equal(np.asarray(ka_v),
                                      np.asarray(ka_s))
        np.testing.assert_array_equal(np.asarray(va_v),
                                      np.asarray(va_s))
        np.testing.assert_array_equal(
            np.asarray(out_v), np.asarray(jnp.concatenate(outs, 1)))

    def test_chunk_attention_unchanged_through_dispatch(self, np_rng):
        """paged_chunk_attention now routes its read through the
        ragged dispatcher — on CPU that must still be the identical
        jnp gather (the engine's golden transcripts depend on it)."""
        ka, va = _arena(np_rng, 9)
        row = jnp.asarray(np_rng.randint(0, 9, (4,)), jnp.int32)
        c = 5
        q = jnp.asarray(np_rng.standard_normal((1, c, 4, DH)),
                        jnp.float32)
        k = jnp.asarray(np_rng.standard_normal((1, c, HKV, DH)),
                        jnp.float32)
        v = jnp.asarray(np_rng.standard_normal((1, c, HKV, DH)),
                        jnp.float32)
        kw = dict(page_size=PAGE, max_len=14)
        out, ka2, va2 = _jit(PA.paged_chunk_attention, **kw)(
            q, k, v, ka, va, row, jnp.int32(3))
        ap = 3 + jnp.arange(c, dtype=jnp.int32)
        pg, off = PA.page_addresses(row, ap, page_size=PAGE)
        ka_ref = PA.write_kv(ka, k[0], pg, off)
        va_ref = PA.write_kv(va, v[0], pg, off)
        k_read = PA.gather_kv(ka_ref, row[None], 14, q.dtype)
        v_read = PA.gather_kv(va_ref, row[None], 14, q.dtype)
        valid = jnp.arange(14, dtype=jnp.int32)[None, :] <= ap[:, None]
        ref = jax.jit(PA.grouped_masked_attention)(
            q, k_read, v_read, valid[None, None])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)
