"""Checkpoint/resume tests (reference test model: per-pass save dirs in
trainer tests + Parameters to_tar/from_tar round trips)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import nn, optim
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.train import (
    CheckpointManager,
    TrainState,
    Trainer,
    export_inference_artifact,
    load_inference_artifact,
    load_parameters_tar,
    save_parameters_tar,
)


def _model():
    return nn.Sequential([nn.Dense(8, name="fc", activation="relu"),
                          nn.Dense(3, name="out")])


def _loss(o, y):
    return jnp.mean(losses.softmax_cross_entropy(o, y))


def _trees_equal(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_checkpoint_roundtrip(tmp_path):
    model = _model()
    tr = Trainer(model, _loss, optim.adam(1e-3))
    state = tr.init_state(ShapeSpec((4, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    mgr.save(state, step=0)
    # train_step donates its input buffers — keep a host copy to compare
    params0 = jax.tree.map(np.asarray, state.params)

    # mutate by training one step, save again
    rng = np.random.RandomState(0)
    batch = (rng.rand(4, 5).astype(np.float32), rng.randint(0, 3, 4))
    state2 = tr.train(state, lambda: iter([batch]), num_passes=1)
    mgr.save(state2)

    assert mgr.latest_step() == int(state2.step)
    template = tr.init_state(ShapeSpec((4, 5)))
    restored = mgr.restore(template)
    _trees_equal(restored.params, state2.params)
    _trees_equal(restored.opt_state, state2.opt_state)
    assert int(restored.step) == int(state2.step)
    # restore an older step explicitly
    restored0 = mgr.restore(template, step=0)
    _trees_equal(restored0.params, params0)
    mgr.close()


def test_checkpoint_retention(tmp_path):
    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((2, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for s in (1, 2, 3):
        mgr.save(state, step=s)
    assert mgr.all_steps() == [2, 3]
    mgr.close()


def test_trainer_periodic_checkpoint(tmp_path):
    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    rng = np.random.RandomState(0)
    batches = [(rng.rand(4, 5).astype(np.float32), rng.randint(0, 3, 4))
               for _ in range(4)]
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=5)
    final = tr.train(state, lambda: iter(batches), num_passes=2,
                     checkpoint_manager=mgr, checkpoint_every_n_batches=2)
    # saves at batches 2,4 each pass (steps 2,4,6,8) + pass ends (4, 8)
    assert mgr.latest_step() == int(final.step) == 8
    assert 2 in mgr.all_steps()
    restored = mgr.restore(tr.init_state(ShapeSpec((4, 5))))
    _trees_equal(restored.params, final.params)
    mgr.close()


def test_parameters_tar_roundtrip(tmp_path):
    model = _model()
    rng = jax.random.key(0)
    params, _ = model.init(rng, ShapeSpec((4, 5)))
    path = str(tmp_path / "params.tar")
    save_parameters_tar(params, path)
    zeros = jax.tree.map(jnp.zeros_like, params)
    loaded = load_parameters_tar(zeros, path)
    _trees_equal(loaded, params)


def test_parameters_tar_shape_mismatch(tmp_path):
    model = _model()
    params, _ = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    path = str(tmp_path / "params.tar")
    save_parameters_tar(params, path)
    other, _ = model.init(jax.random.key(0), ShapeSpec((4, 7)))
    with pytest.raises(ValueError, match="shape"):
        load_parameters_tar(other, path)


def test_inference_artifact_roundtrip(tmp_path):
    model = nn.Sequential([nn.Dense(6, name="fc", activation="relu"),
                           nn.BatchNorm(name="bn"), nn.Dense(2, name="out")])
    params, mstate = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    path = str(tmp_path / "model.tar")
    export_inference_artifact(params, mstate, path, meta={"model": "toy"})
    p2, s2, meta = load_inference_artifact(
        jax.tree.map(jnp.zeros_like, params),
        jax.tree.map(jnp.zeros_like, mstate), path)
    _trees_equal(p2, params)
    _trees_equal(s2, mstate)
    assert meta == {"model": "toy"}
    # restored state must drive inference identically
    x = jnp.asarray(np.random.RandomState(0).rand(4, 5), jnp.float32)
    out_a, _ = model.apply(params, mstate, x, training=False)
    out_b, _ = model.apply(p2, s2, x, training=False)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=1e-6)


def test_checkpoint_restore_sharded_template(tmp_path):
    """Restore onto a sharded template re-shards onto the mesh
    (preemption-aware resume onto a fresh slice)."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import parallel
    from paddle_tpu.core import mesh as mesh_lib

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    model = _model()
    tr = Trainer(model, _loss, optim.adam(1e-3))
    state = tr.init_state(ShapeSpec((8, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(state, step=0)

    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=4, model=2))
    template = parallel.shard_train_state(
        tr.init_state(ShapeSpec((8, 5))), mesh,
        param_rules=[("fc/kernel", P(None, "model"))])
    restored = mgr.restore(template)
    _trees_equal(restored.params, state.params)
    # the restored kernel carries the template's sharding
    kernel = restored.params["fc"]["kernel"]
    assert kernel.sharding.spec == P(None, "model")
    mgr.close()


def test_restore_missing_dir(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    with pytest.raises(FileNotFoundError):
        mgr.restore(tr.init_state(ShapeSpec((2, 5))))
    mgr.close()


# ---- corruption: torn writes must fail loudly or fall back ----------


def test_tar_truncated_is_clear_error(tmp_path):
    """A truncated tar (torn write / partial upload) must produce a
    clear ValueError naming the file, never a garbage restore."""
    model = _model()
    params, _ = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    path = str(tmp_path / "params.tar")
    save_parameters_tar(params, path)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) // 3])     # tear the write
    with pytest.raises(ValueError, match="params.tar"):
        load_parameters_tar(jax.tree.map(jnp.zeros_like, params), path)


def test_tar_missing_manifest_is_clear_error(tmp_path):
    import io
    import tarfile

    path = str(tmp_path / "bogus.tar")
    with tarfile.open(path, "w") as tar:
        info = tarfile.TarInfo(name="param_0.npy")
        info.size = 4
        tar.addfile(info, io.BytesIO(b"\0\0\0\0"))
    model = _model()
    params, _ = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    with pytest.raises(ValueError, match="manifest.json"):
        load_parameters_tar(params, path)


def test_tar_corrupt_manifest_is_clear_error(tmp_path):
    import io
    import tarfile

    path = str(tmp_path / "bad-manifest.tar")
    with tarfile.open(path, "w") as tar:
        blob = b"{not json"
        info = tarfile.TarInfo(name="manifest.json")
        info.size = len(blob)
        tar.addfile(info, io.BytesIO(blob))
    model = _model()
    params, _ = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    with pytest.raises(ValueError, match="manifest"):
        load_parameters_tar(params, path)


def test_tar_manifest_mismatch_is_clear_error(tmp_path):
    """manifest.json from a DIFFERENT model (wrong count / keys) must
    be rejected with the mismatch named."""
    model = _model()
    params, _ = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    other = nn.Sequential([nn.Dense(8, name="zz", activation="relu"),
                           nn.Dense(3, name="out")])
    oparams, _ = other.init(jax.random.key(0), ShapeSpec((4, 5)))
    path = str(tmp_path / "other.tar")
    save_parameters_tar(oparams, path)
    with pytest.raises(ValueError, match="key"):
        load_parameters_tar(params, path)


def test_inference_artifact_truncated_is_clear_error(tmp_path):
    model = _model()
    params, mstate = model.init(jax.random.key(0), ShapeSpec((4, 5)))
    path = str(tmp_path / "model.tar")
    export_inference_artifact(params, mstate, path)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: 100])
    with pytest.raises(ValueError, match="model.tar"):
        load_inference_artifact(params, mstate, path)


@pytest.mark.faults
def test_resilient_restore_falls_back_past_corrupt_step(tmp_path):
    """A half-written/corrupt orbax step (newest) must not poison
    resume: restore_with_fallback walks back to the previous intact
    step — the ResilientTrainer startup path."""
    import os
    import shutil

    from paddle_tpu.train import restore_with_fallback

    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((4, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=5)
    mgr.save(state, step=1)
    # the train step donates its input buffers — keep a host copy
    params1 = jax.tree.map(np.asarray, state.params)
    rng = np.random.RandomState(0)
    batch = (rng.rand(4, 5).astype(np.float32), rng.randint(0, 3, 4))
    state2 = tr.train(state, lambda: iter([batch]), num_passes=1)
    mgr.save(state2, step=9)

    # corrupt the NEWEST committed step: empty every array file under
    # it (the half-written-then-power-cut shape orbax's commit marker
    # cannot catch, because the marker is already there)
    step_dir = os.path.join(str(tmp_path / "ckpt"), "9")
    assert os.path.isdir(step_dir)
    for root, dirs, files in os.walk(step_dir):
        for fn in files:
            if fn.endswith((".json", "metadata")):
                continue
            with open(os.path.join(root, fn), "wb"):
                pass
    template = tr.init_state(ShapeSpec((4, 5)))
    restored, step = restore_with_fallback(mgr, template)
    assert step == 1
    _trees_equal(restored.params, params1)
    mgr.close()


@pytest.mark.faults
def test_resilient_restore_nothing_restorable(tmp_path):
    from paddle_tpu.train import restore_with_fallback

    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    template = tr.init_state(ShapeSpec((2, 5)))
    mgr = CheckpointManager(str(tmp_path / "none"))
    restored, step = restore_with_fallback(mgr, template)
    assert step is None
    assert restored is template
    mgr.close()


def test_async_checkpoint_roundtrip(tmp_path):
    """async_save=True: save() returns before the write is durable;
    wait()/restore() must still hand back exactly what was saved, and
    back-to-back async saves must not corrupt each other (orbax
    serializes them on its background thread)."""
    model = _model()
    tr = Trainer(model, _loss, optim.adam(1e-3))
    state = tr.init_state(ShapeSpec((4, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=3,
                            async_save=True)
    mgr.save(state, step=0)
    params0 = jax.tree.map(np.asarray, state.params)

    rng = np.random.RandomState(1)
    batch = (rng.rand(4, 5).astype(np.float32), rng.randint(0, 3, 4))
    state2 = tr.train(state, lambda: iter([batch]), num_passes=1)
    mgr.save(state2)          # second async save queued immediately
    mgr.wait()

    assert mgr.latest_step() == int(state2.step)
    template = tr.init_state(ShapeSpec((4, 5)))
    restored = mgr.restore(template)
    _trees_equal(restored.params, state2.params)
    restored0 = mgr.restore(template, step=0)
    _trees_equal(restored0.params, params0)
    mgr.close()


def test_async_restore_waits_for_pending_save(tmp_path):
    """restore() right after an un-waited async save must see the step
    (latest_step waits internally) — an async manager can never hand
    back a half-written checkpoint."""
    model = _model()
    tr = Trainer(model, _loss, optim.sgd(0.1))
    state = tr.init_state(ShapeSpec((2, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=True)
    mgr.save(state, step=7)
    template = tr.init_state(ShapeSpec((2, 5)))
    restored = mgr.restore(template)   # no explicit wait()
    _trees_equal(restored.params, state.params)
    mgr.close()


def test_save_onto_existing_step_overwrites(tmp_path):
    """Re-saving an existing step must WRITE, not silently skip:
    orbax's own save-decision policy skips existing steps without an
    error, which would hand back a false durability signal (the drain
    save after a fallback-restore replay depends on the overwrite)."""
    model = _model()
    tr = Trainer(model, _loss, optim.adam(1e-3))
    state = tr.init_state(ShapeSpec((4, 5)))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=3)
    mgr.save(state, step=4)
    bumped = state._replace(
        params=jax.tree.map(lambda x: x + 1.0, state.params))
    mgr.save(bumped, step=4)            # same step, different state
    restored = mgr.restore(state, step=4)
    _trees_equal(restored.params, bumped.params)
    assert mgr.all_steps() == [4]
    mgr.close()
