"""Data pipeline tests (reference: python/paddle/v2/reader/tests/)."""

import numpy as np
import pytest

from paddle_tpu.data import batch as B
from paddle_tpu.data import datasets, reader as R


def counting_reader(n=10):
    def r():
        return iter(range(n))

    return r


class TestReaders:
    def test_map_readers(self):
        r = R.map_readers(lambda a, b: a + b, counting_reader(3), counting_reader(3))
        assert list(r()) == [0, 2, 4]

    def test_shuffle_preserves_items(self):
        r = R.shuffle(counting_reader(20), 5, seed=0)
        assert sorted(r()) == list(range(20))

    def test_chain(self):
        r = R.chain(counting_reader(2), counting_reader(3))
        assert list(r()) == [0, 1, 0, 1, 2]

    def test_compose(self):
        r = R.compose(counting_reader(3), counting_reader(3))
        assert list(r()) == [(0, 0), (1, 1), (2, 2)]

    def test_compose_misaligned_raises(self):
        r = R.compose(counting_reader(2), counting_reader(3))
        with pytest.raises(R.ComposeNotAligned):
            list(r())

    def test_buffered(self):
        r = R.buffered(counting_reader(50), 8)
        assert list(r()) == list(range(50))

    def test_buffered_propagates_error(self):
        def bad():
            yield 1
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            list(R.buffered(lambda: bad(), 2)())

    def test_firstn(self):
        assert list(R.firstn(counting_reader(10), 3)()) == [0, 1, 2]

    def test_xmap_unordered(self):
        r = R.xmap_readers(lambda x: x * 2, counting_reader(20), 4, 8)
        assert sorted(r()) == [2 * i for i in range(20)]

    def test_xmap_ordered(self):
        r = R.xmap_readers(lambda x: x * 2, counting_reader(20), 4, 8, order=True)
        assert list(r()) == [2 * i for i in range(20)]

    def test_cache(self):
        calls = []

        def src():
            calls.append(1)
            return iter(range(3))

        r = R.cache(src)
        assert list(r()) == [0, 1, 2]
        assert list(r()) == [0, 1, 2]
        assert len(calls) == 1


class TestBatch:
    def test_batch_drop_last(self):
        b = B.batch(counting_reader(10), 4)
        batches = list(b())
        assert [len(x) for x in batches] == [4, 4]

    def test_batch_keep_last(self):
        b = B.batch(counting_reader(10), 4, drop_last=False)
        assert [len(x) for x in b()] == [4, 4, 2]

    def test_stack_columns(self):
        samples = [(np.zeros((2,)), 1), (np.ones((2,)), 0)]
        x, y = B.stack_columns(samples)
        assert x.shape == (2, 2) and y.shape == (2,)

    def test_pack_sequences(self):
        seqs = [np.arange(3), np.arange(5), np.arange(2)]
        sb = B.pack_sequences(seqs, capacity=16, max_seqs=4)
        assert sb.tokens.shape == (16,)
        assert sb.num_seqs == 3
        np.testing.assert_array_equal(sb.lengths, [3, 5, 2, 0])
        np.testing.assert_array_equal(sb.segment_ids[:3], [0, 0, 0])
        np.testing.assert_array_equal(sb.segment_ids[3:8], [1] * 5)
        np.testing.assert_array_equal(sb.positions[3:8], np.arange(5))
        assert sb.mask[:10].all() and not sb.mask[10:].any()

    def test_pack_overflow_raises(self):
        with pytest.raises(ValueError):
            B.pack_sequences([np.arange(10)], capacity=8)

    def test_pad_sequences(self):
        x, lens = B.pad_sequences([np.arange(3), np.arange(1)])
        assert x.shape == (2, 3)
        np.testing.assert_array_equal(lens, [3, 1])
        np.testing.assert_array_equal(x[1], [0, 0, 0])

    def test_bucket_by_length(self):
        data = [np.zeros(n) for n in [2, 9, 3, 8, 2, 9]]
        r = B.bucket_by_length(lambda: iter(data), 2, [4])
        batches = list(r())
        for b in batches:
            lens = [len(s) for s in b]
            assert all(l <= 4 for l in lens) or all(l > 4 for l in lens)


class TestDatasets:
    def test_mnist_schema(self):
        it = datasets.mnist("train", synthetic_n=8)()
        img, lbl = next(it)
        assert img.shape == (28, 28, 1)
        assert img.dtype == np.float32
        assert 0 <= int(lbl) < 10

    def test_text_classification_schema(self):
        it = datasets.synthetic_text_classification(n=5)()
        tokens, label = next(it)
        assert tokens.ndim == 1 and tokens.dtype == np.int32

    def test_tagging_schema(self):
        it = datasets.synthetic_tagging(n=3)()
        tokens, tags = next(it)
        assert tokens.shape == tags.shape

    def test_translation_schema(self):
        it = datasets.synthetic_translation(n=3)()
        src, tgt = next(it)
        assert len(src) == len(tgt)

    def test_ctr_schema(self):
        it = datasets.synthetic_ctr(n=3)()
        ids, dense, click = next(it)
        assert ids.shape == (3,) and dense.shape == (8,)
