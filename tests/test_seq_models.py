"""Sequence-model convergence and generation tests — the 'book tests'
for the sequence stack (reference: trainer/tests/
test_recurrent_machine_generation.cpp golden decode,
v1_api_demo/sequence_tagging convergence)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optim
from paddle_tpu.data import batch as B, datasets
from paddle_tpu.models import bilstm_crf, seq2seq_attn, text_lstm
from paddle_tpu.ops import beam_search as bs


def _padded_batches(reader, batch_size, max_len):
    out = []
    buf = []
    for tokens, label in reader():
        buf.append((tokens[:max_len], label))
        if len(buf) == batch_size:
            toks, lens = B.pad_sequences([t for t, _ in buf], max_len)
            labels = np.asarray([l for _, l in buf])
            out.append((toks, lens, labels))
            buf = []
    return out


class TestTextLSTM:
    def test_converges(self):
        vocab, classes = 120, 2
        params = text_lstm.init_params(
            jax.random.key(0), vocab, classes, embed_dim=16, hidden=24,
            num_layers=1,
        )
        batches = _padded_batches(
            datasets.synthetic_text_classification(
                vocab_size=vocab, num_classes=classes, n=128, max_len=20
            ),
            16, 20,
        )
        opt = optim.adam(5e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, toks, lens, labels, i):
            def loss_fn(p):
                logits = text_lstm.apply(p, toks, lens, num_layers=1)
                from paddle_tpu.ops import losses

                return jnp.mean(losses.softmax_cross_entropy(logits, labels))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, opt_state = opt.update(grads, opt_state, params, i)
            return params, opt_state, loss

        first = last = None
        i = 0
        for epoch in range(6):
            for toks, lens, labels in batches:
                params, opt_state, loss = step(
                    params, opt_state, jnp.asarray(toks), jnp.asarray(lens),
                    jnp.asarray(labels), jnp.asarray(i),
                )
                if first is None:
                    first = float(loss)
                last = float(loss)
                i += 1
        assert last < first * 0.6, (first, last)


class TestBiLSTMCRF:
    def test_converges_and_decodes(self):
        vocab, tags = 50, 4
        params = bilstm_crf.init_params(
            jax.random.key(0), vocab, tags, embed_dim=16, hidden=16
        )
        data = []
        for tokens, tg in datasets.synthetic_tagging(
            vocab_size=vocab, num_tags=tags, n=64, max_len=12
        )():
            data.append((tokens, tg))
        toks, lens = B.pad_sequences([t for t, _ in data], 12)
        tag_arr, _ = B.pad_sequences([t for _, t in data], 12)
        toks, lens, tag_arr = map(jnp.asarray, (toks, lens, tag_arr))

        opt = optim.adam(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, i):
            loss, grads = jax.value_and_grad(bilstm_crf.loss)(
                params, toks, tag_arr, lens
            )
            params, opt_state = opt.update(grads, opt_state, params, i)
            return params, opt_state, loss

        losses = []
        for i in range(60):
            params, opt_state, loss = step(params, opt_state, jnp.asarray(i))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        decoded, score = jax.jit(bilstm_crf.decode)(params, toks, lens)
        acc = 0.0
        total = 0
        d = np.asarray(decoded)
        tg = np.asarray(tag_arr)
        for i, n in enumerate(np.asarray(lens)):
            acc += (d[i, :n] == tg[i, :n]).sum()
            total += n
        assert acc / total > 0.8, acc / total


class TestBeamSearch:
    def test_beam_finds_higher_score_than_greedy(self):
        """Beam-1 == greedy; beam-4 score >= beam-1 score on a toy LM."""
        vocab = 8

        # fixed "language model": logits depend only on previous token
        table = jax.random.normal(jax.random.key(3), (vocab, vocab))

        def step_fn(tokens, state):
            return table[tokens], state

        tokens1, scores1, _ = bs.beam_search(
            {"dummy": jnp.zeros((2, 1))}, step_fn, batch_size=2, beam_size=1,
            max_len=5, bos_id=1, eos_id=0, vocab_size=vocab,
        )
        tokens4, scores4, _ = bs.beam_search(
            {"dummy": jnp.zeros((2, 1))}, step_fn, batch_size=2, beam_size=4,
            max_len=5, bos_id=1, eos_id=0, vocab_size=vocab,
        )
        assert float(scores4[0, 0]) >= float(scores1[0, 0]) - 1e-5

        greedy_toks, _ = bs.greedy_search(
            {"dummy": jnp.zeros((2, 1))}, step_fn, batch_size=2, max_len=5,
            bos_id=1, eos_id=0,
        )
        # note: greedy path == beam-1 path
        np.testing.assert_array_equal(
            np.asarray(tokens1[:, 0]), np.asarray(greedy_toks)
        )

    def test_eos_terminates_and_pads(self):
        vocab = 5

        def step_fn(tokens, state):
            # always strongly prefer EOS (id 0)
            logits = jnp.full((tokens.shape[0], vocab), -10.0).at[:, 0].set(10.0)
            return logits, state

        tokens, scores, lengths = bs.beam_search(
            {"d": jnp.zeros((1, 1))}, step_fn, batch_size=1, beam_size=3,
            max_len=6, bos_id=1, eos_id=0, vocab_size=vocab,
        )
        assert int(lengths[0, 0]) == 1  # just the eos
        assert np.all(np.asarray(tokens)[0, 0] == 0)

    def test_modify_logits_hook(self):
        """The user-callback equivalent: force token 3 at step 0."""
        vocab = 6
        table = jax.random.normal(jax.random.key(0), (vocab, vocab))

        def step_fn(tokens, state):
            return table[tokens], state

        def force3(step, logits, state):
            forced = jnp.full_like(logits, -1e9).at[:, 3].set(0.0)
            return jnp.where(step == 0, forced, logits)

        tokens, _, _ = bs.beam_search(
            {"d": jnp.zeros((1, 1))}, step_fn, batch_size=1, beam_size=2,
            max_len=4, bos_id=1, eos_id=0, vocab_size=vocab,
            modify_logits_fn=force3,
        )
        assert int(np.asarray(tokens)[0, 0, 0]) == 3


class TestSeq2Seq:
    def test_loss_decreases_and_generates(self):
        src_v = tgt_v = 30
        params = seq2seq_attn.init_params(
            jax.random.key(0), src_v, tgt_v, embed_dim=16, hidden=16
        )
        pairs = list(
            datasets.synthetic_translation(
                src_vocab=src_v, tgt_vocab=tgt_v, n=64, min_len=3, max_len=8
            )()
        )
        src, src_lens = B.pad_sequences([s for s, _ in pairs], 8)
        tgt, tgt_lens = B.pad_sequences([t for _, t in pairs], 8)
        src, src_lens, tgt, tgt_lens = map(jnp.asarray, (src, src_lens, tgt, tgt_lens))

        opt = optim.adam(5e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, i):
            loss, grads = jax.value_and_grad(seq2seq_attn.loss)(
                params, src, src_lens, tgt, tgt_lens
            )
            params, opt_state = opt.update(grads, opt_state, params, i)
            return params, opt_state, loss

        losses = []
        for i in range(120):
            params, opt_state, l = step(params, opt_state, jnp.asarray(i))
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

        toks, scores, lens = jax.jit(
            lambda p, s, sl: seq2seq_attn.generate(p, s, sl, beam_size=3, max_len=10)
        )(params, src[:4], src_lens[:4])
        assert toks.shape == (4, 3, 10)
        # scores sorted best-first
        s = np.asarray(scores)
        assert np.all(np.diff(s, axis=1) <= 1e-5)

        gt, gl = jax.jit(
            lambda p, s, sl: seq2seq_attn.greedy_generate(p, s, sl, max_len=10)
        )(params, src[:4], src_lens[:4])
        assert gt.shape == (4, 10)


# ---- quick_start family (reference: v1_api_demo/quick_start configs) --


def _toy_text(n=256, vocab=200, t=12, seed=0):
    """Separable synthetic task: class = whether tokens from the upper
    half of the vocab dominate."""
    r = np.random.RandomState(seed)
    lengths = r.randint(4, t + 1, n)
    tokens = r.randint(0, vocab, (n, t))
    labels = np.zeros(n, np.int64)
    for i in range(n):
        lo = (tokens[i, :lengths[i]] < vocab // 2).sum()
        labels[i] = int(lo * 2 < lengths[i])
        tokens[i, lengths[i]:] = 0
    return (jnp.asarray(tokens, jnp.int32), jnp.asarray(lengths),
            jnp.asarray(labels))


def _train_text_model(init_fn, apply_fn, *, steps=60, lr=5e-2, seed=0):
    from paddle_tpu import optim
    from paddle_tpu.ops import losses

    vocab = 200
    tokens, lengths, labels = _toy_text(vocab=vocab, seed=seed)
    params = init_fn(jax.random.key(0), vocab)
    opt = optim.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, i):
        def loss_fn(p):
            logits = apply_fn(p, tokens, lengths)
            return jnp.mean(losses.softmax_cross_entropy(logits, labels))
        loss, g = jax.value_and_grad(loss_fn)(params)
        new_p, new_o = opt.update(g, opt_state, params, i)
        return new_p, new_o, loss

    first = None
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(i, jnp.int32))
        if first is None:
            first = float(loss)
    logits = apply_fn(params, tokens, lengths)
    from paddle_tpu.ops import metrics as M
    acc = float(M.accuracy(logits, labels))
    return first, float(loss), acc


def test_quick_start_bow_lr_learns():
    from paddle_tpu.models import quick_start as qs

    first, last, acc = _train_text_model(
        qs.init_bow_lr, qs.bow_lr_from_tokens)
    assert last < first * 0.6 and acc > 0.9, (first, last, acc)


def test_quick_start_bow_dense_equals_token_path():
    from paddle_tpu.models import quick_start as qs

    vocab = 50
    tokens, lengths, _ = _toy_text(n=8, vocab=vocab, t=6, seed=1)
    params = qs.init_bow_lr(jax.random.key(0), vocab)
    # build the dense count vector and compare the two input forms
    counts = np.zeros((8, vocab), np.float32)
    for i in range(8):
        for tkn in np.asarray(tokens[i, : int(lengths[i])]):
            counts[i, tkn] += 1
    dense = qs.bow_lr(params, jnp.asarray(counts))
    sparse = qs.bow_lr_from_tokens(params, tokens, lengths)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(sparse),
                               rtol=1e-5, atol=1e-5)


def test_quick_start_text_cnn_learns():
    from paddle_tpu.models import quick_start as qs

    first, last, acc = _train_text_model(
        lambda rng, v: qs.init_text_cnn(rng, v, embed_dim=16, hidden=32),
        qs.text_cnn, steps=80)
    assert last < first * 0.6 and acc > 0.9, (first, last, acc)


def test_quick_start_bidi_lstm_shapes_and_grad():
    from paddle_tpu.models import quick_start as qs

    vocab = 60
    tokens, lengths, labels = _toy_text(n=8, vocab=vocab, t=6, seed=2)
    params = qs.init_bidi_lstm(jax.random.key(0), vocab, embed_dim=8,
                               hidden=12)
    logits = qs.bidi_lstm(params, tokens, lengths)
    assert logits.shape == (8, 2)
    g = jax.grad(lambda p: jnp.sum(qs.bidi_lstm(p, tokens, lengths) ** 2))(
        params)
    assert float(jnp.abs(g["fwd"]["w_ih"]).sum()) > 0
    assert float(jnp.abs(g["bwd"]["w_ih"]).sum()) > 0


def test_quick_start_db_lstm_depth_and_direction():
    from paddle_tpu.models import quick_start as qs

    vocab, depth = 40, 3
    tokens, lengths, _ = _toy_text(n=4, vocab=vocab, t=5, seed=3)
    params = qs.init_db_lstm(jax.random.key(0), vocab, embed_dim=8,
                             hidden=10, depth=depth)
    logits = qs.db_lstm(params, tokens, lengths)
    assert logits.shape == (4, 2)
    # every level's parameters participate
    g = jax.grad(lambda p: jnp.sum(
        qs.db_lstm(p, tokens, lengths) ** 2))(params)
    for i in range(depth):
        assert float(jnp.abs(g[f"lstm{i}"]["w_hh"]).sum()) > 0, i


def test_generation_matches_golden_file():
    """Golden-output generation test (reference strategy:
    trainer/tests/test_recurrent_machine_generation.cpp compares decode
    output against checked-in golden files in rnn_gen_test_model_dir).
    Seeded params + fixed source batch -> beam and greedy decodes must
    reproduce tests/golden/seq2seq_gen_golden.json exactly (token ids
    and lengths bit-exact; scores to 1e-4). Regenerate the golden ONLY
    for intentional decode-semantics changes."""
    import json
    import os

    from paddle_tpu.models import seq2seq_attn

    path = os.path.join(os.path.dirname(__file__), "golden",
                        "seq2seq_gen_golden.json")
    with open(path) as f:
        golden = json.load(f)

    params = seq2seq_attn.init_params(jax.random.key(7), 40, 40,
                                      embed_dim=12, hidden=16)
    r = np.random.RandomState(3)
    src = jnp.asarray(r.randint(2, 40, (3, 6)), jnp.int32)
    lens = jnp.asarray([6, 4, 5])
    toks, scores, lengths = seq2seq_attn.generate(
        params, src, lens, beam_size=3, max_len=8)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(golden["beam_tokens"]))
    np.testing.assert_array_equal(np.asarray(lengths),
                                  np.asarray(golden["beam_lengths"]))
    np.testing.assert_allclose(np.asarray(scores),
                               np.asarray(golden["beam_scores"]),
                               rtol=1e-4, atol=1e-4)
    g = seq2seq_attn.greedy_generate(params, src, lens, max_len=8)
    got = [np.asarray(x).tolist() for x in (g if isinstance(g, tuple)
                                            else (g,))]
    assert got == golden["greedy"]


class TestSeq2SeqFusedCE:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    def test_fused_ce_matches_plain(self):
        """fused_ce_chunk folds the 30k-vocab decoder head into a
        checkpointed chunked scan; values and grads must match the
        plain materialized-logits loss exactly (same ops, chunked
        lhs + bias)."""
        params = seq2seq_attn.init_params(
            jax.random.key(3), src_vocab=50, tgt_vocab=70,
            embed_dim=16, hidden=24)
        r = np.random.RandomState(3)
        src = jnp.asarray(r.randint(0, 50, (3, 7)), jnp.int32)
        slen = jnp.asarray([7, 5, 3])
        tgt = jnp.asarray(r.randint(0, 70, (3, 9)), jnp.int32)
        tlen = jnp.asarray([9, 6, 2])
        a = seq2seq_attn.loss(params, src, slen, tgt, tlen)
        b = seq2seq_attn.loss(params, src, slen, tgt, tlen,
                              fused_ce_chunk=5)
        np.testing.assert_allclose(float(a), float(b), rtol=1e-6)
        ga = jax.grad(lambda p: seq2seq_attn.loss(
            p, src, slen, tgt, tlen))(params)
        gb = jax.grad(lambda p: seq2seq_attn.loss(
            p, src, slen, tgt, tlen, fused_ce_chunk=5))(params)
        for la, lb in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       atol=1e-6)
