"""CLI driver (reference: `paddle train|merge_model|dump_config|version`,
scripts/submit_local.sh.in:3-14). Runs in-process via cli.main."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.cli import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = """
import jax.numpy as jnp
import numpy as np
from paddle_tpu import nn, optim
from paddle_tpu.ops import losses


def _reader():
    rng = np.random.RandomState(0)
    for _ in range(128):
        x = rng.rand(16).astype(np.float32)
        yield x, int(x.sum() > 8)


def get_config():
    return {
        "name": "toy_mlp",
        "model": nn.Sequential(
            [nn.Dense(32, name="fc1", activation="relu"),
             nn.Dense(2, name="logits")]),
        "input_spec": (32, 16),
        "optimizer": optim.adam(1e-2),
        "loss_fn": lambda lo, la: jnp.mean(
            losses.softmax_cross_entropy(lo, la)),
        "reader": _reader,
        "num_passes": 2,
    }
"""


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "config.py"
    p.write_text(CONFIG)
    return str(p)


def test_version(capsys):
    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "paddle_tpu" in out and "jax" in out


def test_dump_config(config_file, capsys):
    assert main(["dump-config", "--config", config_file]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["input_shape"] == [32, 16]
    # fc1: 16*32 + 32; logits: 32*2 + 2
    assert d["num_parameters"] == 16 * 32 + 32 + 32 * 2 + 2
    assert any("kernel" in k for k in d["parameters"])


def test_train_save_merge_infer(config_file, tmp_path, capsys):
    save_dir = str(tmp_path / "out")
    assert main(["train", "--config", config_file, "--batch-size", "32",
                 "--save-dir", save_dir]) == 0
    out = capsys.readouterr().out
    assert "pass 0 batch 0" in out
    params_tar = os.path.join(save_dir, "params.tar")
    assert os.path.exists(params_tar)

    artifact = str(tmp_path / "model.ptc")
    assert main(["merge-model", "--config", config_file,
                 "--params", params_tar, "--output", artifact]) == 0
    capsys.readouterr()

    x = np.random.RandomState(0).rand(32, 16).astype(np.float32)
    xnpy = str(tmp_path / "x.npy")
    np.save(xnpy, x)
    prefix = str(tmp_path / "y")
    assert main(["infer", "--artifact", artifact,
                 "--output-prefix", prefix, xnpy]) == 0
    y = np.load(prefix + "0.npy")
    assert y.shape == (32, 2)
    assert np.isfinite(y).all()


def test_cli_subprocess_entry():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", "version"],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "paddle_tpu" in r.stdout


def test_launch_dry_run(capsys):
    from paddle_tpu.cli import main

    rc = main(["launch", "--hosts", "hostA,hostB", "--dry-run",
               "--workdir", "/tmp/w", "--",
               "train", "--config", "cfg.py"])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [l for l in out.strip().splitlines() if l]
    assert len(lines) == 2
    assert "hostA" in lines[0] and "hostB" in lines[1]
    assert "--coordinator hostA:1234" in lines[0].replace("'", "")
    assert "--process-id 0" in lines[0].replace("'", "")
    assert "--process-id 1" in lines[1].replace("'", "")
    assert "--num-processes 2" in lines[1].replace("'", "")
    assert "cd /tmp/w" in lines[0]


def test_launch_emit_jobset(capsys):
    from paddle_tpu.cli import main

    rc = main(["launch", "--emit-jobset", "myjob", "--image", "img:1",
               "--num-hosts", "4", "--tpu-topology", "4x4", "--",
               "train", "--config", "cfg.py"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "kind: JobSet" in out
    assert "name: myjob" in out
    assert "parallelism: 4" in out
    assert '"train", "--config", "cfg.py"' in out
    import yaml

    doc = yaml.safe_load(out)
    assert doc["spec"]["replicatedJobs"][0]["template"]["spec"][
        "parallelism"] == 4


def test_launch_requires_command():
    from paddle_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["launch", "--hosts", "a,b"])


def test_make_diagram(config_file, tmp_path, capsys):
    rc = main(["make-diagram", "--config", config_file])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph")
    dot_file = str(tmp_path / "m.dot")
    rc = main(["make-diagram", "--config", config_file,
               "--output", dot_file])
    assert rc == 0
    assert open(dot_file).read().startswith("digraph")
