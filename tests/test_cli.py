"""CLI driver (reference: `paddle train|merge_model|dump_config|version`,
scripts/submit_local.sh.in:3-14). Runs in-process via cli.main."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.cli import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = """
import jax.numpy as jnp
import numpy as np
from paddle_tpu import nn, optim
from paddle_tpu.ops import losses


def _reader():
    rng = np.random.RandomState(0)
    for _ in range(128):
        x = rng.rand(16).astype(np.float32)
        yield x, int(x.sum() > 8)


def get_config():
    return {
        "name": "toy_mlp",
        "model": nn.Sequential(
            [nn.Dense(32, name="fc1", activation="relu"),
             nn.Dense(2, name="logits")]),
        "input_spec": (32, 16),
        "optimizer": optim.adam(1e-2),
        "loss_fn": lambda lo, la: jnp.mean(
            losses.softmax_cross_entropy(lo, la)),
        "reader": _reader,
        "num_passes": 2,
    }
"""


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "config.py"
    p.write_text(CONFIG)
    return str(p)


def test_version(capsys):
    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "paddle_tpu" in out and "jax" in out


def test_dump_config(config_file, capsys):
    assert main(["dump-config", "--config", config_file]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["input_shape"] == [32, 16]
    # fc1: 16*32 + 32; logits: 32*2 + 2
    assert d["num_parameters"] == 16 * 32 + 32 + 32 * 2 + 2
    assert any("kernel" in k for k in d["parameters"])


def test_train_save_merge_infer(config_file, tmp_path, capsys):
    save_dir = str(tmp_path / "out")
    assert main(["train", "--config", config_file, "--batch-size", "32",
                 "--save-dir", save_dir]) == 0
    out = capsys.readouterr().out
    assert "pass 0 batch 0" in out
    params_tar = os.path.join(save_dir, "params.tar")
    assert os.path.exists(params_tar)

    artifact = str(tmp_path / "model.ptc")
    assert main(["merge-model", "--config", config_file,
                 "--params", params_tar, "--output", artifact]) == 0
    capsys.readouterr()

    x = np.random.RandomState(0).rand(32, 16).astype(np.float32)
    xnpy = str(tmp_path / "x.npy")
    np.save(xnpy, x)
    prefix = str(tmp_path / "y")
    assert main(["infer", "--artifact", artifact,
                 "--output-prefix", prefix, xnpy]) == 0
    y = np.load(prefix + "0.npy")
    assert y.shape == (32, 2)
    assert np.isfinite(y).all()


@pytest.mark.elastic
def test_train_zero_resilient_resume(config_file, tmp_path, capsys):
    """`train --zero --checkpoint-dir`: the ZeRO-layout state rides the
    resilient path (ElasticCheckpointManager + the zero step_builder),
    and a second invocation resumes past the finished pass instead of
    retraining it."""
    ck = str(tmp_path / "ck")
    base = ["train", "--config", config_file, "--batch-size", "32",
            "--zero", "--checkpoint-dir", ck, "--checkpoint-every", "2"]
    assert main(base + ["--num-passes", "1"]) == 0
    out = capsys.readouterr().out
    assert "pass 0 batch 0" in out
    assert main(base + ["--num-passes", "2"]) == 0
    out = capsys.readouterr().out
    # pass 0 was restored from the checkpoint, not re-run
    assert "pass 1 batch 0" in out
    assert "pass 0 batch 0" not in out


@pytest.mark.elastic
def test_gang_job_from_config_builder(config_file):
    """The `--elastic` builder every (re)formed gang member calls: a
    config script becomes the parallel.launch job contract, and the
    batch sequence is deterministic across rebuilds — the property the
    exactly-once resume accounting rests on."""
    from paddle_tpu.cli import _gang_job_from_config

    job = _gang_job_from_config(config=config_file, batch_size=32)
    assert set(job) >= {"model", "loss_fn", "optimizer",
                        "input_specs", "batches"}
    # the config's reader yields 128 samples -> 4 full batches; asking
    # for 5 must cycle the reader, not starve
    bs = job["batches"](5)
    assert len(bs) == 5
    x, y = bs[0]
    assert x.shape == (32, 16) and y.shape == (32,)
    job2 = _gang_job_from_config(config=config_file, batch_size=32)
    bs2 = job2["batches"](5)
    np.testing.assert_array_equal(bs[4][0], bs2[4][0])
    np.testing.assert_array_equal(bs[4][1], bs2[4][1])


def test_cli_subprocess_entry():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    r = subprocess.run([sys.executable, "-m", "paddle_tpu", "version"],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "paddle_tpu" in r.stdout


def test_launch_dry_run(capsys):
    from paddle_tpu.cli import main

    rc = main(["launch", "--hosts", "hostA,hostB", "--dry-run",
               "--workdir", "/tmp/w", "--",
               "train", "--config", "cfg.py"])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [l for l in out.strip().splitlines() if l]
    assert len(lines) == 2
    assert "hostA" in lines[0] and "hostB" in lines[1]
    assert "--coordinator hostA:1234" in lines[0].replace("'", "")
    assert "--process-id 0" in lines[0].replace("'", "")
    assert "--process-id 1" in lines[1].replace("'", "")
    assert "--num-processes 2" in lines[1].replace("'", "")
    assert "cd /tmp/w" in lines[0]


def test_launch_emit_jobset(capsys):
    from paddle_tpu.cli import main

    rc = main(["launch", "--emit-jobset", "myjob", "--image", "img:1",
               "--num-hosts", "4", "--tpu-topology", "4x4", "--",
               "train", "--config", "cfg.py"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "kind: JobSet" in out
    assert "name: myjob" in out
    assert "parallelism: 4" in out
    assert '"train", "--config", "cfg.py"' in out
    import yaml

    doc = yaml.safe_load(out)
    assert doc["spec"]["replicatedJobs"][0]["template"]["spec"][
        "parallelism"] == 4


def test_launch_requires_command():
    from paddle_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["launch", "--hosts", "a,b"])


def test_make_diagram(config_file, tmp_path, capsys):
    rc = main(["make-diagram", "--config", config_file])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph")
    dot_file = str(tmp_path / "m.dot")
    rc = main(["make-diagram", "--config", config_file,
               "--output", dot_file])
    assert rc == 0
    assert open(dot_file).read().startswith("digraph")


def test_export_native_and_serve(config_file, tmp_path, capsys):
    """export-native writes a .ptni the Python-free engine loads and
    whose output matches the jax forward."""
    import ctypes

    import jax
    import jax.numpy as jnp

    out = str(tmp_path / "toy.ptni")
    assert main(["export-native", "--config", config_file,
                 "--output", out]) == 0
    assert os.path.exists(out)

    from paddle_tpu.native import build

    lib = ctypes.CDLL(build.ensure_infer_built())
    lib.ptn_load.restype = ctypes.c_void_p
    lib.ptn_load.argtypes = [ctypes.c_char_p]
    lib.ptn_output_dim.restype = ctypes.c_longlong
    lib.ptn_output_dim.argtypes = [ctypes.c_void_p]
    lib.ptn_forward.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.c_longlong, ctypes.POINTER(ctypes.c_float)]
    m = lib.ptn_load(out.encode())
    assert m
    x = np.random.RandomState(0).rand(4, 16).astype(np.float32)
    got = np.zeros((4, 2), np.float32)
    assert lib.ptn_forward(
        m, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 4,
        got.ctypes.data_as(ctypes.POINTER(ctypes.c_float))) == 0
    lib.ptn_free(ctypes.c_void_p(m))

    # same weights (seed 0 init, no --params) through the jax forward
    import importlib.util

    spec = importlib.util.spec_from_file_location("cfg", config_file)
    cfg_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cfg_mod)
    cfg = cfg_mod.get_config()
    from paddle_tpu.nn.module import ShapeSpec

    model = cfg["model"]
    params, mstate = model.init(jax.random.key(0), ShapeSpec((4, 16)))
    want, _ = model.apply(params, mstate, jnp.asarray(x), training=False)
    np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
def test_serve_verb(tmp_path, capsys):
    """`paddle_tpu serve`: config script -> engine pool -> id-in/id-out
    completions matching generate() (greedy default)."""
    cfg_src = """
import jax
jax.config.update("jax_platforms", "cpu")


def get_serve_config():
    from paddle_tpu.models import transformer as T
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense")
    return {"cfg": cfg,
            "params": T.init_params(jax.random.key(0), cfg),
            "slots": 2, "max_len": 24}
"""
    cfg_file = tmp_path / "serve_cfg.py"
    cfg_file.write_text(cfg_src)
    prompts = tmp_path / "prompts.txt"
    prompts.write_text("1 2 3 4 5\n7 8 9\n")
    out = tmp_path / "out.txt"
    assert main(["serve", "--config", str(cfg_file),
                 "--prompts", str(prompts), "--max-new", "6",
                 "--logprobs", "--output", str(out)]) == 0
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 4  # 2 completions + 2 logprob comments
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.models import transformer as T
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense")
    params = T.init_params(jax.random.key(0), cfg)
    for line, p in zip(lines[::2], ([1, 2, 3, 4, 5], [7, 8, 9])):
        got = [int(t) for t in line.split()]
        ref = T.generate(params, cfg,
                         jnp.asarray(p, jnp.int32)[None, :], steps=6)
        assert got == [int(t) for t in np.asarray(ref[0, len(p):])]
    assert lines[1].startswith("# logprobs ")

    # --transfer-guard: the same run under jax.transfer_guard
    # ("disallow") — the decode loop must not implicitly re-stage
    # anything (docs/ANALYSIS.md), and the output must be identical
    out2 = tmp_path / "out_guarded.txt"
    assert main(["serve", "--config", str(cfg_file),
                 "--prompts", str(prompts), "--max-new", "6",
                 "--transfer-guard", "--output", str(out2)]) == 0
    assert out2.read_text().strip().splitlines() == lines[::2]


SERVE_CFG = """
import jax
jax.config.update("jax_platforms", "cpu")


def get_serve_config():
    from paddle_tpu.models import transformer as T
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense")
    return {"cfg": cfg,
            "params": T.init_params(jax.random.key(0), cfg),
            "slots": 2, "max_len": 24}
"""


def _wait_addr(addr_file, alive, timeout_s=120.0):
    import time

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(addr_file):
            host, port = open(addr_file).read().split()
            return host, int(port)
        assert alive(), "serve --http exited before binding"
        time.sleep(0.1)
    raise AssertionError("serve --http never published its address")


@pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
@pytest.mark.edge
def test_serve_http_verb(tmp_path):
    """`serve --http 0`: the network mode — main() drives the edge
    while a raw-socket client streams completions matching the solo
    greedy decode; --http-max-requests drains the run to rc 0."""
    import threading

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as T
    from paddle_tpu.testing.traffic import stream_generate

    cfg_file = tmp_path / "serve_cfg.py"
    cfg_file.write_text(SERVE_CFG)
    addr_file = tmp_path / "addr.txt"
    rc = {}

    def run():
        rc["v"] = main(["serve", "--config", str(cfg_file),
                        "--http", "0",
                        "--http-addr-file", str(addr_file),
                        "--http-max-requests", "2",
                        "--max-queue", "8", "--buckets", "16"])

    t = threading.Thread(target=run, daemon=True)
    t.start()
    addr = _wait_addr(str(addr_file), t.is_alive)
    cfg = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                              attn_impl="dense")
    params = T.init_params(jax.random.key(0), cfg)
    for prompt in ([1, 2, 3, 4, 5], [7, 8, 9]):
        r = stream_generate(addr, prompt, 6)
        assert r.status == 200 and r.outcome == "completed"
        ref = T.generate(params, cfg,
                         jnp.asarray(prompt, jnp.int32)[None, :],
                         steps=6)
        assert r.tokens == [int(x) for x in
                            np.asarray(ref[0, len(prompt):])]
    t.join(timeout=60.0)
    assert rc.get("v") == 0


@pytest.mark.slow  # real process boot + SIGTERM, slow lane
@pytest.mark.edge
def test_serve_http_sigterm_drains_fleet(tmp_path):
    """The SIGTERM sequence on a real process, composed with
    --replicas: edge drain (newcomers shed 503) -> fleet drain ->
    the drain report and metrics snapshot land, exit code 0."""
    import signal
    import time

    from paddle_tpu.testing.traffic import stream_generate

    cfg_file = tmp_path / "serve_cfg.py"
    cfg_file.write_text(SERVE_CFG)
    addr_file = tmp_path / "addr.txt"
    report = tmp_path / "drain.json"
    metrics = tmp_path / "metrics.prom"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.cli", "serve",
         "--config", str(cfg_file), "--http", "0",
         "--http-addr-file", str(addr_file), "--replicas", "2",
         "--max-queue", "8", "--buckets", "16",
         "--drain-report", str(report),
         "--metrics-out", str(metrics)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        addr = _wait_addr(str(addr_file),
                          lambda: proc.poll() is None)
        r = stream_generate(addr, [1, 2, 3], 4)
        assert r.status == 200 and r.outcome == "completed"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60.0)
    finally:
        if proc.poll() is None:
            proc.kill()
            out, _ = proc.communicate(timeout=10.0)
    assert proc.returncode == 0, out
    payload = json.loads(report.read_text())
    assert payload["kind"] == "edge_drain_report"
    assert payload["reason"].startswith("signal")
    assert payload["edge"]["requests"] == 1
    assert payload["fleet"]["completed"] >= 1
    assert "edge_requests" in metrics.read_text()
