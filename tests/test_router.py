"""Multi-replica serving fleet: the router chaos harness.

`serve.ServingRouter` fronts N `ServingServer` replicas with
prefix-affinity routing (the paged pool's chained block keys ARE the
routing key), circuit-breaker health checks, and replica-loss
redistribution. The headline claim, proven here the same way every
reliability layer in this repo is proven (deterministic
`testing.faults` injection, `ManualClock`, no sleeps): kill a replica
mid-burst under mixed traffic and EVERY router-submitted request
still ends in exactly one outcome (never lost with the device, never
served twice), the fleet counters reconcile, completed requests match
their solo `generate()` decode bit-exactly, and the aggregate
prefix-hit rate recovers after the dead cache's traffic redistributes
onto (initially cold) survivors.
"""

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.paged import chain_keys
from paddle_tpu.serve.policy import RandomRoutingPolicy
from paddle_tpu.serve.router import (QueueFullError, ServingRouter)
from paddle_tpu.serve.server import ServingServer
from paddle_tpu.testing.faults import (FaultPlan, ManualClock,
                                       garbage_prompts)

pytestmark = [pytest.mark.faults, pytest.mark.router]

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


# ONE module-scoped engine set shared by every fleet in this file:
# engines are stateless between runs (init_state resets the device
# pool) and their jitted compiles dominate test cost. Fleets differ
# only in the wrappers (fault proxies) and servers around them.
@pytest.fixture(scope="module")
def engines(params):
    engs = [DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4)
            for _ in range(3)]
    # pre-warm each replica's compiles (prefill at the two prompt
    # shapes the fleets use — bare len-11 and the chaos test's (16,)
    # bucket — plus the decode step) so no single test's call phase
    # pays 3x first-compile and trips the tier-1 budget guard
    warm = np.arange(11, dtype=np.int32)
    for e in engs:
        e.serve([warm], max_new=2)
        e.serve([warm], max_new=2, buckets=(16,))
    return engs


def make_fleet(engines, clk, *, wrap=None, max_queue=16, max_retries=2,
               probe_interval_s=1.0, policy=None, buckets=None,
               **router_kw):
    """3 replicas on a shared ManualClock; `wrap[i]` optionally
    wraps replica i's engine (fault proxies)."""
    servers = []
    for i, eng in enumerate(engines):
        if wrap and wrap.get(i) is not None:
            eng = wrap[i](eng)
        servers.append(ServingServer(eng, max_queue=max_queue,
                                     clock=clk, buckets=buckets,
                                     max_retries=max_retries))
    return ServingRouter(servers, clock=clk,
                         probe_interval_s=probe_interval_s,
                         policy=policy, **router_kw)


def routed_to(router, rr_id):
    """Which replica currently holds rr_id (pre-run introspection)."""
    for rep in router.replicas:
        if rr_id in rep.pending.values():
            return rep.rid
    return None


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def family_prompts(n, seed, prefix_len=8, tail_len=3, n_families=3,
                   prefix_seed=None):
    """Mixed traffic: `n` prompts cycling over `n_families` distinct
    8-token system prefixes (two full page_size=4 blocks each — the
    affinity chain is non-trivial) plus a unique tail. Pass the same
    `prefix_seed` across waves to keep the FAMILIES stable while the
    tails vary (the recovery-measurement scenario)."""
    pr = np.random.RandomState(seed if prefix_seed is None
                               else prefix_seed)
    r = np.random.RandomState(seed)
    prefixes = [pr.randint(0, 61, (prefix_len,)).astype(np.int32)
                for _ in range(n_families)]
    out = []
    for i in range(n):
        tail = r.randint(0, 61, (tail_len,)).astype(np.int32)
        out.append(np.concatenate([prefixes[i % n_families], tail]))
    return out


class TestRouting:
    def test_affinity_groups_prefix_families(self, params, engines):
        """Each shared-prefix family converges onto ONE replica (its
        chain keys point there after the first routing), so the
        fleet-wide hit rate approaches the single-box rate instead of
        scattering hot prefixes across N cold caches."""
        clk = ManualClock()
        router = make_fleet(engines, clk)
        ps = family_prompts(6, seed=1)
        ids = [router.submit(p, max_new=4) for p in ps]
        res = router.run()
        router.reconcile()
        by_family = {}
        for i, (rid, p) in enumerate(zip(ids, ps)):
            assert res[rid].outcome == "completed"
            assert res[rid].tokens == ref_tokens(params, p, 4)
            by_family.setdefault(i % 3, set()).add(res[rid].replica)
        # one replica per family — affinity, not scatter
        for fam, reps in by_family.items():
            assert len(reps) == 1, (fam, reps)
        c = router.counters()
        # 6 requests, 3 cold first-routings: the rest were affinity
        assert c["affinity_hits"] >= 3
        # and the replica-local caches agree the prefixes were hot
        assert c["fleet_prefix_hits"] >= 3

    def test_affinity_key_matches_pool_derivation(self, engines):
        """The router's routing key IS the pool's cache key: both
        call paged.chain_keys, so 'hot on replica k' is decided by
        exactly the hash replica k's own cache would hit."""
        clk = ManualClock()
        router = make_fleet(engines, clk)
        p = np.arange(11, dtype=np.int32)
        chain = router._chain(p)
        assert chain == chain_keys(p, 11, engines[0].page_size)
        assert chain[0] == ((), (0, 1, 2, 3))
        assert chain[1] == (chain[0], (4, 5, 6, 7))

    def test_spill_to_least_loaded_on_miss(self, engines):
        """Affinity-miss traffic levels across the fleet instead of
        piling onto one replica."""
        clk = ManualClock()
        router = make_fleet(engines, clk)
        r = np.random.RandomState(7)
        used = []
        # submit without running: loads grow as requests queue, so
        # unique-prefix prompts must fan out round-robin-by-load
        for _ in range(6):
            p = r.randint(0, 61, (9,)).astype(np.int32)
            rid = router.submit(p, max_new=2)
            used.append(routed_to(router, rid))
        assert set(used) == {0, 1, 2}, used
        router.run()
        router.reconcile()

    def test_affinity_target_full_spills_not_sheds(self, engines):
        """A FULL affinity target is a miss, not a shed: the burst
        spills to replicas with queue space (one prefill is the cost;
        a shed would lose the request while other replicas idle).
        Only a fleet-wide full queue sheds."""
        clk = ManualClock()
        router = make_fleet(engines, clk, max_queue=2)
        ps = family_prompts(5, seed=61, n_families=1)
        ids = [router.submit(p, max_new=2) for p in ps]
        # the single family overflows its replica's 2-deep queue and
        # fans out instead of shedding
        assert len({routed_to(router, rid) for rid in ids}) >= 2
        res = router.run()
        router.reconcile()
        assert all(res[i].outcome == "completed" for i in ids)
        assert router.stats["shed"] == 0

    def test_random_policy_scatters(self, engines):
        """The bench's control arm: RandomRoutingPolicy ignores the
        affinity map, so even a single shared-prefix family lands on
        several replicas (several cold caches pay the prefill the
        affinity map would have saved)."""
        clk = ManualClock()
        router = make_fleet(engines, clk,
                            policy=RandomRoutingPolicy(seed=3))
        ps = family_prompts(9, seed=1, n_families=1)
        for p in ps:
            router.submit(p, max_new=2)
        res = router.run()
        router.reconcile()
        reps = {r.replica for r in res.values()}
        assert len(reps) >= 2, reps


class TestChaosKill:
    @pytest.mark.slow  # tier-1 budget guard: >10s-class test, slow lane
    @pytest.mark.locks  # chaos lane re-run under LockOrderGuard
    def test_kill_midburst_exactly_once_and_hit_rate_recovers(
            self, params, engines, lock_order_guard):
        """THE acceptance chaos run (ISSUE 6): >= 3 replicas under a
        mixed burst (3 prefix families + garbage traffic), one
        replica killed at a decode step MID-burst (slots occupied,
        queue non-empty). Asserts, fleet-wide: every submitted
        request ends in EXACTLY ONE outcome (never zero, never two),
        counters reconcile across the fleet, completed requests are
        bit-exact vs generate(), and after redistribution warms the
        survivors the aggregate prefix-hit rate recovers to within
        10% of the pre-kill rate."""
        clk = ManualClock()
        plan = FaultPlan()             # armed between waves, below
        router = make_fleet(
            engines, clk, buckets=(16,),
            wrap={0: lambda e: plan.wrap_replica_engine(e, clock=clk)})

        # -- warm wave: every family hot somewhere, measure the rate
        warm = family_prompts(6, seed=11, prefix_seed=99)
        warm_ids = [router.submit(p, max_new=4) for p in warm]
        wres = router.run()
        router.reconcile()
        assert all(wres[i].outcome == "completed" for i in warm_ids)
        pre_rate = router.prefix_hit_rate()
        assert pre_rate >= 0.5          # the cache is genuinely warm
        assert router.stats["replicas_lost"] == 0

        # -- the kill burst: arm the fault at the 5th decode step of
        # THIS burst on replica 0 — mid-burst by construction (its
        # two slots are decoding and its queue still holds work)
        plan.router_kill_decode_at = plan._router_decode_counter + 4
        burst = family_prompts(9, seed=12, prefix_seed=99)
        burst_ids = [router.submit(p, max_new=4) for p in burst]
        garbage_failed = 0
        for g in garbage_prompts(61, 16).values():
            try:
                router.submit(g, max_new=2)
            except ValueError:
                garbage_failed += 1
        assert garbage_failed == 6
        res = router.run()
        router.reconcile()              # THE fleet invariant
        assert plan.count("replicakill") == 1
        c = router.counters()
        assert c["replicas_lost"] == 1
        assert c["redistributed"] >= 1  # the dead replica held work
        # exactly-once: every submission has one terminal outcome
        assert len(res) == c["requests"] == len(warm) + len(burst) + 6
        assert (c["completed"] + c["expired"] + c["shed"] + c["failed"]
                == c["requests"])
        assert c["failed"] == 6         # garbage only — no request
        #                                 died with the device
        # completions are still the exact greedy decode — the kill is
        # invisible in the output stream (warm-wave parity is
        # test_affinity_groups' job; the kill-affected burst is THE
        # check here)
        for rid, p in zip(burst_ids, burst):
            assert res[rid].outcome == "completed", (rid, res[rid])
            assert res[rid].tokens == ref_tokens(params, p, 4)
        # redistributed requests finished on survivors
        moved = [rid for rid in burst_ids
                 if res[rid].redistributions > 0]
        assert moved and all(res[rid].replica != 0 for rid in moved)

        # -- recovery wave: the same families, now served by the
        # survivors' warmed caches — aggregate hit rate within 10%
        # of pre-kill
        rec = family_prompts(6, seed=13, prefix_seed=99)
        rec_ids = [router.submit(p, max_new=4) for p in rec]
        res = router.run()
        router.reconcile()
        for rid, p in zip(rec_ids, rec):
            assert res[rid].outcome == "completed"
        # spot-check parity on the recovery wave (full parity is the
        # burst's check above)
        for rid, p in list(zip(rec_ids, rec))[:2]:
            assert res[rid].tokens == ref_tokens(params, p, 4)
        post = router.counters()
        dh = post["fleet_prefix_hits"] - c["fleet_prefix_hits"]
        dm = post["fleet_prefix_misses"] - c["fleet_prefix_misses"]
        post_rate = dh / max(dh + dm, 1)
        assert post_rate >= pre_rate - 0.10, (pre_rate, post_rate)

    def test_kill_preserves_retry_budgets(self, engines):
        """Redistribution carries each harvested request's REMAINING
        retries_left to the survivor — budgets intact: not reset, and
        not billed for the replica's death. The whole fleet run —
        routing, kill, harvest, redistribution — executes under
        transfer_guard('disallow'): the router adds ZERO implicit
        host<->device transfers on top of the already-clean decode
        loop (docs/ANALYSIS.md)."""
        clk = ManualClock()
        plan = FaultPlan(router_kill_decode_at=0)
        router = make_fleet(
            engines, clk, max_retries=2,
            wrap={0: lambda e: plan.wrap_replica_engine(e, clock=clk)})
        ps = family_prompts(4, seed=21, n_families=1)
        ids = [router.submit(p, max_new=3) for p in ps]
        with jax.transfer_guard("disallow"):
            res = router.run()
        router.reconcile()
        assert plan.count("replicakill") == 1
        assert router.stats["redistributed"] >= 1
        for rid in ids:
            assert res[rid].outcome == "completed"
            # retries counts transient requeues: the death handoff
            # consumed none of the budget (retries_left rode over)
            assert res[rid].retries == 0
            assert res[rid].redistributions in (0, 1)

    def test_all_replicas_dead_fails_closed(self, engines):
        """With no survivor, pending requests end FAILED — an
        explicit outcome, not a hang and not silence — and later
        submits shed with 'no routable replica'."""
        clk = ManualClock()
        plans = [FaultPlan(router_kill_decode_at=0) for _ in range(3)]
        router = make_fleet(
            engines, clk,
            wrap={i: (lambda e, p=plans[i]:
                      p.wrap_replica_engine(e, clock=clk))
                  for i in range(3)})
        ps = family_prompts(3, seed=22)
        ids = [router.submit(p, max_new=3) for p in ps]
        res = router.run()
        router.reconcile()
        # kill-at-decode-0 everywhere: nothing ever completes a step
        assert all(res[i].outcome == "failed" for i in ids)
        assert all("replica" in res[i].error for i in ids)
        assert router.counters()["replicas_alive"] == 0
        with pytest.raises(QueueFullError, match="no routable"):
            router.submit(ps[0], max_new=2)
        router.reconcile()


class TestHealth:
    def test_probe_blackhole_opens_breaker_and_recovers(self, engines):
        """Blackholed health probes (the replica is FINE — only its
        probes fail) open the breaker after failure_threshold
        consecutive misses: routing avoids the replica, with NO false
        kill and NO redistribution. Once probes flow again, the
        half-open probe closes the breaker and traffic returns."""
        clk = ManualClock()
        plan = FaultPlan(router_probe_drop_first_n=2)
        router = make_fleet(engines, clk, probe_interval_s=1.0,
                            failure_threshold=2, cooldown_s=5.0)
        plan.wrap_probe(router.replicas[0])
        router.probe_all()              # miss #1
        clk.advance(1.5)
        router.probe_all()              # miss #2 -> open
        assert plan.count("probedrop") == 2
        assert router.replicas[0].breaker.state == "open"
        assert not router.replicas[0].routable()
        # traffic flows around the quarantined replica
        ps = family_prompts(4, seed=31)
        ids = [router.submit(p, max_new=3) for p in ps]
        res = router.run()
        router.reconcile()
        assert all(res[i].outcome == "completed" for i in ids)
        assert all(res[i].replica != 0 for i in ids)
        assert router.stats["replicas_lost"] == 0   # no false kill
        assert router.stats["redistributed"] == 0
        # past cooldown the probes are clean: half-open -> closed
        clk.advance(6.0)
        router.probe_all()
        assert router.replicas[0].breaker.state == "closed"
        assert router.replicas[0].routable()

    def test_failing_half_open_probe_reopens_breaker(self, engines):
        """The breaker contract through the PROBE path: after the
        cooldown, ONE half-open probe decides — a still-blackholed
        probe RE-OPENS the breaker for another full cooldown (it must
        not sit half-open being re-probed every interval)."""
        clk = ManualClock()
        plan = FaultPlan(router_probe_drop_first_n=3)
        router = make_fleet(engines, clk, probe_interval_s=1.0,
                            failure_threshold=2, cooldown_s=5.0)
        rep = router.replicas[0]
        plan.wrap_probe(rep)
        router.probe_all()              # miss #1
        clk.advance(1.5)
        router.probe_all()              # miss #2 -> OPEN
        assert rep.breaker.state == "open" and rep.breaker.trips == 1
        clk.advance(6.0)                # past cooldown: half-open
        router.probe_all()              # miss #3: the deciding probe
        assert plan.count("probedrop") == 3
        assert rep.breaker.state == "open"      # re-opened, not stuck
        clk.advance(1.5)
        router.probe_all()              # still cooling: NOT probed
        assert plan._router_probe_counter == 3
        clk.advance(6.0)                # next half-open: clean probe
        router.probe_all()
        assert rep.breaker.state == "closed" and rep.routable()

    def test_probe_detects_dead_replica_with_queued_work(self,
                                                        engines):
        """A replica that dies holding only QUEUED work (no decode
        ever reaches it to raise) is caught by the health sweep's
        ping — its queue redistributes and every request completes."""
        clk = ManualClock()
        plan = FaultPlan()
        box = {}

        def wrap1(e):
            box["w"] = plan.wrap_replica_engine(e, clock=clk)
            return box["w"]

        router = make_fleet(engines, clk, wrap={1: wrap1})
        ps = family_prompts(6, seed=32)
        ids = [router.submit(p, max_new=3) for p in ps]
        victims = [rid for rid in ids if routed_to(router, rid) == 1]
        assert victims                  # the dead replica held work
        box["w"].dead = True            # device falls off the bus
        res = router.run()              # first sweep probes (due)
        router.reconcile()
        assert router.stats["replicas_lost"] == 1
        assert router.stats["redistributed"] >= len(victims)
        assert all(res[i].outcome == "completed" for i in ids)
        assert all(res[i].replica != 1 for i in ids)

    def test_slow_replica_skew_is_contained(self, params, engines):
        """A persistently slow replica (every decode burns 40ms of
        the shared clock) expires its own deadline-bound long
        requests; the round-robin drive keeps the other replicas
        stepping at full rate, so their requests complete exactly —
        one straggler cannot stall the fleet."""
        clk = ManualClock()
        plan = FaultPlan(router_slow_decode_s=0.04)
        router = make_fleet(
            engines, clk,
            wrap={0: lambda e: plan.wrap_replica_engine(e, clock=clk)})
        slow_ps = family_prompts(2, seed=41, n_families=1)
        fast_ps = family_prompts(2, seed=42, n_families=1)
        # first submit spills to replica 0 (empty fleet, stable
        # order); the second family spills to the next-least-loaded
        slow_ids = [router.submit(p, max_new=20, deadline_ms=100)
                    for p in slow_ps]
        fast_ids = [router.submit(p, max_new=6, deadline_ms=2000)
                    for p in fast_ps]
        assert routed_to(router, slow_ids[0]) == 0
        assert routed_to(router, fast_ids[0]) != 0
        res = router.run()
        router.reconcile()
        for i in slow_ids:
            assert res[i].outcome == "expired"
            assert 0 < len(res[i].tokens) < 20    # died mid-decode
        for i, p in zip(fast_ids, fast_ps):
            assert res[i].outcome == "completed"
            assert res[i].tokens == ref_tokens(params, p, 6)


class TestRetire:
    def test_retire_redistributes_queue_zero_recompute(
            self, params, engines):
        """Planned maintenance: retire_replica stops new routing and
        hands the replica's QUEUE to survivors immediately (those
        requests never started — the handoff is free). Every request
        completes; the retiree serves nothing new."""
        clk = ManualClock()
        router = make_fleet(engines, clk)
        ps = family_prompts(8, seed=51, n_families=2)
        ids = [router.submit(p, max_new=4) for p in ps]
        target = next(rep for rep in router.replicas
                      if rep.server.queue)
        router.retire_replica(target.rid, reason="maintenance")
        res = router.run()
        router.reconcile()
        for rid, p in zip(ids, ps):
            assert res[rid].outcome == "completed"
            assert res[rid].tokens == ref_tokens(params, p, 4)
        assert not target.routable()
        # nothing was in flight pre-retire, so the retiree served 0
        assert all(res[rid].replica != target.rid for rid in ids)
        # a fully-retired fleet fails closed, like a fully-dead one
        for rep in router.replicas:
            router.retire_replica(rep.rid)
        with pytest.raises(QueueFullError, match="no routable"):
            router.submit(ps[0], max_new=2)
        router.reconcile()


class TestCliFleet:
    @pytest.mark.slow
    def test_cli_serve_replicas(self, params, tmp_path):
        """`serve --replicas 2` routes through ServingRouter: ordered
        per-request output lines plus the fleet outcomes trailer.
        (2 replicas — the CLI test covers plumbing, not chaos; the
        >=3-replica chaos criterion lives in TestChaosKill.)"""
        from paddle_tpu.cli import main

        cfg_src = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n\n\n"
            "def get_serve_config():\n"
            "    from paddle_tpu.models import transformer as T\n"
            "    cfg = T.TransformerConfig(vocab=61, dim=32,"
            " n_layers=2, n_heads=4, attn_impl='dense')\n"
            "    return {'cfg': cfg,"
            " 'params': T.init_params(jax.random.key(0), cfg),"
            " 'slots': 2, 'max_len': 24}\n")
        cfg_file = tmp_path / "serve_cfg.py"
        cfg_file.write_text(cfg_src)
        prompts = tmp_path / "prompts.txt"
        prompts.write_text("1 2 3 4 5\n7 8 9\n1 2 3 4 5\n")
        out = tmp_path / "out.txt"
        assert main(["serve", "--config", str(cfg_file),
                     "--prompts", str(prompts), "--max-new", "4",
                     "--replicas", "2", "--max-queue", "8",
                     "--output", str(out)]) == 0
        lines = out.read_text().strip().splitlines()
        assert len(lines) == 4                # 3 requests + trailer
        for line, p in zip(lines, ([1, 2, 3, 4, 5], [7, 8, 9],
                                   [1, 2, 3, 4, 5])):
            got = [int(t) for t in line.split()]
            assert got == ref_tokens(params,
                                     np.asarray(p, np.int32), 4)
        assert lines[-1].startswith("# outcomes ")
        assert "completed=3" in lines[-1]
        assert "replicas_alive=2" in lines[-1]
