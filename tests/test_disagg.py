"""Disaggregated prefill/decode fleet: tiered routing + live KV-block
migration chaos harness.

The tentpole claim (ROADMAP item 2, docs/SERVING.md "Disaggregated
prefill/decode"): a role="prefill" replica parks every finished
prefill, its paged KV blocks migrate to a role="decode" replica keyed
by the SAME `chain_keys` derivation the prefix caches hash with, and
the destination's first decode step emits exactly the token the source
would have — bit-exact greedy parity through the handoff. Proven here
the way every reliability layer in this repo is proven (deterministic
`testing.faults` injection, `ManualClock`, no sleeps):

- greedy AND speculative parity vs solo `generate()` through a full
  cross-tier migration;
- migrated blocks SEED the destination's prefix cache: a repeat
  prefix routes straight to the decode tier and hits, no re-prefill,
  no second migration;
- a destination killed MID-TRANSFER (`router_kill_import_at`) costs
  nothing: the source's export pins keep its copy whole, the same
  payload retries the next destination (or cancels to source-local
  decode when none is left), every request still ends in exactly one
  outcome and the fleet counters reconcile;
- the migration path adds ZERO steady-state compiles after its first
  warm-up (RecompileGuard) — static [max_pages_per_slot] padding keeps
  every transfer on one set of compiled bodies.
"""

import numpy as np
import pytest

import jax

from paddle_tpu.analysis import RecompileGuard
from paddle_tpu.models import transformer as T
from paddle_tpu.serve.engine import DecodeEngine
from paddle_tpu.serve.router import ServingRouter
from paddle_tpu.serve.server import (MigrationRefusedError, ServingServer)
from paddle_tpu.testing.faults import FaultPlan, ManualClock

pytestmark = [pytest.mark.disagg]

CFG = T.TransformerConfig(vocab=61, dim=32, n_layers=2, n_heads=4,
                          attn_impl="dense")
BUCKETS = (16,)


@pytest.fixture(scope="module")
def params():
    return T.init_params(jax.random.key(0), CFG)


# ONE module-scoped engine set: replica 0 serves as the prefill tier,
# 1..2 as the decode tier (fleets differ only in servers/wrappers).
# Engines are stateless between runs (init_state resets the pool) and
# their jitted compiles — including the four migration bodies, which
# compile lazily at the module's FIRST migration — dominate test cost.
@pytest.fixture(scope="module")
def engines(params):
    engs = [DecodeEngine(params, CFG, slots=2, max_len=32, page_size=4,
                         prefill_chunk=8)
            for _ in range(3)]
    warm = np.arange(11, dtype=np.int32)
    for e in engs:
        e.serve([warm], max_new=2, buckets=BUCKETS)
    return engs


def make_fleet(engines, clk, *, roles=("prefill", "decode", "decode"),
               wrap=None, speculative=False, max_queue=16,
               max_retries=2, **router_kw):
    """Disaggregated fleet on a shared ManualClock. `wrap[i]`
    optionally wraps replica i's engine (fault proxies); decode-tier
    replicas optionally serve speculatively (the prefill tier never
    decodes, so speculation there is meaningless)."""
    servers = []
    for i, (eng, role) in enumerate(zip(engines, roles)):
        if wrap and wrap.get(i) is not None:
            eng = wrap[i](eng)
        servers.append(ServingServer(
            eng, role=role, max_queue=max_queue, clock=clk,
            buckets=BUCKETS, max_retries=max_retries,
            speculative=(speculative and role == "decode")))
    return ServingRouter(servers, clock=clk, probe_interval_s=1e9,
                         **router_kw)


def ref_tokens(params, prompt, max_new):
    out = T.generate(params, CFG, jax.numpy.asarray(prompt)[None, :],
                     steps=max_new)
    return [int(t) for t in np.asarray(out[0, len(prompt):])]


def prompts_for(n, seed, lo=9, hi=14):
    r = np.random.RandomState(seed)
    return [r.randint(1, 60, (int(r.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


class TestHandoffSeam:
    """The ServingServer-level handoff API, driven directly."""

    def test_prefill_role_parks_and_pins(self, params, engines):
        srv = ServingServer(engines[0], role="prefill",
                            buckets=BUCKETS, clock=lambda: 0.0)
        prompt = np.arange(1, 12, dtype=np.int32)
        rid = srv.submit(prompt, max_new=4)
        srv.run()           # returns with the request PARKED, not done
        assert srv.ready_handoffs() == [rid]
        pool = srv._active_pool
        assert pool.exports_outstanding == 1
        pool.reconcile()    # export pins are counted holders
        payload = srv.export_request(rid)
        assert payload["n_pages"] == len(payload["kv"][0][0])
        assert payload["geometry"] == engines[0].kv_geometry()
        assert payload["seed"].pos == prompt.size
        # destination ACK: source copy released, ledger backed out
        srv.handoff_complete(rid)
        assert pool.exports_outstanding == 0
        assert srv.stats.requests == 0 and not srv.results
        assert srv.counters()["migrated_out"] == 1
        srv.reconcile()

    def test_cancel_handoff_decodes_locally(self, params, engines):
        srv = ServingServer(engines[0], role="prefill",
                            buckets=BUCKETS, clock=lambda: 0.0)
        prompt = np.arange(2, 13, dtype=np.int32)
        rid = srv.submit(prompt, max_new=4)
        srv.run()
        assert srv.ready_handoffs() == [rid]
        srv.cancel_handoff(rid)         # graceful degrade
        res = srv.run()
        assert res[rid].outcome == "completed"
        assert res[rid].tokens == ref_tokens(params, prompt, 4)
        assert srv._active_pool.exports_outstanding == 0
        assert srv.counters()["handoffs_cancelled"] == 1
        srv.reconcile()

    def test_deadline_expires_while_parked(self, params, engines):
        clk = ManualClock()
        srv = ServingServer(engines[0], role="prefill",
                            buckets=BUCKETS, clock=clk)
        rid = srv.submit(np.arange(1, 10, dtype=np.int32), max_new=4,
                         deadline_ms=500)
        srv.run()
        assert srv.ready_handoffs() == [rid]
        clk.advance(1.0)
        srv.step()          # expiry retires the slot AND drops the pin
        assert srv.results[rid].outcome == "expired"
        assert srv.ready_handoffs() == []
        assert srv._active_pool.exports_outstanding == 0
        srv.reconcile()

    def test_import_gates(self, params, engines):
        src = ServingServer(engines[0], role="prefill",
                            buckets=BUCKETS, clock=lambda: 0.0)
        rid = src.submit(np.arange(3, 14, dtype=np.int32), max_new=4)
        src.run()
        payload = src.export_request(rid)
        dst = ServingServer(engines[1], role="decode",
                            buckets=BUCKETS, clock=lambda: 0.0)
        # a draining destination refuses TRANSIENTLY
        dst.drain(reason="test")
        with pytest.raises(MigrationRefusedError):
            dst.import_request(payload)
        # a geometry mismatch is deterministic mis-wiring
        bad = dict(payload)
        bad["geometry"] = dict(payload["geometry"], page_size=999)
        dst2 = ServingServer(engines[2], role="decode",
                             buckets=BUCKETS, clock=lambda: 0.0)
        with pytest.raises(ValueError):
            dst2.import_request(bad)
        # nothing changed anywhere: source copy intact, books balance
        assert src._active_pool.exports_outstanding == 1
        src.cancel_handoff(rid)
        src.run()
        src.reconcile()

    def test_role_validation(self, params, engines):
        with pytest.raises(ValueError):
            ServingServer(engines[0], role="verifier")
        with pytest.raises(ValueError):
            # a prefill tier needs a decode tier to migrate to
            ServingRouter([
                ServingServer(engines[0], role="prefill",
                              buckets=BUCKETS)])


class TestDisaggFleet:
    def test_greedy_parity_through_migration(self, params, engines):
        clk = ManualClock()
        router = make_fleet(engines, clk)
        prompts = prompts_for(3, seed=7)
        ids = [router.submit(p, max_new=5) for p in prompts]
        res = router.run()
        for p, rr in zip(prompts, ids):
            assert res[rr].outcome == "completed"
            assert res[rr].tokens == ref_tokens(params, p, 5)
            # the outcome landed on the DECODE tier
            assert res[rr].replica in (1, 2), res[rr]
        c = router.counters()
        assert c["migrations"] == 3
        assert c["fleet_migrated_out"] == 3
        assert c["fleet_migrated_in"] == 3
        assert c["fleet_migrated_out_pages"] >= 3
        assert (c["fleet_migrated_out_pages"]
                >= c["fleet_migrated_in_pages"])
        assert c["fleet_requests"] == 3     # each request counted ONCE
        router.reconcile()

    def test_migrated_blocks_seed_decode_prefix_cache(
            self, params, engines):
        clk = ManualClock()
        router = make_fleet(engines, clk)
        prefix = np.asarray(
            [5, 9, 2, 44, 17, 3, 28, 51], np.int32)   # two full blocks
        p1 = np.concatenate([prefix, np.asarray([7, 11, 30], np.int32)])
        p2 = np.concatenate([prefix, np.asarray([19, 4, 55], np.int32)])
        r1 = router.submit(p1, max_new=5)
        router.run()
        c1 = router.counters()
        assert c1["migrations"] == 1
        # the repeat prefix routes by affinity STRAIGHT to the decode
        # replica whose cache the migration seeded — served end-to-end
        # there with a prefix hit, no second migration
        r2 = router.submit(p2, max_new=5)
        res = router.run()
        c2 = router.counters()
        assert res[r2].outcome == "completed"
        assert res[r2].tokens == ref_tokens(params, p2, 5)
        assert res[r2].replica == res[r1].replica
        assert c2["migrations"] == 1                   # no new transfer
        assert c2["fleet_prefix_hits"] > c1["fleet_prefix_hits"]
        assert c2["affinity_hits"] >= 1
        router.reconcile()

    @pytest.mark.slow  # tier-1 budget guard: the disagg lane runs it
    def test_speculative_parity_through_migration(self, params,
                                                  engines):
        clk = ManualClock()
        router = make_fleet(engines, clk, speculative=True)
        prompts = prompts_for(2, seed=11)
        ids = [router.submit(p, max_new=6) for p in prompts]
        res = router.run()
        for p, rr in zip(prompts, ids):
            assert res[rr].outcome == "completed"
            assert res[rr].tokens == ref_tokens(params, p, 6)
        c = router.counters()
        assert c["migrations"] == 2
        assert c["fleet_spec_rounds"] > 0   # decode tier speculated
        router.reconcile()

    def test_migration_zero_steady_state_compiles(self, params,
                                                  engines):
        """One warm migration compiles the pause/kvread/kvwrite/resume
        bodies; every later transfer — different prompt, different
        block count — reuses them (static page-vector padding)."""
        clk = ManualClock()
        router = make_fleet(engines, clk)
        router.submit(np.arange(1, 12, dtype=np.int32), max_new=4)
        router.run()                        # warm-up migration
        with RecompileGuard(name="steady-state migration") as g:
            rr = router.submit(np.arange(4, 17, dtype=np.int32),
                               max_new=4)
            res = router.run()
        assert g.compiles == 0
        assert res[rr].outcome == "completed"
        assert router.counters()["migrations"] == 2
        router.reconcile()


class TestMigrationChaos:
    pytestmark = [pytest.mark.faults]

    def test_destination_death_mid_transfer_retries(self, params,
                                                    engines):
        """The first migration's destination dies MID-IMPORT: the
        source export pins keep its copy whole, the SAME payload lands
        on the surviving decode replica, parity holds, exactly-once
        holds, and the fleet counters reconcile."""
        clk = ManualClock()
        plan = FaultPlan(router_kill_import_at=0)
        router = make_fleet(
            engines, clk,
            wrap={1: lambda e: plan.wrap_replica_engine(e, clock=clk)})
        prompt = np.arange(2, 14, dtype=np.int32)
        rr = router.submit(prompt, max_new=6)
        res = router.run()
        assert plan.count("importkill") == 1
        assert res[rr].outcome == "completed"
        assert res[rr].tokens == ref_tokens(params, prompt, 6)
        assert res[rr].replica == 2         # the surviving destination
        c = router.counters()
        assert c["replicas_lost"] == 1
        assert c["migration_retargets"] == 1
        assert c["migrations"] == 1
        assert c["migration_failed"] == 0
        # the source released its copy only after the final ACK
        src = router.replicas[0].server
        assert src._active_pool.exports_outstanding == 0
        assert src.counters()["migrated_out"] == 1
        router.reconcile()

    def test_destination_death_with_no_survivor_cancels(self, params,
                                                        engines):
        """Only ONE decode replica, and it dies mid-import: the
        handoff cancels back to the source, which decodes the request
        locally from its still-pinned blocks — graceful degrade,
        never a lost request."""
        clk = ManualClock()
        plan = FaultPlan(router_kill_import_at=0)
        router = make_fleet(
            engines[:2], clk, roles=("prefill", "decode"),
            wrap={1: lambda e: plan.wrap_replica_engine(e, clock=clk)})
        prompt = np.arange(1, 13, dtype=np.int32)
        rr = router.submit(prompt, max_new=6)
        res = router.run()
        assert plan.count("importkill") == 1
        assert res[rr].outcome == "completed"
        assert res[rr].tokens == ref_tokens(params, prompt, 6)
        assert res[rr].replica == 0         # decoded at the source
        c = router.counters()
        assert c["replicas_lost"] == 1
        assert c["migration_failed"] == 1
        assert c["migrations"] == 0
        src = router.replicas[0].server
        assert src._active_pool.exports_outstanding == 0
        assert src.counters()["handoffs_cancelled"] == 1
        router.reconcile()

    def test_source_death_while_parked_resubmits_exactly_once(
            self, params, engines):
        """Both copies lost: the PREFILL replica dies while requests
        are parked (its pinned blocks die with it, and no destination
        ever imported). The PR6 harvest path resubmits each request
        to a survivor — decode replicas serve end-to-end as the
        degrade tier — with exactly one outcome each."""
        clk = ManualClock()
        plan = FaultPlan()
        router = make_fleet(engines, clk)
        src = router.replicas[0]
        prompt = np.arange(3, 15, dtype=np.int32)
        rr = router.submit(prompt, max_new=6)
        # park it (one sweep of the source alone), then kill the
        # source BEFORE the router's migration harvest runs
        src.server.step()
        while src.server._prefilling:
            src.server.step()
        assert src.server.ready_handoffs()
        src.server.engine = plan.wrap_replica_engine(src.server.engine,
                                                     clock=clk)
        src.server.engine.dead = True
        src.server._backend = src.server.engine
        res = router.run()
        assert res[rr].outcome == "completed"
        assert res[rr].tokens == ref_tokens(params, prompt, 6)
        assert res[rr].replica in (1, 2)
        c = router.counters()
        assert c["replicas_lost"] == 1
        assert c["redistributed"] == 1
        assert c["migrations"] == 0
        # redistribution is a RESUBMIT (per-replica submission counted
        # on source and survivor both — the PR6 semantic); contrast
        # the migrated path, where the destination ACK backs the
        # request out of the source ledger and fleet_requests stays 1
        assert c["fleet_requests"] == 2
        router.reconcile()
